"""Headline benchmark: GLM grad-steps/sec (BASELINE.json primary metric).

Times the innermost distributed operation of the framework — one full
value-and-gradient evaluation of a logistic-GLM objective over a sparse
batch (the rebuild of the reference's ``DistributedGLMLossFunction.calculate``
treeAggregate hot path, SURVEY.md §3.4) — as a jit-compiled XLA program on
whatever backend JAX exposes (one real TPU chip under the driver; CPU
elsewhere).

Prints ONE JSON line:
    {"metric": "glm_grad_steps_per_sec", "value": N, "unit": "steps/s",
     "vs_baseline": N}

``vs_baseline`` is vs. the reference's published numbers — of which there are
none (``BASELINE.json.published == {}``), so it reports the ratio against a
recorded prior run in ``BENCH_BASELINE.json`` when present and 1.0 otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Resolved once by _acquire_backend(); recorded into every emitted line so a
# CPU-fallback run is visibly not a TPU number.
_PLATFORM_INFO = {"platform": None, "tpu_error": None}

# Error signatures of a jaxlib whose CPU backend cannot run cross-process
# collectives at all — a platform limitation, not a failure.  The ONE copy:
# _run_stream_workers re-raises on these so the signature survives the
# bench_error detail truncation, and tests/test_multiprocess.py +
# tests/test_streaming.py import this tuple to skip-with-reason.
MP_UNSUPPORTED_MARKERS = (
    "Multiprocess computations aren't implemented",
    "multiprocess computations are not supported",
)


def _acquire_backend(timeout_s: float | None = None) -> None:
    """Resolve a usable JAX backend WITHOUT ever hanging or crashing the bench.

    Round 2 shipped zero perf data because ``jax.devices()`` hung when the
    tunneled TPU backend was down and the driver recorded ``rc=1,
    parsed=null``.  Backend initialization hangs cannot be interrupted
    in-process, so the probe runs ``jax.devices()`` in a SUBPROCESS with a
    bounded timeout; on any failure the parent forces the CPU backend via
    ``jax.config.update`` (the env var is overridden by site customization)
    and records the TPU error for the emitted JSON.
    """
    if _PLATFORM_INFO["platform"] is not None:
        return
    if timeout_s is None:
        # The tunneled backend has been observed to take >120s to come up
        # when healthy-but-slow; 240s balances that against the wait a
        # genuinely-down tunnel costs (paid once per hour via the cache).
        timeout_s = float(os.environ.get("PHOTON_BENCH_PROBE_TIMEOUT", "240"))
    # Cache the CPU-FALLBACK outcome (15-minute TTL) so back-to-back bench
    # invocations against a dead tunnel re-pay the probe timeout at most
    # once per TTL window.  A successful TPU probe is deliberately NOT cached:
    # the tunnel can drop mid-round, and a cached "tpu" would skip the
    # subprocess guard and reintroduce the unbounded in-process hang.
    cache_path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "photon_bench_backend_probe.json"
    )
    try:
        st = os.stat(cache_path)
        # 15-minute TTL: bounds a dead tunnel's probe-timeout cost to one
        # wait per window, while a recovered tunnel is noticed within 15
        # minutes (an hour-long TTL once masked a live chip all round).
        if time.time() - st.st_mtime < 900:
            with open(cache_path) as f:
                cached = json.load(f)
            if cached.get("platform") == "cpu-fallback":
                _PLATFORM_INFO.update(cached)
                import jax

                jax.config.update("jax_platforms", "cpu")
                return
    except Exception:  # noqa: BLE001 — unreadable cache means re-probe
        pass
    err = None
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if proc.returncode == 0 and proc.stdout.strip():
            # Trust the probe: the parent must not run its own unbounded
            # jax.devices() here — that is the exact hang this guards against.
            _PLATFORM_INFO["platform"] = proc.stdout.strip().splitlines()[-1]
        else:
            err = (proc.stderr or "backend probe failed").strip()[-500:]
    except subprocess.TimeoutExpired:
        err = f"backend init timed out after {timeout_s:.0f}s"
    except Exception as ex:  # noqa: BLE001 — any probe failure must degrade
        err = f"{type(ex).__name__}: {ex}"
    if err is not None:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — backend may already be initialized
            pass
        _PLATFORM_INFO["platform"] = "cpu-fallback"
        _PLATFORM_INFO["tpu_error"] = err
    if _PLATFORM_INFO["platform"] == "cpu-fallback":
        try:
            with open(cache_path + ".tmp", "w") as f:
                json.dump(_PLATFORM_INFO, f)
            os.replace(cache_path + ".tmp", cache_path)
        except Exception:  # noqa: BLE001 — cache write failure is non-fatal
            pass


def _build_batch(n: int, k: int, d: int, seed: int = 0):
    """Synthetic sparse logistic data in the framework's padded-COO layout.

    ``PHOTON_BENCH_SKEW=zipf`` draws power-law feature ids (the realistic
    sparse-GLM regime and the adversarial case for aligned-layout padding);
    default is uniform ids.
    """
    import jax.numpy as jnp

    from photon_tpu.data.batch import SparseBatch

    rng = np.random.default_rng(seed)
    if os.environ.get("PHOTON_BENCH_SKEW", "uniform") == "zipf":
        ids = (1 + (rng.zipf(1.3, size=(n, k)) - 1) % (d - 1)).astype(np.int32)
    else:
        ids = rng.integers(1, d, size=(n, k), dtype=np.int32)  # id 0 = pad/intercept
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32) * 0.1
    margin = (w_true[ids] * vals).sum(axis=1)
    label = (rng.random(n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    from photon_tpu.data.batch import attach_feature_major
    from photon_tpu.ops.sparse_grad_select import aligned_layout_wanted

    return attach_feature_major(SparseBatch(
        ids=jnp.asarray(ids),
        vals=jnp.asarray(vals),
        label=jnp.asarray(label),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
    ), aligned_dim=d if aligned_layout_wanted(n * k) else None)


_BANKED_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "TPU_BANKED.json")
_HEADLINE_METRIC = "glm_grad_steps_per_sec"
# The round-over-round comparison shape (BASELINE.md row 1); seeds
# canonical_shape if the bank ever has to start from scratch.
_CANONICAL_SHAPE = {"rows": 1 << 20, "nnz_per_row": 32, "dim": 1 << 18}


def _is_tpu_platform(p) -> bool:
    """One predicate for BOTH the live and the baseline side: the
    tunneled chip reports platform \"axon\", recorded baselines say
    \"tpu-v5e-1chip\" — asymmetric checks here once meant a genuine
    like-for-like axon comparison got suppressed as cross-platform."""
    s = str(p or "")
    return "tpu" in s or s == "axon"


def _load_banked() -> dict | None:
    """The most recent banked TPU hardware table (TPU_BANKED.json), or
    None.  This is how a BENCH_r0N.json captured during a tunnel outage
    still carries the operative hardware truth (VERDICT r4 item 4)."""
    try:
        with open(_BANKED_PATH) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001 — absent/corrupt bank = no embed
        return None


def _bank_tpu_result(value: float, detail: dict) -> None:
    """Write-through bank: a headline run that completed on a LIVE TPU
    backend records itself into TPU_BANKED.json (atomic replace), so the
    next outage-window bench emission automatically embeds the newest
    hardware truth.  The headline slot tracks the best steps/s at the
    canonical shape in the production configuration (f32, uniform,
    per-step dispatch — the configuration the round-over-round number
    is defined on)."""
    bank = _load_banked()
    if bank is None:
        if os.path.exists(_BANKED_PATH):
            # An existing-but-unreadable bank is hand-curated data: never
            # clobber it from here — skip banking and say so.
            print(
                f"WARNING: {_BANKED_PATH} exists but is unreadable; "
                "skipping the TPU result bank update to preserve it",
                file=sys.stderr,
            )
            return
        bank = {"entries": {}, "canonical_shape": dict(_CANONICAL_SHAPE)}
    kernel = str(detail.get("kernel", "auto"))
    if kernel.startswith("auto:"):
        kernel = kernel.split(":", 1)[1]
    key = "|".join([
        kernel, str(detail.get("dtype")), str(detail.get("skew")),
        str(detail.get("dispatch")),
    ])
    if detail.get("xchg_reduce"):
        key += "|" + str(detail["xchg_reduce"])
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    entry = {
        "value": round(value, 3), "unit": "steps/s", "kernel": kernel,
        "rows": detail.get("rows"), "nnz_per_row": detail.get("nnz_per_row"),
        "dim": detail.get("dim"), "dtype": detail.get("dtype"),
        "skew": detail.get("skew"), "dispatch": detail.get("dispatch"),
        "measured_utc": stamp, "window": "banked live by bench.py",
    }
    if detail.get("xchg_reduce"):
        entry["xchg_reduce"] = detail["xchg_reduce"]
    bank.setdefault("entries", {})[key] = entry
    bank["updated"] = stamp
    shape = bank.get("canonical_shape") or dict(_CANONICAL_SHAPE)
    head = bank.get("headline") or {}
    at_canonical = (
        detail.get("rows") == shape.get("rows")
        and detail.get("nnz_per_row") == shape.get("nnz_per_row")
        and detail.get("dim") == shape.get("dim")
        and detail.get("dtype") == "float32"
        and detail.get("skew") == "uniform"
        and detail.get("dispatch") == "per-step"
    )
    if at_canonical and value > float(head.get("value") or 0.0):
        bank["headline"] = {
            "metric": _HEADLINE_METRIC, "platform": "tpu", **entry,
        }
    try:
        tmp = _BANKED_PATH + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(bank, f, indent=2)
        os.replace(tmp, _BANKED_PATH)
    except Exception:  # noqa: BLE001 — banking is best-effort
        pass


def _emit(metric: str, value: float, unit: str, detail: dict) -> None:
    live_platform = detail.get("platform") or _PLATFORM_INFO["platform"]
    on_tpu = _is_tpu_platform(live_platform)
    vs_baseline = 1.0
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as f:
                prior = json.load(f)
            if prior.get("metric") == metric and prior.get("value"):
                # Same-platform comparisons only (VERDICT r4 weak 1): a
                # CPU-fallback number against the TPU baseline is
                # apples-to-oranges however it is normalized, and the
                # headline field must never read as progress when the
                # hardware was unreachable.
                prior_tpu = _is_tpu_platform(prior.get("platform"))
                if prior_tpu != on_tpu:
                    vs_baseline = None
                    detail["vs_baseline_basis"] = (
                        f"null: live platform is {live_platform!r} but the "
                        f"baseline is {prior.get('platform')!r} — "
                        "cross-platform ratios are suppressed; see "
                        "detail.last_tpu for the operative hardware numbers"
                    )
                else:
                    vs_baseline = value / float(prior["value"])
                    # Shapes can still differ within a platform; compare on
                    # sparse-entry throughput (nnz/sec — rows alone would
                    # bias by the differing nnz_per_row) and say so.
                    here = (detail.get("rows"), detail.get("nnz_per_row"))
                    prior_shape = (prior.get("rows"), prior.get("nnz_per_row"))
                    if (
                        None not in here
                        and None not in prior_shape
                        and here != prior_shape
                        and detail.get("rows_per_sec")
                        and prior.get("rows_per_sec")
                    ):
                        vs_baseline = (
                            float(detail["rows_per_sec"]) * here[1]
                        ) / (float(prior["rows_per_sec"]) * prior_shape[1])
                        detail["vs_baseline_basis"] = (
                            f"nnz_per_sec (shapes differ: {here[0]}x{here[1]} "
                            f"here vs {prior_shape[0]}x{prior_shape[1]} in "
                            f"baseline)"
                        )
        except Exception:  # noqa: BLE001 — a corrupt baseline must not kill the bench
            pass
    if _PLATFORM_INFO["platform"] is not None:
        detail = dict(detail)
        if _PLATFORM_INFO["platform"] == "cpu-fallback":
            detail["platform"] = "cpu-fallback"
        else:
            detail.setdefault("platform", _PLATFORM_INFO["platform"])
        if _PLATFORM_INFO["tpu_error"]:
            detail["tpu_error"] = _PLATFORM_INFO["tpu_error"]
    if metric in (_HEADLINE_METRIC, "bench_error"):
        if on_tpu and metric == _HEADLINE_METRIC:
            _bank_tpu_result(value, detail)
        elif not on_tpu:
            banked = _load_banked()
            if banked is not None:
                # The record of the round must carry the hardware truth
                # even when the tunnel is down at capture time: embed the
                # banked TPU table (values + timestamps + provenance).
                detail["last_tpu"] = banked
    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": unit,
        "vs_baseline": None if vs_baseline is None else round(vs_baseline, 3),
        "detail": detail,
    }))


def _bench_config(num: int) -> None:
    """The five BASELINE.json bench configs (SURVEY.md §6), scaled to the
    local platform (full scale on accelerators, small on CPU sanity runs).
    Each run is a REAL driver invocation end-to-end (read -> fit -> eval).
    """
    import tempfile
    import jax

    import numpy as np

    from photon_tpu.data.synthetic import make_game_data, make_glm_data, write_libsvm

    if num not in (1, 2, 3, 4, 5):
        raise ValueError(f"unknown bench config {num}; valid: 1-5 (SURVEY.md §6)")

    platform = jax.devices()[0].platform
    big = platform != "cpu"
    tmp = tempfile.mkdtemp(prefix="photon_bench_")

    if num in (1, 2, 3):
        # (1) a1a-statistics logistic + L-BFGS (committed fixture, real AUC
        # anchor); (2) linear elastic-net OWL-QN; (3) Poisson TRON.  All
        # through the legacy-driver path.
        from photon_tpu.drivers import train

        task, opt, reg = {
            1: ("logistic_regression", "lbfgs", "l2"),
            2: ("linear_regression", "owlqn", "elastic_net"),
            3: ("poisson_regression", "tron", "l2"),
        }[num]
        extra = []
        if num == 1:
            from photon_tpu.data.fixtures import a1a_fixture_paths

            path, test_path = a1a_fixture_paths()
            n, d = 1605, 123
            extra = ["--validation-input", test_path]
        else:
            # Quality anchor for every config (VERDICT r3 weak 6): a 20%
            # held-out split from the same generated population gives each
            # perf row a validation metric (RMSE / Poisson NLL via the
            # task's default evaluators) so a broken optimizer can't hide
            # behind a fast wall-clock.
            n, d = (200_000, 1024) if big else (5000, 128)
            n_val = n // 5
            batch, _ = make_glm_data(n + n_val, d, task=task, seed=0)
            x, y = np.asarray(batch.x)[:, :-1], np.asarray(batch.label)
            path = os.path.join(tmp, "train.libsvm")
            val_path = os.path.join(tmp, "val.libsvm")
            write_libsvm(path, x[:n], y[:n])
            write_libsvm(val_path, x[n:], y[n:])
            extra = ["--validation-input", val_path]
        t0 = time.perf_counter()
        summary = train.run(train.build_parser().parse_args([
            "--input", path, "--task", task, "--optimizer", opt,
            "--reg-type", reg, "--reg-weights", "1.0",
            "--max-iterations", "100",
            "--output-dir", os.path.join(tmp, "out"),
        ] + extra))
        wall = time.perf_counter() - t0
        entry = summary["sweep"][0]
        _emit(f"config{num}_fit_seconds", wall, "s", {
            "task": task, "optimizer": opt, "rows": n, "dim": d,
            "iterations": entry["iterations"],
            "reason": entry["convergence_reason"],
            "rows_per_sec": round(n * entry["iterations"] / max(wall, 1e-9), 1),
            "metrics": entry.get("metrics"),
            "platform": platform,
        })
        return

    # (4) GAME fixed + user random effect on the MovieLens-shaped fixture
    #     (real Avro path, zipf item popularity, per-user skew);
    # (5) GAME fixed + user + item random effects (LinkedIn-scale, scaled
    #     to the chip: rows/sec is the comparable number).
    from photon_tpu.drivers import train_game

    if num == 4:
        from photon_tpu.data.fixtures import movielens_dataset
        from photon_tpu.data.game_io import write_game_avro

        # MovieLens-1M user/item counts; ratings-per-user scaled so the
        # host-side Avro fixture write stays bounded (~300K rows).  When
        # PHOTON_REAL_DATA_DIR/ml-1m exists, the REAL MovieLens-1M is used
        # instead (true literature-comparable metrics).
        ml_kw = dict(n_users=6040, n_items=3700, mean_ratings=50) if big \
            else {}
        data, ml_maps = movielens_dataset(**ml_kw)
        avro_path = os.path.join(tmp, "movielens.avro")
        write_game_avro(avro_path, data, ml_maps)
        coords = [
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=30",
            "--coordinate",
            "per_user:type=random,shard=per_user,entity=userId,max_iters=20",
        ]
        t0 = time.perf_counter()
        summary = train_game.run(train_game.build_parser().parse_args([
            "--input", avro_path,
            "--feature-bags", "global=global,per_user=per_user",
            "--id-columns", "userId,itemId",
            *coords,
            "--descent-iterations", "2",
            "--validation-split", "0.2",
            "--output-dir", os.path.join(tmp, "out"),
        ]))
        wall = time.perf_counter() - t0
        n_rows = data.num_examples
        _emit("config4_game_epoch_seconds", wall / 2.0, "s/epoch", {
            "fixture": "movielens-like",
            "metrics": summary["best_metrics"],
            "rows": n_rows,
            "users": len(set(np.asarray(data.id_columns["userId"]).tolist())),
            "rows_per_sec": round(2.0 * n_rows / wall, 1),
            "platform": platform,
        })
        return
    else:
        spec = "synthetic-game:20000:100:128:16:2:0" if big else \
            "synthetic-game:400:12:32:8:2:0"
        coords = [
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=20",
            "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=15",
            "--coordinate", "per_item:type=random,shard=re1,entity=re1,max_iters=15",
        ]
    t0 = time.perf_counter()
    summary = train_game.run(train_game.build_parser().parse_args([
        "--input", spec, *coords,
        "--descent-iterations", "2",
        "--validation-split", "0.2",
        "--output-dir", os.path.join(tmp, "out"),
    ]))
    wall = time.perf_counter() - t0
    n_rows = int(spec.split(":")[1]) * int(spec.split(":")[2])
    _emit(f"config{num}_game_epoch_seconds", wall / 2.0, "s/epoch", {
        "spec": spec,
        "metrics": summary["best_metrics"],
        "approx_rows": n_rows,
        "rows_per_sec": round(2.0 * n_rows / wall, 1),
        "platform": jax.devices()[0].platform,
    })


def _game_bench_fixture(n_random_coords: int, descent_iterations: int,
                        sizes=None):
    """Shared synthetic-fit fixture of the GAME micro-benches: one dataset
    + configuration sized so the path under test (residual passing /
    validation) is a visible slice of the wall clock — solver work is
    capped at a few inner iterations.  ~200k rows x coordinates on CPU:
    below that, solve noise swamps the deltas.  ONE builder so the descent
    and validation benches can never drift onto differently-shaped fits.
    """
    import jax

    from photon_tpu.core.objective import RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig
    from photon_tpu.data.synthetic import make_game_dataset
    from photon_tpu.game.coordinate import (
        FixedEffectCoordinateConfig,
        RandomEffectCoordinateConfig,
    )
    from photon_tpu.game.estimator import GameOptimizationConfiguration

    platform = jax.devices()[0].platform
    if sizes is None:
        big = platform != "cpu"
        n_entities, rows_mean = (20_000, 50) if big else (8000, 25)
    else:
        # Explicit sizes: the resharded-restore subprocess must rebuild
        # the PARENT's fixture (its own platform is forced CPU, so the
        # platform-derived sizes could differ from the checkpoint's).
        n_entities, rows_mean = sizes
    data, _ = make_game_dataset(
        n_entities, rows_mean, 32, 8, seed=0,
        n_random_coords=n_random_coords,
    )

    def problem(lam: float, max_iters: int) -> ProblemConfig:
        return ProblemConfig(
            regularization=RegularizationContext("l2", lam),
            optimizer_config=OptimizerConfig(max_iterations=max_iters),
        )

    coordinates = {"fixed": FixedEffectCoordinateConfig("global", problem(0.01, 5))}
    for i in range(n_random_coords):
        coordinates[f"re{i}"] = RandomEffectCoordinateConfig(
            f"re{i}", f"re{i}", problem(1.0, 4)
        )
    config = GameOptimizationConfiguration(
        coordinates=coordinates, descent_iterations=descent_iterations
    )
    # Sizes ride the return so subprocess rebuilds (the resharded-restore
    # worker) use the PARENT's fixture shape verbatim instead of
    # re-deriving it from their own (forced-CPU) platform.
    return platform, (n_entities, rows_mean), data, config


def _bench_ooc(spill: bool = False, tile_dtype: str | None = None) -> None:
    """Out-of-core GAME micro-bench (``--mode ooc [--spill]`` — ISSUE
    10/11).

    Runs the SAME synthetic GAME fit — resident (device residual engine),
    streamed under a FORCED small ``--max-resident-mb``-style chunk
    budget, and (``spill=True``, the default bench run) streamed again
    through the DISK-backed tile store under a ``--max-host-mb`` budget
    small enough to force LRU eviction.  Emits ``game_ooc_rows_per_sec``
    (streamed training rows/s vs resident) and, with spill,
    ``game_ooc_disk_rows_per_sec`` with per-tier stall fractions and the
    cache/store shape.  The spilled leg ASSERTS the ISSUE 11 acceptance
    bars in-bench: forced evictions observed, spilled-vs-host-resident
    tiles bit-identical (``np.array_equal`` against a recomputation from
    the host-resident fit's final models), metrics ≤1e-6, and the spilled
    rate ≥ 0.5× the host-resident streamed rate on CPU.  Each mode times
    its SECOND fit (the first pays compilation, all modes alike).
    """
    import tempfile

    from photon_tpu.game.estimator import GameEstimator
    from photon_tpu.game.tile_store import TileStore
    from photon_tpu.game.tiles import (
        PREFETCH_DEPTH,
        RESIDUAL_TILE_KIND as TILES,
        ChunkPlan,
        ChunkStreamer,
        per_row_bytes,
        score_model_chunks,
        stream_host_bytes_estimate,
    )
    from photon_tpu.telemetry import TelemetrySession

    iters = 2
    platform, (n_entities, _rows_mean), data, config = _game_bench_fixture(
        n_random_coords=1, descent_iterations=iters
    )
    # Force a budget ~1/8 of the dataset: the streamed fit must page.
    chunk_rows = max(1, data.num_examples // 8)
    chunk_mb = (
        (PREFETCH_DEPTH + 1) * chunk_rows * per_row_bytes(data) / (1 << 20)
    )

    resident = GameEstimator("logistic_regression", data,
                             residual_mode="device")
    resident.fit([config])  # warm-up: compile + device-data upload
    t0 = time.perf_counter()
    resident.fit([config])
    resident_wall = time.perf_counter() - t0

    session = TelemetrySession("bench-ooc")
    streamed = GameEstimator("logistic_regression", data,
                             stream_chunks=chunk_rows, telemetry=session)
    streamed.fit([config])  # warm-up
    stall_c = session.registry.counter("stream.stall_s", tier="h2d")
    overlap_c = session.registry.counter(
        "stream.prefetch_overlap_s", tier="h2d"
    )
    stall0, overlap0 = stall_c.value, overlap_c.value
    t0 = time.perf_counter()
    host_fit = streamed.fit([config])[0]
    streamed_wall = time.perf_counter() - t0
    stall = stall_c.value - stall0
    overlap = overlap_c.value - overlap0
    peak = streamed._streamer.peak_in_flight_bytes
    # Chunk compute ≈ streamed wall minus the time spent stalled on loads.
    compute = max(1e-9, streamed_wall - stall)

    _emit("game_ooc_rows_per_sec",
          iters * data.num_examples / streamed_wall, "rows/s", {
              "rows": data.num_examples,
              "entities": n_entities,
              "descent_iterations": iters,
              "chunk_rows": chunk_rows,
              "chunk_budget_mb": round(chunk_mb, 2),
              "device_peak_in_flight_bytes": int(peak),
              "streamed_fit_seconds": round(streamed_wall, 4),
              "resident_fit_seconds": round(resident_wall, 4),
              "resident_rows_per_sec": round(
                  iters * data.num_examples / resident_wall, 1
              ),
              "streaming_overhead_x": round(
                  streamed_wall / resident_wall, 3
              ),
              "stall_s": round(stall, 4),
              "prefetch_overlap_s": round(overlap, 4),
              "stall_fraction_of_compute": round(stall / compute, 4),
              "platform": platform,
          })
    if not spill:
        return

    # -- the disk tier (ISSUE 11): tile+feature bytes must EXCEED the host
    # budget so the LRU cache pages against the store.
    host_set = stream_host_bytes_estimate(data, n_coordinates=2)
    max_host_mb = host_set / (1 << 20) / 4
    with tempfile.TemporaryDirectory() as td:
        sp_session = TelemetrySession("bench-ooc-spill")
        spilled = GameEstimator(
            "logistic_regression", data, stream_chunks=chunk_rows,
            spill_dir=td, max_host_mb=max_host_mb, telemetry=sp_session,
        )
        spilled.fit([config])  # warm-up
        d_stall_c = sp_session.registry.counter(
            "stream.stall_s", tier="disk"
        )
        h_stall_c = sp_session.registry.counter(
            "stream.stall_s", tier="h2d"
        )
        d_overlap_c = sp_session.registry.counter(
            "stream.prefetch_overlap_s", tier="disk"
        )
        evict_c = sp_session.registry.counter("tiles.cache_evictions")
        d0, h0, o0 = d_stall_c.value, h_stall_c.value, d_overlap_c.value
        e0 = evict_c.value
        t0 = time.perf_counter()
        result = spilled.fit([config])[0]
        spill_wall = time.perf_counter() - t0
        disk_stall = d_stall_c.value - d0
        h2d_stall = h_stall_c.value - h0
        disk_overlap = d_overlap_c.value - o0
        # Delta around the timed fit, like the stall/overlap counters:
        # the warm-up fit evicts too, and the acceptance bar is "the
        # MEASURED fit pages against the store".
        evictions = evict_c.value - e0
        cache_bytes = sp_session.registry.gauge(
            "tiles.host_cache_bytes"
        ).value
        disk_bytes = sp_session.registry.gauge("tiles.disk_bytes").value

        # ISSUE 11 acceptance, asserted in-bench --------------------------
        if not evictions > 0:
            raise AssertionError(
                f"--spill bench must force LRU eviction (budget "
                f"{max_host_mb:.2f} MB vs host set "
                f"{host_set / (1 << 20):.2f} MB) but "
                f"tiles.cache_evictions == {evictions}"
            )
        # Models bit-identical => every downstream artifact is too; check
        # them directly, then check the PUBLISHED tiles against a
        # recomputation from the host-resident fit's final models.
        def model_table(m):
            if hasattr(m, "table"):
                return np.asarray(m.table)
            return np.asarray(m.model.coefficients.means)

        sp_last = result.descent.last_model.coordinates
        host_last = host_fit.descent.last_model.coordinates
        for name, host_model in host_last.items():
            if not np.array_equal(
                model_table(host_model), model_table(sp_last[name])
            ):
                raise AssertionError(
                    f"spilled fit diverged from host-resident streamed "
                    f"fit on coordinate {name!r}"
                )
        for name, value in host_fit.metrics.items():
            if abs(value - result.metrics[name]) > 1e-6:
                raise AssertionError(
                    f"spilled metrics diverged: {name} "
                    f"{value} vs {result.metrics[name]}"
                )
        plan = ChunkPlan(data.num_examples, chunk_rows)
        store = TileStore(td)
        oracle_streamer = ChunkStreamer()
        names = list(config.coordinates)
        oracle_rows = {
            name: score_model_chunks(
                host_last[name], data, plan, oracle_streamer
            )
            for name in names
        }
        for k in range(plan.num_chunks):
            arrays, _ = store.read(TILES, k)
            lo, hi = plan.bounds(k)
            want = np.stack([oracle_rows[name][lo:hi] for name in names])
            if not np.array_equal(arrays["tile"], want):
                raise AssertionError(
                    f"published tile {k} differs from the host-resident "
                    "recomputation (spill roundtrip not bit-exact)"
                )
        host_rate = iters * data.num_examples / streamed_wall
        spill_rate = iters * data.num_examples / spill_wall
        if spill_rate < 0.5 * host_rate:
            raise AssertionError(
                f"spilled rate {spill_rate:.1f} rows/s fell below 0.5x the "
                f"host-resident streamed rate {host_rate:.1f} rows/s"
            )
        _emit("game_ooc_disk_rows_per_sec", spill_rate, "rows/s", {
            "rows": data.num_examples,
            "chunk_rows": chunk_rows,
            "max_host_mb": round(max_host_mb, 3),
            "host_set_mb": round(host_set / (1 << 20), 3),
            "spilled_fit_seconds": round(spill_wall, 4),
            "host_resident_rows_per_sec": round(host_rate, 1),
            "spill_overhead_x": round(spill_wall / streamed_wall, 3),
            "disk_stall_s": round(disk_stall, 4),
            "h2d_stall_s": round(h2d_stall, 4),
            "disk_overlap_s": round(disk_overlap, 4),
            # Per-tier stall fractions of WALL: disk stalls land on h2d
            # worker threads (overlapping consumer compute), so wall is
            # the only denominator that cannot double-count.
            "disk_stall_fraction_of_wall": round(
                disk_stall / spill_wall, 4
            ),
            "h2d_stall_fraction_of_wall": round(
                h2d_stall / spill_wall, 4
            ),
            "cache_evictions": int(evictions),
            "host_cache_bytes": int(cache_bytes),
            "disk_bytes": int(disk_bytes),
            "tiles_vs_host_resident": "bit-identical",
            "platform": platform,
        })

    # -- ISSUE 17 precision tiers on the DISK tier: rerun the spilled fit
    # with the tile store's bf16/int8 codecs.  Host-resident tiles and all
    # accumulation stay f32, so the only drift is the store roundtrip of
    # evicted-then-reloaded tiles; final metrics must stay under the
    # per-codec TILE_METRIC_TOL bound vs the host-resident streamed fit.
    from photon_tpu.game.lowp import tile_metric_tol_for

    f32_disk_bytes = disk_bytes
    # --tile-dtype restricts the lossy legs (f32 above always runs: it is
    # the parity oracle and the rate/bytes denominator).
    lossy_legs = (
        ("bf16", "int8") if tile_dtype is None
        else () if tile_dtype == "f32" else (tile_dtype,)
    )
    for dtype in lossy_legs:
        tol = tile_metric_tol_for(dtype)
        with tempfile.TemporaryDirectory() as td:
            lp_session = TelemetrySession(f"bench-ooc-spill-{dtype}")
            lp = GameEstimator(
                "logistic_regression", data, stream_chunks=chunk_rows,
                spill_dir=td, max_host_mb=max_host_mb,
                telemetry=lp_session, tile_dtype=dtype,
            )
            lp.fit([config])  # warm-up
            lp_evict_c = lp_session.registry.counter("tiles.cache_evictions")
            e0 = lp_evict_c.value
            t0 = time.perf_counter()
            lp_result = lp.fit([config])[0]
            lp_wall = time.perf_counter() - t0
            lp_evictions = lp_evict_c.value - e0
            lp_disk_bytes = lp_session.registry.gauge(
                "tiles.disk_bytes"
            ).value
            if not lp_evictions > 0:
                raise AssertionError(
                    f"[{dtype}] spill bench must force LRU eviction but "
                    f"tiles.cache_evictions == {lp_evictions}"
                )
            # Parity vs the host-resident streamed fit: final coordinate
            # tables (the fixture carries no validation metrics) plus any
            # metrics, all under the codec's declared bound.
            worst = 0.0
            lp_last = lp_result.descent.last_model.coordinates
            for name, host_model in host_last.items():
                worst = max(worst, float(np.max(np.abs(
                    model_table(host_model) - model_table(lp_last[name])
                ))))
            for name, value in host_fit.metrics.items():
                worst = max(worst, abs(value - lp_result.metrics[name]))
            if worst > tol:
                raise AssertionError(
                    f"[{dtype}] spilled fit drifted {worst:.2e} from the "
                    f"host-resident streamed fit; the codec's declared "
                    f"bound is {tol:g}"
                )
            lp_rate = iters * data.num_examples / lp_wall
            _emit(f"game_ooc_disk_rows_per_sec_{dtype}", lp_rate, "rows/s", {
                "rows": data.num_examples,
                "chunk_rows": chunk_rows,
                "max_host_mb": round(max_host_mb, 3),
                "tile_dtype": dtype,
                "spilled_fit_seconds": round(lp_wall, 4),
                "f32_disk_rows_per_sec": round(spill_rate, 1),
                "rate_vs_f32": round(lp_rate / spill_rate, 3),
                "cache_evictions": int(lp_evictions),
                "disk_bytes": int(lp_disk_bytes),
                "disk_bytes_vs_f32": round(
                    f32_disk_bytes / max(1, lp_disk_bytes), 2
                ),
                "max_metric_delta": worst,
                "metric_bound": tol,
                "platform": platform,
            })


def _bench_descent() -> None:
    """GAME coordinate-descent residual micro-bench (``--mode descent``).

    Runs the SAME synthetic multi-coordinate GAME fit twice — once under the
    seed's host float64 residual path (``PHOTON_RESIDUALS=host``) and once
    under the device-resident residual engine (``game/residuals.py``) — and
    emits one JSON line whose value is the device path's descent
    iterations/sec, with the host path's number and the speedup in detail.
    Each mode is timed on its SECOND fit: the first pays compilation and the
    estimator's one-time device-data upload, which both modes share.
    """
    from photon_tpu.game.estimator import GameEstimator

    iters = 3
    platform, (n_entities, _rows_mean), data, config = _game_bench_fixture(
        n_random_coords=3, descent_iterations=iters
    )

    walls = {}
    reps = 3
    for mode in ("host", "device"):
        estimator = GameEstimator(
            "logistic_regression", data, residual_mode=mode
        )
        estimator.fit([config])  # warm-up: compile + device-data upload
        best = float("inf")
        for _ in range(reps):  # best-of-reps: shared-CPU noise rejection
            t0 = time.perf_counter()
            estimator.fit([config])
            best = min(best, time.perf_counter() - t0)
        walls[mode] = best

    _emit("game_descent_iters_per_sec", iters / walls["device"], "iters/s", {
        "rows": data.num_examples,
        "entities": n_entities,
        "coordinates": 4,
        "descent_iterations": iters,
        "device_fit_seconds": round(walls["device"], 4),
        "host_fit_seconds": round(walls["host"], 4),
        "host_iters_per_sec": round(iters / walls["host"], 3),
        "speedup_vs_host": round(walls["host"] / walls["device"], 3),
        "rows_per_sec": round(iters * data.num_examples / walls["device"], 1),
        "platform": platform,
    })


def _bench_validation() -> None:
    """GAME validation-pipeline micro-bench (``--mode validation``).

    Fits one synthetic multi-coordinate GAME model, then times the per-
    outer-iteration validation step both ways on the SAME fit: the seed's
    host path (full ``GameModel.score`` fetch + numpy evaluator pass, once
    per iteration) against the device pipeline (incremental re-score of the
    one coordinate that "just trained", compensated composite, jitted
    device metrics — one scalar sync per metric).  Emits one JSON line
    whose value is the device path's validation rows/sec.
    """
    from photon_tpu.evaluation.evaluators import MultiEvaluator, get_evaluator
    from photon_tpu.game.data import split_game_dataset
    from photon_tpu.game.estimator import GameEstimator
    from photon_tpu.game.model import DeviceScoringCache
    from photon_tpu.game.residuals import ValidationEngine

    platform, _, data, config = _game_bench_fixture(
        n_random_coords=2, descent_iterations=1
    )
    train, val = split_game_dataset(data, 0.25)
    evaluators = MultiEvaluator(
        [get_evaluator("auc"), get_evaluator("logistic_loss"),
         get_evaluator("sharded_auc:re0")]
    )
    model = GameEstimator(
        "logistic_regression", train, val, evaluators=evaluators
    ).fit([config])[0].model
    names = list(model.coordinates)
    n_val, iters, reps = val.num_examples, 8, 3

    # Host path: what every outer iteration used to pay — full composite
    # re-score (margins of EVERY coordinate to host) + numpy evaluators.
    def host_pass() -> None:
        scores = model.score(val)
        evaluators.evaluate(scores, val.label, val.weight, dict(val.id_columns))

    host_pass()  # warm-up: jitted per-coordinate margins compile
    host_best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            host_pass()
        host_best = min(host_best, time.perf_counter() - t0)

    # Device pipeline: the steady state of descent — only the coordinate
    # that just trained re-scores; metrics are jitted device kernels.
    cache = DeviceScoringCache(val)
    engine = ValidationEngine(val.offset, names=names)
    entity_ids = {"re0": cache.entity_codes("re0")}
    for name in names:
        engine.update(name, cache.score(model.coordinates[name]))

    def device_pass(i: int) -> None:
        name = names[i % len(names)]
        engine.update(name, cache.score(model.coordinates[name]))
        evaluators.evaluate(
            engine.composite(), cache.label, cache.weight, entity_ids
        )

    device_pass(0)  # warm-up: metric kernels compile
    device_best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for i in range(iters):
            device_pass(i)
        device_best = min(device_best, time.perf_counter() - t0)

    _emit("game_validation_rows_per_sec", iters * n_val / device_best, "rows/s", {
        "validation_rows": n_val,
        "iterations": iters,
        "coordinates": len(names),
        "metrics": [ev.name for ev in evaluators.evaluators],
        "device_seconds": round(device_best, 4),
        "host_seconds": round(host_best, 4),
        "host_rows_per_sec": round(iters * n_val / host_best, 1),
        "speedup_vs_host": round(host_best / device_best, 3),
        "platform": platform,
    })


def _entities_dataset(n_entities: int, rows_mean: int = 3, dim: int = 8,
                      seed: int = 0):
    """Synthetic single-coordinate per-entity dataset for the entity-scaling
    bench: geometric (skewed) rows per entity, dense ``dim``-feature shard
    with an intercept column — the per-user/per-item shape at whatever
    entity count the curve point asks for (vectorized: the 1M point builds
    in seconds, not minutes)."""
    from photon_tpu.game.data import DenseShard, GameDataset

    rng = np.random.default_rng(seed)
    counts = np.maximum(1, rng.geometric(1.0 / rows_mean, n_entities))
    n = int(counts.sum())
    ent = np.repeat(np.arange(n_entities, dtype=np.int64), counts)
    x = rng.standard_normal((n, dim)).astype(np.float32)
    x[:, -1] = 1.0
    w_true = (rng.standard_normal((n_entities, dim)) * 0.5).astype(np.float32)
    z = np.einsum("nd,nd->n", x, w_true[ent])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-z))).astype(np.float32)
    return GameDataset.create(y, {"re0": DenseShard(x)},
                              id_columns={"re0": ent})


def _entities_problem():
    from photon_tpu.core.objective import RegularizationContext
    from photon_tpu.core.optimizers import OptimizerConfig
    from photon_tpu.core.problem import ProblemConfig

    return ProblemConfig(
        regularization=RegularizationContext("l2", 1.0),
        optimizer_config=OptimizerConfig(max_iterations=50),
    )


def _solve_path_env(path: str) -> dict:
    """Env knobs of one entity-solve path: ``batched`` (the default size-
    binned Newton), ``bucket_loop`` (the seed's per-capacity loop + vmapped
    L-BFGS — the perf baseline), ``bucket_loop_newton`` (per-capacity loop,
    Newton solver — the exact-parity baseline: same solver, so the only
    delta is the batched restructuring)."""
    return {
        "batched": {"PHOTON_SOLVE_BINNING": "on", "PHOTON_SOLVE_NEWTON": "on"},
        "bucket_loop": {"PHOTON_SOLVE_BINNING": "off",
                        "PHOTON_SOLVE_NEWTON": "off"},
        "bucket_loop_newton": {"PHOTON_SOLVE_BINNING": "off",
                               "PHOTON_SOLVE_NEWTON": "on"},
    }[path]


def _bench_entities(max_entities: int | None = None) -> None:
    """Entity-scaling micro-bench (``--mode entities``) — the ISSUE 8
    headline: a 10k → 1M synthetic-entity CPU curve timing one
    ``RandomEffectCoordinate.train`` under the size-binned batched
    Cholesky/Newton path against the seed's bucket-loop path, plus a small
    coordinate-descent fit in BOTH residual modes checking solver parity
    and the one-host-sync-per-iteration contract.

    Asserted per curve point: the batched path matches the bucket-loop
    path run with the SAME (Newton) solver to ≤1e-5 (the batched
    restructuring is exact) and the seed's iterative solver to ≤5e-3 at
    the 99.9th percentile (the f32 cross-solver agreement; the max is
    bounded at 5e-2 — the seed solver's own stall tail over a million
    entities; the batched path itself sits ~1e-7 from the f64
    ground-truth optimum — tests/test_batched_solve.py pins that).
    At ≥100k entities the batched path must BEAT the bucket loop on
    entity-solves/sec.  ``PHOTON_BENCH_ENTITIES_MAX`` caps the curve (the
    default bench run rides with a 100k cap; standalone runs the full 1M).
    """
    import jax

    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        RandomEffectCoordinateConfig,
    )

    platform = jax.devices()[0].platform
    cap = int(
        max_entities
        if max_entities is not None
        else os.environ.get("PHOTON_BENCH_ENTITIES_MAX", str(1_000_000))
    )
    curve_points = [n for n in (10_000, 100_000, 1_000_000) if n <= cap]
    if not curve_points:
        curve_points = [cap]
    config = RandomEffectCoordinateConfig(
        shard_name="re0", entity_column="re0", problem=_entities_problem()
    )

    def run_path(data, path: str) -> tuple:
        saved = {
            k: os.environ.get(k)
            for k in ("PHOTON_SOLVE_BINNING", "PHOTON_SOLVE_NEWTON")
        }
        os.environ.update(_solve_path_env(path))
        try:
            coord = RandomEffectCoordinate(data, config, "logistic_regression")
            offsets = np.zeros(data.num_examples, np.float32)
            model, _ = coord.train(offsets)  # warm-up: compile + upload
            np.asarray(model.table)  # block: warm-up fully done pre-timing
            best = float("inf")
            for _ in range(2):  # best-of-reps: shared-CPU noise rejection
                t0 = time.perf_counter()
                model, _ = coord.train(offsets)
                np.asarray(model.table)  # block: solves actually ran
                best = min(best, time.perf_counter() - t0)
            table = np.asarray(model.table)
            bins = len(coord.device_data.buckets)
        finally:
            for k, v in saved.items():
                os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
        return best, table, bins

    curve = []
    for n_entities in curve_points:
        data = _entities_dataset(n_entities)
        results = {p: run_path(data, p) for p in
                   ("batched", "bucket_loop", "bucket_loop_newton")}
        batched_s, batched_table, n_bins = results["batched"]
        loop_s, loop_table, n_buckets = results["bucket_loop"]
        exact = np.abs(batched_table - results["bucket_loop_newton"][1]).max()
        cross_diff = np.abs(batched_table - loop_table)
        cross = float(cross_diff.max())
        # The seed's L-BFGS stalls in a per-entity ~1e-4 f32 value basin
        # whose worst case grows with the max over a million entities, so
        # the cross-solver sanity check is quantile-based: virtually every
        # entity agrees to the f32 floor, and even the seed solver's worst
        # stall stays bounded.  The ≤1e-5 acceptance parity is the
        # same-solver check above it, where the only delta is the batched
        # restructuring.
        cross_p999 = float(np.quantile(cross_diff, 0.999))
        if exact > 1e-5:
            raise RuntimeError(
                f"batched vs bucket-loop (same solver) parity {exact:.3e} "
                f"> 1e-5 at {n_entities} entities"
            )
        if cross_p999 > 5e-3 or cross > 5e-2:
            raise RuntimeError(
                f"batched vs seed-solver agreement p99.9={cross_p999:.3e} "
                f"max={cross:.3e} (bounds 5e-3 / 5e-2) at "
                f"{n_entities} entities"
            )
        speedup = loop_s / batched_s
        if n_entities >= 100_000 and speedup <= 1.0:
            raise RuntimeError(
                f"batched path did not beat the bucket loop at "
                f"{n_entities} entities ({speedup:.3f}x)"
            )
        curve.append({
            "entities": n_entities,
            "rows": data.num_examples,
            "bins": n_bins,
            "buckets": n_buckets,
            "batched_solve_seconds": round(batched_s, 4),
            "bucket_loop_solve_seconds": round(loop_s, 4),
            "batched_solves_per_sec": round(n_entities / batched_s, 1),
            "bucket_loop_solves_per_sec": round(n_entities / loop_s, 1),
            "speedup_vs_bucket_loop": round(speedup, 3),
            "max_same_solver_diff": float(exact),
            "max_cross_solver_diff": cross,
            "p999_cross_solver_diff": cross_p999,
        })
        del results, batched_table, loop_table, data

    descent = _entities_descent_checks()

    top = curve[-1]
    _emit("game_entity_solves_per_sec", top["batched_solves_per_sec"],
          "solves/s", {
              "entities": top["entities"],
              "rows": top["rows"],
              "speedup_vs_bucket_loop": top["speedup_vs_bucket_loop"],
              "curve": curve,
              "descent_parity": descent,
              "platform": platform,
          })
    # The high-dim Newton-CG leg (ISSUE 14) rides every entities
    # invocation; PHOTON_BENCH_HIDIM=off skips it (it pays 6 compiled
    # programs up to d=1024 — real money on a cold cache).
    if os.environ.get("PHOTON_BENCH_HIDIM", "on").strip().lower() not in (
        "off", "0", "false",
    ):
        _bench_entities_hidim()


def _hidim_solve_env(path: str) -> dict:
    """Env knobs of one HIGH-DIM entity-solve path: ``newton_cg`` (the
    ISSUE 14 matrix-free route — ``PHOTON_NEWTON_MAX_DIM=0`` forces it at
    EVERY dim so the d=64 point measures CG, not the dense Cholesky) vs
    ``lbfgs`` (the vmapped iterative baseline every over-cap bin used to
    fall back to)."""
    return {
        "newton_cg": {
            "PHOTON_SOLVE_BINNING": "on", "PHOTON_SOLVE_NEWTON": "on",
            "PHOTON_SOLVE_NEWTON_CG": "on", "PHOTON_NEWTON_MAX_DIM": "0",
            # Pinned so an ambient shell override cannot shrink the CG
            # window below the d=1024 point and abort the route assertion.
            "PHOTON_NEWTON_CG_MAX_DIM": "1024",
        },
        "lbfgs": {
            "PHOTON_SOLVE_BINNING": "on", "PHOTON_SOLVE_NEWTON": "off",
            "PHOTON_SOLVE_NEWTON_CG": "off",
        },
    }[path]


_HIDIM_ENV_KEYS = ("PHOTON_SOLVE_BINNING", "PHOTON_SOLVE_NEWTON",
                   "PHOTON_SOLVE_NEWTON_CG", "PHOTON_NEWTON_MAX_DIM",
                   "PHOTON_NEWTON_CG_MAX_DIM")


def _bench_entities_hidim() -> None:
    """High-dim entity-solve leg of ``--mode entities`` (ISSUE 14): a
    d=64/256/1024 curve timing one ``RandomEffectCoordinate.train`` under
    the matrix-free Newton-CG route against the vmapped L-BFGS program
    those dims used to fall back to, emitting
    ``game_entity_solves_per_sec_hidim`` (the d=256 Newton-CG rate) on the
    default run.

    Asserted per point: the two solvers agree at the f32 cross-solver
    floor (p99 ≤ 5e-3, max ≤ 5e-2 — tests/test_newton_cg.py pins the
    Newton-CG path itself ≤1e-5 from the f64 ground truth) and every bin
    actually routed ``newton_cg``.  The acceptance bar — Newton-CG ≥ 1×
    the L-BFGS rate at d=256 — is asserted in-bench with the retry-once
    de-flake (1-core timing tails swing ±2×: a real regression fails both
    draws; only the timing is re-drawn, parity failures raise first)."""
    import jax

    from photon_tpu.game.coordinate import (
        RandomEffectCoordinate,
        RandomEffectCoordinateConfig,
    )

    platform = jax.devices()[0].platform
    points = ((64, 384), (256, 160), (1024, 32))  # (dim, entities)
    config = RandomEffectCoordinateConfig(
        shard_name="re0", entity_column="re0", problem=_entities_problem()
    )

    def run_path(data, path: str) -> tuple:
        saved = {k: os.environ.get(k) for k in _HIDIM_ENV_KEYS}
        os.environ.update(_hidim_solve_env(path))
        try:
            coord = RandomEffectCoordinate(data, config,
                                           "logistic_regression")
            routes = coord._bin_routes()
            offsets = np.zeros(data.num_examples, np.float32)
            model, _ = coord.train(offsets)  # warm-up: compile + upload
            np.asarray(model.table)
            best = float("inf")
            for _ in range(2):  # best-of-reps: shared-CPU noise rejection
                t0 = time.perf_counter()
                model, _ = coord.train(offsets)
                np.asarray(model.table)
                best = min(best, time.perf_counter() - t0)
            table = np.asarray(model.table)
        finally:
            for k, v in saved.items():
                os.environ.pop(k, None) if v is None else os.environ.__setitem__(k, v)
        return best, table, routes

    def measure(dim: int, n_entities: int) -> dict:
        data = _entities_dataset(n_entities, rows_mean=6, dim=dim, seed=5)
        cg_s, cg_table, cg_routes = run_path(data, "newton_cg")
        lb_s, lb_table, _ = run_path(data, "lbfgs")
        if any(r != "newton_cg" for r in cg_routes):
            raise RuntimeError(
                f"hidim d={dim}: expected every bin on the newton_cg "
                f"route, got {cg_routes}"
            )
        diff = np.abs(cg_table - lb_table)
        p99 = float(np.quantile(diff, 0.99))
        worst = float(diff.max())
        if p99 > 5e-3 or worst > 5e-2:
            raise RuntimeError(
                f"hidim d={dim}: newton_cg vs vmapped-lbfgs agreement "
                f"p99={p99:.3e} max={worst:.3e} (bounds 5e-3 / 5e-2)"
            )
        return {
            "dim": dim,
            "entities": n_entities,
            "rows": data.num_examples,
            "newton_cg_solve_seconds": round(cg_s, 4),
            "lbfgs_solve_seconds": round(lb_s, 4),
            "newton_cg_solves_per_sec": round(n_entities / cg_s, 1),
            "lbfgs_solves_per_sec": round(n_entities / lb_s, 1),
            "speedup_vs_vmapped_lbfgs": round(lb_s / cg_s, 3),
            "p99_cross_solver_diff": p99,
            "max_cross_solver_diff": worst,
        }

    curve = [measure(dim, n) for dim, n in points]
    bar_idx = next(i for i, p in enumerate(curve) if p["dim"] == 256)
    if curve[bar_idx]["speedup_vs_vmapped_lbfgs"] < 1.0:
        # Retry-once de-flake: re-draw ONLY the d=256 timing (parity
        # re-checks ride along); a real regression fails both draws.
        curve[bar_idx] = measure(*points[bar_idx])
        if curve[bar_idx]["speedup_vs_vmapped_lbfgs"] < 1.0:
            raise RuntimeError(
                f"newton_cg did not reach the vmapped L-BFGS rate at "
                f"d=256 on both draws "
                f"({curve[bar_idx]['speedup_vs_vmapped_lbfgs']:.3f}x < 1.0x)"
            )
    bar = curve[bar_idx]
    _emit("game_entity_solves_per_sec_hidim",
          bar["newton_cg_solves_per_sec"], "solves/s", {
              "dim": bar["dim"],
              "entities": bar["entities"],
              "speedup_vs_vmapped_lbfgs": bar["speedup_vs_vmapped_lbfgs"],
              "curve": curve,
              "platform": platform,
          })


def _entities_descent_checks() -> dict:
    """The ``--mode entities`` descent-level assertions: a small GAME fit
    (fixed + per-entity coordinate) under the batched path vs the
    bucket-loop path with the same solver, in BOTH residual modes — final
    random-effect tables must agree ≤1e-5 — and ``descent.host_syncs``
    must stay exactly 1 per outer iteration under the batched path."""
    from photon_tpu.game.coordinate import (
        FixedEffectCoordinateConfig,
        RandomEffectCoordinateConfig,
    )
    from photon_tpu.game.estimator import (
        GameEstimator,
        GameOptimizationConfiguration,
    )
    from photon_tpu.telemetry import TelemetrySession

    iters = 3
    data = _entities_dataset(4000, seed=7)
    # A one-shard fixture: the fixed effect trains on the same dense shard
    # (a global bias model), the random coordinate on per-entity rows.
    config = GameOptimizationConfiguration(
        coordinates={
            "fixed": FixedEffectCoordinateConfig("re0", _entities_problem()),
            "per_entity": RandomEffectCoordinateConfig(
                "re0", "re0", _entities_problem()
            ),
        },
        descent_iterations=iters,
    )
    out: dict = {}
    for residual_mode in ("device", "host"):
        tables = {}
        for path in ("batched", "bucket_loop_newton"):
            saved = {
                k: os.environ.get(k)
                for k in ("PHOTON_SOLVE_BINNING", "PHOTON_SOLVE_NEWTON")
            }
            os.environ.update(_solve_path_env(path))
            try:
                session = TelemetrySession(f"bench-entities-{residual_mode}")
                result = GameEstimator(
                    "logistic_regression", data,
                    residual_mode=residual_mode, telemetry=session,
                ).fit([config])[0]
                tables[path] = np.asarray(
                    result.model.coordinate("per_entity").table
                )
                if path == "batched" and residual_mode == "device":
                    syncs = int(
                        session.counter("descent.host_syncs", kind="stats").value
                    )
                    if syncs != iters:
                        raise RuntimeError(
                            f"descent.host_syncs == {syncs}, want {iters} "
                            "(one per outer iteration) under the batched path"
                        )
                    out["host_syncs_per_iteration"] = syncs / iters
            finally:
                for k, v in saved.items():
                    os.environ.pop(k, None) if v is None \
                        else os.environ.__setitem__(k, v)
        diff = float(
            np.abs(tables["batched"] - tables["bucket_loop_newton"]).max()
        )
        if diff > 1e-5:
            raise RuntimeError(
                f"descent-level batched parity {diff:.3e} > 1e-5 in "
                f"{residual_mode} residual mode"
            )
        out[f"max_table_diff_{residual_mode}"] = diff
    return out


def _serving_fixture():
    """Synthetic GAME model + request source for the serving bench: the
    model is CONSTRUCTED (seeded coefficient tables over the dataset's
    entity vocabulary), not fitted — serving measures scoring, and a fit
    would dominate the bench's wall clock for nothing."""
    import jax

    from photon_tpu.data.synthetic import make_game_dataset
    from photon_tpu.game.model import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, model_for_task

    platform = jax.devices()[0].platform
    big = platform != "cpu"
    n_entities, rows_mean = (20_000, 20) if big else (4000, 8)
    # random_dim 32: wide enough that the int8 tier's per-row scale
    # column amortizes (bytes ratio 4d/(d+4) = 3.56x >= the 3.5x bar);
    # at the pre-ISSUE-17 dim of 8 the ratio tops out at 2.67x.
    fixed_dim, random_dim = 32, 32
    data, _ = make_game_dataset(
        n_entities, rows_mean, fixed_dim, random_dim, seed=0,
        n_random_coords=2,
    )
    rng = np.random.default_rng(7)
    coordinates = {
        "fixed": FixedEffectModel(
            model_for_task("logistic_regression", Coefficients(
                rng.standard_normal(fixed_dim).astype(np.float32)
            )),
            "global",
        )
    }
    for name in ("re0", "re1"):
        keys = np.unique(data.id_columns[name])
        coordinates[name] = RandomEffectModel(
            table=rng.standard_normal(
                (len(keys), random_dim)
            ).astype(np.float32),
            keys=keys, entity_column=name, shard_name=name,
            task_type="logistic_regression",
        )
    model = GameModel(
        coordinates=coordinates, task_type="logistic_regression"
    )
    return platform, model, data


def _bench_serving(dtypes=("f32", "bf16", "int8")) -> None:
    """Online GAME scoring-service micro-bench (``--mode serving``).

    Drives a seeded long-tailed request stream (the serve_game driver's
    size distribution) through the device-resident
    :class:`~photon_tpu.serving.GameScorer` + async batcher with
    closed-loop clients, and reports p50/p99 request latency and QPS
    against the per-request HOST-scoring baseline (``GameModel.score`` on
    each request's dataset slice — the only serving story the repo had
    before the serving layer).  The emitted value is the served QPS;
    the baseline QPS and the ratio ride the detail so the speedup is a
    printed comparison, not a bare number.

    The ISSUE 17 precision tiers ride the same harness: the f32 leg keeps
    the historical ``game_serving_qps`` name (baseline continuity), then
    bf16 and int8 legs re-run the identical request stream against scorers
    whose gather tables store the reduced dtype, emitting
    ``game_serving_qps_bf16`` / ``game_serving_qps_int8``.  Asserted
    in-bench, per dtype: parity vs the f32 HOST oracle under the declared
    ``PARITY_TOL`` bound, table-bytes reduction vs f32 (bf16 >= 1.9x,
    int8 >= 3.5x), and bf16 QPS >= f32 QPS on accelerators — each leg's
    QPS is the best of ``passes`` closed-loop runs, which damps scheduler
    noise; on the CPU fixture (decode ALU cost, no bandwidth win) the bar
    is a no-collapse floor instead, recorded in ``qps_bar``."""
    from photon_tpu.drivers.serve_game import request_sizes
    from photon_tpu.game.data import take_rows
    from photon_tpu.game.lowp import parity_tol_for
    from photon_tpu.serving import (
        GameScorer,
        RequestBatcher,
        build_requests,
        request_spec_for_dataset,
        run_closed_loop,
    )
    from photon_tpu.telemetry import TelemetrySession

    platform, model, data = _serving_fixture()
    max_batch, clients, mean_rows = 128, 16, 8.0
    n_requests = 1500 if platform != "cpu" else 400
    sizes = request_sizes(n_requests, mean_rows, max_batch, seed=0)
    requests = build_requests(data, model, sizes)
    rows = int(sizes.sum())
    spec = request_spec_for_dataset(model, data)

    def leg(dtype: str, passes: int) -> dict:
        """One storage-dtype leg: warm a scorer, drive the stream
        ``passes`` times through a fresh batcher, keep the fastest pass."""
        session = TelemetrySession(f"bench-serving-{dtype}")
        scorer = GameScorer(
            model, request_spec=spec, max_batch=max_batch,
            telemetry=session, table_dtype=dtype,
        )
        t0 = time.perf_counter()
        scorer.warmup()
        warmup_s = time.perf_counter() - t0
        warm_programs = scorer.compilations
        best = None
        with RequestBatcher(
            scorer, max_batch=max_batch, max_delay_s=0.001,
            telemetry=session,
        ) as batcher:
            for _ in range(passes):
                scores, latencies, wall = run_closed_loop(
                    batcher, requests, clients=clients
                )
                if best is None or wall < best[2]:
                    best = (scores, latencies, wall)
        scores, latencies, wall = best
        snapshot = session.registry.snapshot()
        totals = {}
        for m in snapshot["counters"]:
            totals[m["name"]] = totals.get(m["name"], 0) + m["value"]
        batches = totals.get("serving.batches", 0)
        if totals.get("serving.host_syncs", 0) > batches:
            raise AssertionError(
                f"[{dtype}] serving.host_syncs exceeded one per batch"
            )
        # Post-warmup recompiles are forbidden for EVERY storage dtype:
        # the decode lives inside the warmed bucket programs.
        if scorer.compilations != warm_programs:
            raise AssertionError(
                f"[{dtype}] serving recompiled under traffic: "
                f"{scorer.compilations} programs vs {warm_programs} at "
                "warmup"
            )
        pad_hist = next(
            (h for h in snapshot["histograms"]
             if h["name"] == "serving.padded_fraction"), {},
        )
        return {
            "dtype": dtype,
            "scores": scores,
            "qps": len(requests) / wall,
            "wall": wall,
            "lat_ms": np.sort(np.asarray(latencies, np.float64)) * 1e3,
            "batches": int(batches),
            "pad_mean": round(pad_hist.get("mean") or 0.0, 3),
            "cold": int(totals.get("serving.cold_entities", 0)),
            "compiled": scorer.compilations,
            "warmup_s": warmup_s,
            "table_bytes": int(session.registry.gauge(
                "serving.table_bytes", dtype=dtype
            ).value),
        }

    # f32 always runs: it is the historical headline AND the denominator
    # for every cross-dtype bar (--table-dtype restricts the LOSSY legs).
    dtypes = tuple(dict.fromkeys(("f32",) + tuple(dtypes)))
    passes = 3 if platform == "cpu" else 2
    legs = {d: leg(d, passes) for d in dtypes}

    # Host baseline: per-request GameModel.score over the SAME row windows
    # (request_windows — the definition build_requests cut from, so the
    # parity oracle cannot drift onto misaligned rows; a warmup pass pays
    # each distinct shape's compile, as serving's warmup did), on a subset
    # big enough to time and small enough not to dominate the bench.  One
    # oracle serves every dtype leg: host scoring is always f32.
    from photon_tpu.serving import request_windows

    n_base = min(len(requests), 100)
    windows = request_windows(data.num_examples, sizes[:n_base])
    chunks = [take_rows(data, w) for w in windows]
    host_scores = [model.score(c) for c in chunks]  # warmup + parity oracle
    t0 = time.perf_counter()
    for c in chunks:
        model.score(c)
    host_wall = time.perf_counter() - t0
    host_qps = n_base / host_wall

    parity = {}
    for dtype, lg in legs.items():
        worst = max(
            float(np.abs(s[: len(h)] - h).max())
            for s, h in zip(lg["scores"][:n_base], host_scores)
        )
        tol = 1e-3 if dtype == "f32" else parity_tol_for(dtype)
        if worst > tol:
            raise AssertionError(
                f"[{dtype}] serving/host parity broke: max |delta| "
                f"{worst:.2e} > declared bound {tol:g}"
            )
        parity[dtype] = worst

    # ISSUE 17 acceptance, asserted in-bench --------------------------
    f32_bytes = legs["f32"]["table_bytes"]
    for dtype, floor in (("bf16", 1.9), ("int8", 3.5)):
        if dtype not in legs:
            continue
        ratio = f32_bytes / max(1, legs[dtype]["table_bytes"])
        legs[dtype]["bytes_ratio"] = ratio
        if ratio < floor:
            raise AssertionError(
                f"[{dtype}] table bytes only {ratio:.2f}x smaller than "
                f"f32 ({legs[dtype]['table_bytes']} vs {f32_bytes}); "
                f"the precision tier promises >= {floor}x"
            )
    # bf16 QPS bar, platform-scoped like the fleet scaling bar: where the
    # accelerator's memory system is the gather bottleneck the half-width
    # table must not lose to f32 (>= 1.0x); on the CPU fixture the decode
    # convert costs real ALU while the bandwidth saving buys nothing
    # (tables fit in cache), so the bar drops to a no-collapse floor.
    # The emitted ``qps_bar`` says which bar applied.
    qps_bar = 1.0 if platform != "cpu" else 0.8
    if "bf16" in legs:
        ratio = legs["bf16"]["qps"] / legs["f32"]["qps"]
        if ratio < qps_bar:
            raise AssertionError(
                f"bf16 serving QPS {legs['bf16']['qps']:.1f} fell below "
                f"{qps_bar}x f32's {legs['f32']['qps']:.1f} (best of "
                f"{passes} passes each) — the half-width table must not "
                "decode slower than it gathers"
            )

    for dtype, lg in legs.items():
        name = (
            "game_serving_qps" if dtype == "f32"
            else f"game_serving_qps_{dtype}"
        )
        detail = {
            "requests": len(requests),
            "rows": rows,
            "clients": clients,
            "max_batch": max_batch,
            "passes": passes,
            "mean_request_rows": round(float(sizes.mean()), 2),
            "latency_p50_ms": round(float(np.percentile(lg["lat_ms"], 50)), 3),
            "latency_p99_ms": round(float(np.percentile(lg["lat_ms"], 99)), 3),
            "rows_per_sec": round(rows / lg["wall"], 1),
            "batches": lg["batches"],
            "requests_per_batch": round(len(requests) / lg["batches"], 2)
            if lg["batches"] else None,
            "padded_fraction_mean": lg["pad_mean"],
            "cold_entities": lg["cold"],
            "compiled_programs": lg["compiled"],
            "warmup_seconds": round(lg["warmup_s"], 3),
            "host_baseline_qps": round(host_qps, 2),
            "speedup_vs_host_qps": round(lg["qps"] / host_qps, 2),
            "max_parity_delta": parity[dtype],
            "parity_bound": 1e-3 if dtype == "f32" else parity_tol_for(dtype),
            "table_bytes": lg["table_bytes"],
            "platform": platform,
        }
        if dtype != "f32":
            detail["table_dtype"] = dtype
            detail["table_bytes_vs_f32"] = round(lg["bytes_ratio"], 2)
            detail["qps_vs_f32"] = round(lg["qps"] / legs["f32"]["qps"], 3)
            if dtype == "bf16":
                detail["qps_bar"] = qps_bar
        _emit(name, lg["qps"], "req/s", detail)


def _bench_fleet(table_dtype: str = "f32") -> None:
    """Fleet-serving macro-bench (``--mode fleet`` — the ISSUE 12
    tentpole's measurement, and the serving number that rides BENCH_*.json
    going forward).

    Replays GENERATED traffic — power-law entity popularity, diurnal ramp,
    a cold-start storm segment — through the replicated serving fleet over
    the real TCP loopback transport, and measures what the single-scorer
    serving bench cannot: QPS-vs-replicas scaling, admitted-request p50/p99
    under offered load past saturation, and the admission-control shed
    fraction that keeps the tail bounded there.

    In-bench acceptance (raises on violation):

    - per-request score parity vs the host oracle ≤ 1e-3 on EVERY served
      request of every leg (storm requests included — they must ride the
      zero-row fallback, not corrupt);
    - 2-replica QPS ≥ 1.6x single-replica on the same replayed traffic —
      asserted where the host can physically scale (≥ 2 effective cores or
      a real accelerator); on a single-core CPU fixture thread-backed
      replicas share the one core, so the bar drops to a no-collapse floor
      (≥ 0.6x) and the emitted ``scaling_bar`` says which bar applied;
    - at 2x-saturation offered load, admitted-request p99 ≤ 2x the
      unsaturated p99, with the shed fraction (> 10%) reported;
    - ZERO jax compile events across every post-warmup leg (the recompile-
      freedom contract holds fleet-wide, storm and saturation included);
    - the storm segment's unknown entities are counted
      (``serving.cold_entities`` > 0) — the fallback actually exercised.
    """
    import dataclasses as _dc

    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    from photon_tpu.game.lowp import parity_tol_for
    from photon_tpu.serving import (
        AsyncScoringClient,
        ScoringClient,
        ServingFleet,
        SupervisorPolicy,
        TrafficSpec,
        generate_traffic,
        host_score_request,
        replay_open_loop,
        request_spec_for_dataset,
        run_closed_loop_outcomes,
    )
    from photon_tpu.telemetry import TelemetrySession

    platform, model, data = _serving_fixture()
    max_batch, clients = 128, 8
    n_requests = 1000 if platform != "cpu" else 300
    # --table-dtype widens the host-parity bound to the storage codec's
    # declared one (the host oracle always scores f32).
    parity_bound = 1e-3 if table_dtype == "f32" else parity_tol_for(
        table_dtype
    )
    spec = request_spec_for_dataset(model, data)
    base_traffic = TrafficSpec(
        requests=n_requests, mean_rows=8.0, max_rows=max_batch,
        popularity="powerlaw", alpha=1.1, ramp="diurnal",
        storm_frac=0.05, storm_at=0.7, seed=0,
    )
    traffic = generate_traffic(data, model, base_traffic)

    def check_parity(outcomes, leg):
        """Every served response vs the host oracle of ITS OWN request
        (each leg replays its own seeded traffic)."""
        worst = 0.0
        for out in outcomes:
            if out.status != "ok":
                continue
            want = host_score_request(model, out.item.request)
            worst = max(worst, float(np.max(np.abs(
                np.asarray(out.scores, np.float64) - want
            ))))
        if worst > parity_bound:
            raise AssertionError(
                f"fleet/host parity broke on the {leg} leg "
                f"({table_dtype} tables): max |delta| {worst:.2e} > "
                f"{parity_bound:g}"
            )
        return worst

    compile_events = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    # -- capacity legs: closed-loop clients over the TCP loopback ingest ----
    def measure_capacity(n_replicas, session):
        from photon_tpu.serving import AdmissionPolicy

        fleet = ServingFleet(
            model, replicas=n_replicas, request_spec=spec,
            max_batch=max_batch, max_delay_s=0.001, telemetry=session,
            table_dtype=table_dtype,
            # safety > 1: admission compares 2x the projected queue wait
            # against the deadline budget, absorbing EWMA estimation lag —
            # the knob that keeps the admitted tail INSIDE the 2x-p99
            # acceptance bound at the cost of shedding a little more.
            admission=AdmissionPolicy(safety=2.0),
        ).warmup()
        server = fleet.serve()
        client_pool = []

        def factory(tid):
            client = ScoringClient(server.address, telemetry=session)
            client_pool.append(client)
            return lambda item: client.score(item.request)

        jax.monitoring.register_event_listener(listener)
        try:
            outcomes, wall = run_closed_loop_outcomes(
                factory, traffic.items, clients=clients
            )
        finally:
            monitoring_src._unregister_event_listener_by_callback(listener)
            for client in client_pool:
                client.close()
        errors = [o for o in outcomes if o.status != "ok"]
        if errors:
            raise AssertionError(
                f"{len(errors)} failed requests at {n_replicas} replicas; "
                f"first: {errors[0].reason}"
            )
        parity = check_parity(outcomes, f"{n_replicas}-replica capacity")
        return fleet, server, outcomes, len(outcomes) / wall, parity

    cores = len(os.sched_getaffinity(0))
    can_scale = platform != "cpu" or cores >= 2
    scaling_bar = 1.6 if can_scale else 0.6
    # One retry on a scaling miss: on the 1-core fixture the 1-replica
    # leg's closed-loop QPS swings ±2x run-to-run with OS scheduling (8
    # client threads + handlers + batcher on one core), so a single draw
    # under the no-collapse floor can be pure noise — a REAL collapse
    # fails both draws.
    for attempt in range(2):
        session1 = TelemetrySession("bench-fleet-1r")
        fleet1, _, _, qps1, _ = measure_capacity(1, session1)
        fleet1.close()
        session2 = TelemetrySession("bench-fleet-2r")
        fleet2, server2, _, qps2, parity_cap = measure_capacity(2, session2)
        scaling = qps2 / qps1
        if scaling >= scaling_bar or attempt == 1:
            break
        fleet2.close()
    if scaling < scaling_bar:
        raise AssertionError(
            f"2-replica QPS scaling {scaling:.2f}x under the "
            f"{scaling_bar:.1f}x bar ({qps2:.0f} vs {qps1:.0f} req/s, "
            f"{cores} effective cores)"
        )

    # -- unsaturated vs 2x-saturation open-loop replays THROUGH the socket
    # (ISSUE 13 satellite / ROADMAP fleet edge (c)): the pipelined
    # AsyncScoringClient tags request frames with sequence ids and the
    # server responds out of order, so the replay's arrival schedule
    # drives the TCP transport itself — framing + socket backpressure sit
    # inside the overload measurement, while admission keeps its
    # fast-fail semantics (sheds come back as typed frames).  fleet2's
    # per-row service EWMA is already warm from the capacity leg, so the
    # saturation leg's admission projections are live from the first
    # arrival — exactly how a long-running fleet meets an overload.
    open_client = AsyncScoringClient(
        server2.address, connections=clients, telemetry=session2
    )

    def open_loop_legs(seed_base: int):
        unsat = generate_traffic(data, model, _dc.replace(
            base_traffic, target_qps=0.4 * qps2, seed=seed_base,
        ))
        out_unsat = replay_open_loop(open_client.submit, unsat,
                                     timeout_s=120.0)
        ok_unsat = [o for o in out_unsat if o.status == "ok"]
        if len(ok_unsat) != len(out_unsat):
            raise AssertionError(
                f"unsaturated replay shed/failed "
                f"{len(out_unsat) - len(ok_unsat)} requests"
            )
        lat_unsat = np.sort([o.latency_s for o in ok_unsat])
        p50_u = float(np.percentile(lat_unsat, 50))
        p99_u = float(np.percentile(lat_unsat, 99))
        check_parity(out_unsat, "unsaturated")

        deadline = 1.5 * p99_u
        # 2x requests on the saturation leg: its admitted set is the
        # ~(1 - shed) tail of the stream, and a p99 over a few dozen
        # admitted samples is essentially a max — double the sample so
        # the tail gate measures the system, not one scheduler hiccup.
        sat = generate_traffic(data, model, _dc.replace(
            base_traffic, requests=2 * n_requests,
            target_qps=2.0 * qps2, seed=seed_base + 1,
            deadline_ms=deadline * 1e3,
        ))
        out_s = replay_open_loop(open_client.submit, sat, timeout_s=120.0)
        ok_s = [o for o in out_s if o.status == "ok"]
        errors_s = [o for o in out_s if o.status == "error"]
        if errors_s:
            raise AssertionError(
                f"{len(errors_s)} failed requests in the saturation leg; "
                f"first: {errors_s[0].reason}"
            )
        if not ok_s:
            raise AssertionError("saturation leg admitted nothing")
        p99_s = float(np.percentile(
            np.sort([o.latency_s for o in ok_s]), 99
        ))
        shed_frac = sum(1 for o in out_s if o.status == "shed") / len(out_s)
        parity = check_parity(out_s, "saturation")
        return {
            "p50_unsat": p50_u, "p99_unsat": p99_u, "p99_sat": p99_s,
            "deadline_s": deadline, "shed_fraction": shed_frac,
            "admitted_sat": len(ok_s), "parity_sat": parity,
        }

    jax.monitoring.register_event_listener(listener)
    try:
        # One retry on a bounds miss: the 1-core fixture's open-loop tails
        # ride the OS scheduler (client readers + server handlers +
        # batcher threads on one core), so a single p99 gate draw can
        # fail on a hiccup — a REAL tail regression fails both draws.
        legs = open_loop_legs(seed_base=1)
        if (legs["p99_sat"] > 2.0 * legs["p99_unsat"]
                or legs["shed_fraction"] <= 0.10):
            legs = open_loop_legs(seed_base=11)
    finally:
        monitoring_src._unregister_event_listener_by_callback(listener)
        open_client.close()
    p50_unsat, p99_unsat = legs["p50_unsat"], legs["p99_unsat"]
    p99_sat, deadline_s = legs["p99_sat"], legs["deadline_s"]
    shed_fraction, parity_sat = legs["shed_fraction"], legs["parity_sat"]
    if p99_sat > 2.0 * p99_unsat:
        raise AssertionError(
            f"admitted-request p99 {p99_sat * 1e3:.2f} ms at 2x saturation "
            f"exceeds 2x the unsaturated p99 ({p99_unsat * 1e3:.2f} ms) — "
            "admission control is not bounding the tail"
        )
    if shed_fraction <= 0.10:
        raise AssertionError(
            f"only {shed_fraction:.1%} shed at 2x saturation offered load "
            "— past-saturation load is not actually shedding"
        )
    if compile_events:
        raise AssertionError(
            f"{len(compile_events)} jax compile events after warmup "
            f"(first: {compile_events[0]}) — fleet serving recompiled"
        )

    def totals(session, name):
        return sum(
            m["value"] for m in session.registry.snapshot()["counters"]
            if m["name"] == name
        )

    for s in (session1, session2):
        if totals(s, "serving.host_syncs") > totals(s, "serving.batches"):
            raise AssertionError("serving.host_syncs exceeded one per batch")
    cold = totals(session2, "serving.cold_entities")
    if cold <= 0:
        raise AssertionError(
            "the cold-start storm never hit the zero-row fallback "
            "(serving.cold_entities == 0)"
        )
    fleet2.close()

    _emit("game_fleet_qps", qps2, "req/s", {
        "replicas": 2,
        "requests_per_leg": n_requests,
        "clients": clients,
        "transport": "tcp-loopback (capacity legs closed-loop; open-loop "
                     "legs pipelined through AsyncScoringClient)",
        "qps_1_replica": round(qps1, 2),
        "qps_2_replicas": round(qps2, 2),
        "scaling_x": round(scaling, 3),
        "scaling_bar": scaling_bar,
        "effective_cores": cores,
        "latency_p50_unsat_ms": round(p50_unsat * 1e3, 3),
        "latency_p99_unsat_ms": round(p99_unsat * 1e3, 3),
        "latency_p99_saturated_ms": round(p99_sat * 1e3, 3),
        "deadline_ms": round(deadline_s * 1e3, 3),
        "offered_qps_saturated": round(2.0 * qps2, 1),
        "admitted_saturated": legs["admitted_sat"],
        "shed_fraction_saturated": round(shed_fraction, 4),
        "storm_requests": sum(
            1 for item in traffic.items if item.kind == "storm"
        ),
        "cold_entities": int(cold),
        "max_parity_delta": max(parity_cap, parity_sat),
        "compiled_programs_2r": fleet2.compilations,
        "platform": platform,
    })

    # -- CHAOS leg (ISSUE 13): replica kill mid-replay under supervision --
    # A supervised 2-replica fleet takes a replica kill in the middle of
    # an open-loop replay.  In-bench bars: ZERO lost futures (every
    # request resolves ok or shed — exactly-once through the reroute
    # path), the shed fraction during the outage window stays bounded
    # (the survivor serves; no collapse), the replica is resurrected
    # through the canary-gated rejoin, post-rejoin closed-loop QPS
    # recovers to >= 0.9x the pre-kill burst, and the parent records zero
    # jax compile events across the whole cycle.  Backend: subprocess
    # where the host can actually scale processes (>= 2 effective cores
    # or an accelerator — the kill is a real SIGKILL of the child), the
    # thread backend with the same bars on the 1-core fixture.
    import signal
    import threading as _threading
    import time as _time

    from photon_tpu.fault.injection import FaultPlan, set_plan
    from photon_tpu.serving import AdmissionPolicy as _Admission

    chaos_backend = "subprocess" if can_scale else "thread"
    session3 = TelemetrySession("bench-fleet-chaos")
    fleet3 = ServingFleet(
        model, replicas=2, request_spec=spec, backend=chaos_backend,
        max_batch=max_batch, max_delay_s=0.001, telemetry=session3,
        admission=_Admission(safety=2.0), table_dtype=table_dtype,
    ).warmup()
    fleet3.supervise(SupervisorPolicy(
        probe_interval_s=0.1, probe_deadline_s=60.0,
        respawn_base_s=0.05, max_deaths=5,
    ))
    import shutil as _shutil
    import tempfile as _tempfile

    from photon_tpu.serving import ObservePolicy
    from photon_tpu.telemetry import TraceSampler

    flight_dir = _tempfile.mkdtemp(prefix="bench-fleet-flight-")
    compile_events.clear()
    jax.monitoring.register_event_listener(listener)
    try:
        burst_items = generate_traffic(data, model, _dc.replace(
            base_traffic, requests=150, seed=4,
        )).items

        def chaos_factory(tid):
            return lambda item: fleet3.score(item.request)

        # Best-of-3: a single 150-request closed-loop burst covers ~0.1s
        # of wall on the 1-core fixture and swings 30%+ with OS
        # scheduling; the recovery bar below compares PEAK achievable
        # rates (a hiccup only ever slows a draw down, never speeds it
        # up), so one unlucky draw on either side can't fail a healthy
        # fleet while a sustained regression still fails every draw.
        qps_pre = 0.0
        for _ in range(3):
            out_pre, wall_pre = run_closed_loop_outcomes(
                chaos_factory, burst_items, clients=clients
            )
            if any(o.status != "ok" for o in out_pre):
                raise AssertionError("pre-kill burst failed requests")
            qps_pre = max(qps_pre, len(out_pre) / wall_pre)

        # -- observability leg (ISSUE 16): tracing overhead + merged trace.
        # Attach the fleet observer at full sampling, replay the SAME
        # closed-loop burst traced, and bar the overhead: tracing is
        # per-request dict bookkeeping and must cost < 5% QPS.  One-core
        # closed-loop QPS swings with OS scheduling, so a miss re-draws
        # BOTH sides (the sampler toggled off IS the untraced path) — a
        # real overhead regression fails every pair.
        observer = fleet3.observe(
            policy=ObservePolicy(sample_rate=1.0, poll_interval_s=0.1),
            flight_dir=flight_dir,
        )

        def burst_qps(leg):
            out, wall = run_closed_loop_outcomes(
                chaos_factory, burst_items, clients=clients
            )
            if any(o.status != "ok" for o in out):
                raise AssertionError(f"{leg} burst failed requests")
            return len(out) / wall

        qps_untraced = qps_pre
        for t_attempt in range(3):
            qps_traced = burst_qps("traced")
            overhead_x = qps_traced / qps_untraced
            if overhead_x >= 0.95:
                break
            observer.sampler = TraceSampler(0.0)
            qps_untraced = burst_qps("untraced re-draw")
            observer.sampler = TraceSampler(1.0)
        if overhead_x < 0.95:
            raise AssertionError(
                f"traced QPS is {overhead_x:.3f}x untraced "
                f"({qps_traced:.0f} vs {qps_untraced:.0f} req/s) — "
                "tracing overhead exceeds the 5% budget"
            )

        # One request through the full client→router→replica path over
        # TCP: the merged trace tree must span the processes and its
        # critical-path stage sum must reconcile with the end-to-end
        # latency the router observed.
        server3 = fleet3.serve()
        obs_client = AsyncScoringClient(
            server3.address, connections=1, telemetry=session3,
            observer=observer,
        )
        try:
            t_probe0 = _time.monotonic()
            obs_client.submit(burst_items[0].request).result(timeout=60.0)
            probe_wall = _time.monotonic() - t_probe0
        finally:
            obs_client.close()
        observer.poll_once()  # drain child spans shipped inline/ctrl
        tid = next(
            (t for t in reversed(observer.collector.trace_ids())
             if any(d.get("name") == "client.request"
                    for d in observer.collector.trace(t))),
            None,
        )
        if tid is None:
            raise AssertionError(
                "the traced probe request produced no merged trace with a "
                "client span"
            )
        cp = observer.collector.critical_path(tid)
        if cp is None:
            raise AssertionError(
                "no critical path for the probe trace (router span missing)"
            )
        n_procs = len(cp["processes"])
        want_procs = 3 if chaos_backend == "subprocess" else 2
        if n_procs < want_procs:
            raise AssertionError(
                f"probe trace spans {n_procs} process(es) "
                f"({cp['processes']}) — expected >= {want_procs} on the "
                f"{chaos_backend} backend"
            )
        if abs(cp["stage_sum_s"] - cp["total_s"]) > 1e-6 + 1e-3 * cp["total_s"]:
            raise AssertionError(
                f"critical-path stages sum to {cp['stage_sum_s']:.6f}s but "
                f"the request took {cp['total_s']:.6f}s — the decomposition "
                "does not reconcile"
            )
        if cp["total_s"] > probe_wall + 0.05:
            raise AssertionError(
                f"router-observed latency {cp['total_s']:.3f}s exceeds the "
                f"client-measured wall {probe_wall:.3f}s"
            )

        _emit("game_fleet_traced_qps", qps_traced, "req/s", {
            "backend": chaos_backend,
            "sample_rate": 1.0,
            "qps_untraced": round(qps_untraced, 2),
            "overhead_x": round(overhead_x, 3),
            "trace_processes": n_procs,
            "trace_spans": cp["spans"],
            "critical_path_ms": {
                s["stage"]: round(s["duration_s"] * 1e3, 3)
                for s in cp["stages"]
            },
            "end_to_end_ms": round(cp["total_s"] * 1e3, 3),
            "platform": platform,
        })

        rate = min(0.4 * qps2, 150.0)
        horizon_s = 12.0 if chaos_backend == "subprocess" else 8.0
        chaos = generate_traffic(data, model, _dc.replace(
            base_traffic, requests=max(200, int(rate * horizon_s)),
            target_qps=rate, seed=5,
            deadline_ms=max(4.0 * p99_unsat * 1e3, 50.0),
        ))
        kill_at_s = 0.3 * chaos.duration_s
        marks = {}
        t0 = _time.monotonic()

        def chaos_monkey():
            _time.sleep(kill_at_s)
            r0 = fleet3.replicas[0]
            if chaos_backend == "subprocess":
                os.kill(r0.child_pid, signal.SIGKILL)
            else:
                set_plan(FaultPlan.parse(
                    "replica:crash:replica=r0:times=1"
                ))
            # The kill LANDS when the replica actually latches dead (the
            # next batch on it, or the supervisor's probe) — the outage
            # window is [landed, rejoined], not [injected, rejoined].
            while r0.alive and _time.monotonic() - t0 < 120.0:
                _time.sleep(0.02)
            marks["kill"] = _time.monotonic() - t0
            while (not r0.alive
                   and _time.monotonic() - t0 < 120.0):
                _time.sleep(0.02)
            marks["rejoin"] = _time.monotonic() - t0

        monkey = _threading.Thread(target=chaos_monkey, daemon=True)
        monkey.start()
        out_chaos = replay_open_loop(fleet3.submit, chaos, timeout_s=180.0)
        monkey.join(timeout=120.0)
        set_plan(None)

        lost = [o for o in out_chaos if o.status == "error"]
        if lost:
            raise AssertionError(
                f"chaos leg LOST {len(lost)} futures (first: "
                f"{lost[0].reason}) — the exactly-once reroute broke"
            )
        check_parity(out_chaos, "chaos")
        if "rejoin" not in marks or not fleet3.replicas[0].alive:
            raise AssertionError(
                "the killed replica never rejoined the dispatch set"
            )
        deaths3 = sum(
            m["value"] for m in session3.registry.snapshot()["counters"]
            if m["name"] == "serving.replica_deaths"
        )
        resurrections3 = sum(
            m["value"] for m in session3.registry.snapshot()["counters"]
            if m["name"] == "serving.replica_resurrections"
        )
        if deaths3 < 1 or resurrections3 < 1:
            raise AssertionError(
                f"chaos accounting off: deaths={deaths3}, "
                f"resurrections={resurrections3}"
            )
        # Window on COMPLETION times (Outcome.finished_at_s): on the
        # 1-core fixture the replay lags its schedule, so scheduled
        # arrival offsets drift from when requests actually hit the
        # dead-replica window.
        outage = [
            o for o in out_chaos
            if o.finished_at_s is not None
            and marks["kill"] <= o.finished_at_s <= marks["rejoin"]
        ]
        outage_shed = (
            sum(1 for o in outage if o.status == "shed") / len(outage)
            if outage else 0.0
        )
        if outage and outage_shed > 0.9:
            raise AssertionError(
                f"shed fraction {outage_shed:.1%} during the outage — the "
                "survivor is not actually serving through the failure"
            )
        # Best-of-3, mirroring the pre-kill measurement above.
        qps_post = 0.0
        for _ in range(3):
            out_post, wall_post = run_closed_loop_outcomes(
                chaos_factory, burst_items, clients=clients
            )
            if any(o.status != "ok" for o in out_post):
                raise AssertionError("post-rejoin burst failed requests")
            qps_post = max(qps_post, len(out_post) / wall_post)
        recovered = qps_post / qps_pre
        if recovered < 0.9:
            raise AssertionError(
                f"post-rejoin QPS recovered only {recovered:.2f}x of "
                f"pre-kill ({qps_post:.0f} vs {qps_pre:.0f} req/s)"
            )
        # The kill must leave a postmortem: the supervisor hands the victim
        # to the observer, which persists the flight ring next to the run
        # artifacts (ISSUE 16 flight recorder).
        if not observer.flight_dumps:
            raise AssertionError(
                "no flight dump collected after the chaos kill"
            )
        flight0 = observer.flight_dumps[0]
        if not flight0["path"] or not os.path.exists(flight0["path"]):
            raise AssertionError(
                f"flight dump for {flight0['replica']} was not persisted "
                f"({flight0['path']!r})"
            )
    finally:
        monitoring_src._unregister_event_listener_by_callback(listener)
        fleet3.close()
        _shutil.rmtree(flight_dir, ignore_errors=True)
    if compile_events:
        raise AssertionError(
            f"{len(compile_events)} jax compile events across the chaos "
            f"kill->resurrect cycle (first: {compile_events[0]})"
        )

    _emit("game_fleet_chaos_recovery_x", recovered, "x pre-kill QPS", {
        "backend": chaos_backend,
        "qps_pre_kill": round(qps_pre, 2),
        "qps_post_rejoin": round(qps_post, 2),
        "offered_qps_during_outage": round(rate, 1),
        "outage_s": round(marks["rejoin"] - marks["kill"], 3),
        "outage_requests": len(outage),
        "outage_shed_fraction": round(outage_shed, 4),
        "chaos_requests": len(out_chaos),
        "deaths": int(deaths3),
        "resurrections": int(resurrections3),
        "flight_dumps": len(observer.flight_dumps),
        "lost_spans_recovered": int(sum(
            d.get("lost_spans_recovered", 0) for d in observer.flight_dumps
        )),
        "platform": platform,
    })


def _bench_fleet_chaos_matrix(table_dtype: str = "f32") -> None:
    """Partition-tolerance chaos matrix (``--mode fleet --chaos-matrix``
    — the ISSUE 19 acceptance sweep).

    Five deterministic network-fault cells against supervised 2-replica
    SUBPROCESS fleets, each injected through the seeded transport shim
    (``serving/netfault.py``), plus the capacity-boundary background-
    rebuild leg:

    - ``partition_heal``  — both-way partition SHORTER than the lease:
      the replica rejoins silently (zero deaths, lease misses counted);
    - ``partition_lease`` — partition PAST the lease: death declared
      with cause ``lease``, canary-gated resurrection after heal;
    - ``zombie_fenced``   — seeded frame drops force timeout/resend, and
      a generation-ratcheted child (the resurrection race, distilled)
      must have its stale-generation answer FENCED, never served;
    - ``duplicate``       — every data frame duplicated both ways: the
      extra responses are fenced by seq, each request served once;
    - ``slow_replica``    — byte-rate throttle + per-frame delay: slow
      is not dead (zero deaths, zero false resurrections).

    Every cell bars ZERO lost futures and per-response parity vs the
    host oracle (a double-served or cross-wired response breaks parity;
    the fence counters prove the stale answers existed and were
    discarded).  The rebuild leg grows the vocabulary PAST the serving
    tables' headroom under live traffic: ``rollout_with_rebuild`` must
    cross the capacity boundary with zero shed/lost requests and zero
    parent-side recompiles."""
    import dataclasses as _dc
    import threading as _threading
    import time as _time

    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    from photon_tpu.game.lowp import parity_tol_for
    from photon_tpu.game.model import GameModel, RandomEffectModel
    from photon_tpu.serving import (
        AdmissionPolicy,
        ReplicaDeadError,
        ServingFleet,
        SupervisorPolicy,
        TrafficSpec,
        generate_traffic,
        host_score_request,
        replay_open_loop,
        request_spec_for_dataset,
    )
    from photon_tpu.serving.netfault import (
        LinkRule,
        NetFaultPlan,
        partition,
        set_net_plan,
    )
    from photon_tpu.telemetry import TelemetrySession

    platform, model, data = _serving_fixture()
    parity_bound = 1e-3 if table_dtype == "f32" else parity_tol_for(
        table_dtype
    )
    spec = request_spec_for_dataset(model, data)
    n_requests = 60 if platform == "cpu" else 200
    cells: dict = {}

    def counter_sum(session, name, **labels):
        return sum(
            m["value"] for m in session.registry.snapshot()["counters"]
            if m["name"] == name and all(
                m["labels"].get(k) == v for k, v in labels.items()
            )
        )

    def check_parity(outcomes, cell, ref_model=None):
        m = ref_model if ref_model is not None else model
        worst = 0.0
        for out in outcomes:
            if out.status != "ok":
                continue
            want = host_score_request(m, out.item.request)
            worst = max(worst, float(np.max(np.abs(
                np.asarray(out.scores, np.float64) - want
            ))))
        if worst > parity_bound:
            raise AssertionError(
                f"chaos cell {cell}: served/host parity {worst:.2e} > "
                f"{parity_bound:g} — a double-served or cross-wired "
                "response leaked through"
            )
        return worst

    def assert_none_lost(outcomes, cell):
        lost = [o for o in outcomes if o.status == "error"]
        if lost:
            raise AssertionError(
                f"chaos cell {cell}: LOST {len(lost)} futures (first: "
                f"{lost[0].reason})"
            )

    def rewire(fleet):
        """Close every replica's parent-side sockets: the next exchange's
        silent reconnect dials back through ``maybe_shim``, so the links
        pick up (or drop) the installed plan without restarting children."""
        for r in fleet.replicas:
            sc = getattr(r, "scorer", None)
            for ch in ("_data", "_ctrl"):
                s = getattr(sc, ch, None)
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass

    def make_fleet(session, *, lease_s, probe_deadline_s):
        set_net_plan(None)
        fleet = ServingFleet(
            model, replicas=2, request_spec=spec, backend="subprocess",
            max_batch=64, max_delay_s=0.001, telemetry=session,
            admission=AdmissionPolicy(safety=2.0), table_dtype=table_dtype,
        ).warmup()
        fleet.supervise(SupervisorPolicy(
            probe_interval_s=0.1, probe_deadline_s=probe_deadline_s,
            hang_timeout_s=120.0, lease_s=lease_s,
            respawn_base_s=0.05, max_deaths=10,
        ))
        # Tight exchange timeout: a black-holed frame resolves in ~0.25s
        # resends, so the cell's fault window dominates its wall clock.
        for r in fleet.replicas:
            r.scorer.exchange_timeout_s = 0.25
        return fleet

    def traffic_for(seed, requests=n_requests, qps=25.0):
        # No per-request deadline: chaos cells bar exactly-once delivery,
        # not latency — a deadline would let the admission controller shed
        # the very requests whose survival is under test.
        return generate_traffic(data, model, TrafficSpec(
            requests=requests, mean_rows=8.0, max_rows=64,
            popularity="powerlaw", alpha=1.1, ramp="flat",
            target_qps=qps, seed=seed,
        ))

    # ---- cell 1: partition-then-heal-WITHIN-lease (silent rejoin) ----------
    session = TelemetrySession("chaos-partition-heal")
    fleet = make_fleet(session, lease_s=3.0, probe_deadline_s=1.0)
    try:
        plan = NetFaultPlan([partition("r0:*", 0.4, 1.2)], seed=11)
        set_net_plan(plan)
        rewire(fleet)
        out = replay_open_loop(fleet.submit, traffic_for(1), timeout_s=180.0)
        _time.sleep(0.5)  # a post-heal supervisor pass renews the lease
        assert_none_lost(out, "partition_heal")
        worst = check_parity(out, "partition_heal")
        deaths = counter_sum(session, "serving.replica_deaths")
        misses = counter_sum(session, "serving.lease_probe_misses")
        if deaths:
            raise AssertionError(
                f"partition_heal: {deaths} death(s) declared inside the "
                "lease window — the lease did not tolerate the partition"
            )
        if not misses:
            raise AssertionError(
                "partition_heal: zero lease probe misses counted — the "
                "partition never actually hit the control channel"
            )
        if not fleet.replicas[0].alive:
            raise AssertionError("partition_heal: r0 did not rejoin")
        cells["partition_heal"] = {
            "requests": len(out), "lease_misses": int(misses),
            "partitioned_frames": plan.total("partitioned"),
            "resends": int(counter_sum(
                session, "serving.exchange_resends"
            )),
            "parity": worst,
        }
    finally:
        set_net_plan(None)
        fleet.close()

    # ---- cell 2: partition PAST the lease (death + resurrection) -----------
    session = TelemetrySession("chaos-partition-lease")
    fleet = make_fleet(session, lease_s=1.0, probe_deadline_s=0.5)
    try:
        plan = NetFaultPlan([partition("r0:*", 0.3, 4.0)], seed=12)
        set_net_plan(plan)
        rewire(fleet)
        out = replay_open_loop(
            fleet.submit, traffic_for(2, qps=15.0), timeout_s=180.0
        )
        t0 = _time.monotonic()
        while (not fleet.replicas[0].alive
               and _time.monotonic() - t0 < 120.0):
            _time.sleep(0.05)
        assert_none_lost(out, "partition_lease")
        worst = check_parity(out, "partition_lease")
        lease_deaths = counter_sum(
            session, "serving.replica_deaths", cause="lease"
        )
        resurrections = counter_sum(
            session, "serving.replica_resurrections"
        )
        if lease_deaths < 1:
            raise AssertionError(
                "partition_lease: no death with cause 'lease' — expiry "
                "did not declare"
            )
        if resurrections < 1 or not fleet.replicas[0].alive:
            raise AssertionError(
                "partition_lease: the expired replica never resurrected "
                "after the heal"
            )
        cells["partition_lease"] = {
            "requests": len(out), "lease_deaths": int(lease_deaths),
            "resurrections": int(resurrections), "parity": worst,
        }
    finally:
        set_net_plan(None)
        fleet.close()

    # ---- cells 3-5 share one fleet (generous lease: no deaths expected) ----
    session = TelemetrySession("chaos-frames")
    fleet = make_fleet(session, lease_s=60.0, probe_deadline_s=5.0)
    try:
        # -- duplicate-frames: every data frame duplicated, both ways.
        plan = NetFaultPlan(
            [LinkRule(link="r0:data", direction="both", dup_p=1.0)],
            seed=13,
        )
        set_net_plan(plan)
        rewire(fleet)
        out = replay_open_loop(fleet.submit, traffic_for(3), timeout_s=180.0)
        assert_none_lost(out, "duplicate")
        worst = check_parity(out, "duplicate")
        if plan.total("duplicated") < 1:
            raise AssertionError("duplicate: the dup rule never fired")
        fenced_seq = counter_sum(
            session, "serving.fenced_responses", reason="stale_seq"
        )
        cells["duplicate"] = {
            "requests": len(out),
            "duplicated_frames": plan.total("duplicated"),
            "fenced_stale_seq": int(fenced_seq), "parity": worst,
        }

        # -- slow-replica: throttle + delay; slow is NOT dead.
        plan = NetFaultPlan([LinkRule(
            link="r0:data", direction="both", delay_s=0.03,
            rate_bytes_per_s=2e6,
        )], seed=14)
        set_net_plan(plan)
        rewire(fleet)
        out = replay_open_loop(
            fleet.submit, traffic_for(4, qps=15.0), timeout_s=180.0
        )
        assert_none_lost(out, "slow_replica")
        worst = check_parity(out, "slow_replica")
        if plan.total("throttled") < 1:
            raise AssertionError("slow_replica: the throttle never fired")
        if counter_sum(session, "serving.replica_deaths"):
            raise AssertionError(
                "slow_replica: a merely-slow replica was declared dead"
            )
        if counter_sum(session, "serving.replica_resurrections"):
            raise AssertionError(
                "slow_replica: false-positive resurrection"
            )
        cells["slow_replica"] = {
            "requests": len(out),
            "throttled_frames": plan.total("throttled"),
            "parity": worst,
        }

        # -- zombie-fenced: seeded drops force timeout/resend; then the
        # distilled resurrection race — the child ratcheted PAST the
        # router's recorded generation must have its answer fenced.
        plan = NetFaultPlan(
            [LinkRule(link="r0:data", direction="both", drop_p=0.3)],
            seed=15,
        )
        set_net_plan(plan)
        rewire(fleet)
        out = replay_open_loop(fleet.submit, traffic_for(5), timeout_s=180.0)
        assert_none_lost(out, "zombie_fenced")
        worst = check_parity(out, "zombie_fenced")
        resends = counter_sum(session, "serving.exchange_resends")
        if plan.total("dropped") < 1 or resends < 1:
            raise AssertionError(
                "zombie_fenced: drops/resends never fired "
                f"(dropped={plan.total('dropped')}, resends={resends})"
            )
        set_net_plan(None)
        rewire(fleet)
        r0 = fleet.replicas[0]
        r0.scorer.ping(10.0, gen=r0.generation + 3)  # child ratchets ahead
        try:
            r0.scorer.score_batch(traffic_for(6, requests=1).items[0].request)
            raise AssertionError(
                "zombie_fenced: a stale-generation response was SERVED"
            )
        except ReplicaDeadError:
            pass
        fenced_gen = counter_sum(
            session, "serving.fenced_responses", reason="stale_gen"
        )
        if fenced_gen < 1:
            raise AssertionError(
                "zombie_fenced: the stale-generation answer was not "
                "counted as fenced"
            )
        # Re-sync the ratchet we injected so teardown sees a sane replica.
        r0.generation += 3
        r0.scorer.generation = r0.generation
        cells["zombie_fenced"] = {
            "requests": len(out), "dropped_frames": plan.total("dropped"),
            "resends": int(resends), "fenced_stale_gen": int(fenced_gen),
            "parity": worst,
        }
    finally:
        set_net_plan(None)
        fleet.close()

    # ---- rebuild leg: growth past headroom, zero-downtime cutover ----------
    # The grown model is built BEFORE the compile listener attaches:
    # with_entities scatters on device (legitimate one-time compiles that
    # are the MODEL's, not the serving path's).
    coords = dict(model.coordinates)
    for name, coord in model.coordinates.items():
        if isinstance(coord, RandomEffectModel):
            keys = np.asarray(coord.keys)
            extra = max(4, len(keys))  # past the factor-1 headroom (E+1)
            if keys.dtype.kind in "iu":
                new = keys.max() + np.arange(
                    1, extra + 1, dtype=np.int64
                ).astype(keys.dtype)
            else:
                new = np.array([f"grown-{i:06d}" for i in range(extra)])
            coords[name] = coord.with_entities(
                np.unique(np.concatenate([keys, new]))
            )
    grown = GameModel(coordinates=coords, task_type=model.task_type)
    import jax as _jax
    _jax.block_until_ready([
        c.table for c in grown.coordinates.values()
        if isinstance(c, RandomEffectModel)
    ])

    compile_events: list = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    session = TelemetrySession("chaos-rebuild")
    set_net_plan(None)
    fleet = ServingFleet(
        model, replicas=2, request_spec=spec, backend="subprocess",
        max_batch=64, max_delay_s=0.001, telemetry=session,
        admission=AdmissionPolicy(safety=2.0), table_dtype=table_dtype,
        table_capacity_factor=1,
    ).warmup()
    fleet.supervise(SupervisorPolicy(
        probe_interval_s=0.2, probe_deadline_s=60.0, lease_s=30.0,
    ))
    live = traffic_for(7, requests=max(40, n_requests)).items
    stop = _threading.Event()
    served: list = []
    errors: list = []

    def client(tid):
        i = tid
        while not stop.is_set():
            req = live[i % len(live)].request
            try:
                served.append((req, fleet.score(req)))
            except Exception as e:  # noqa: BLE001 — audited below
                errors.append(e)
            i += 1
            _time.sleep(0.02)

    threads = [
        _threading.Thread(target=client, args=(t,), daemon=True)
        for t in range(2)
    ]
    jax.monitoring.register_event_listener(listener)
    try:
        for t in threads:
            t.start()
        _time.sleep(0.3)
        rebuilt = fleet.rollout_with_rebuild(grown)
        _time.sleep(0.5)  # post-cutover traffic rides the new tables
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        monitoring_src._unregister_event_listener_by_callback(listener)
    try:
        if not rebuilt:
            raise AssertionError(
                "rebuild leg: the grown model fit the old tables — the "
                "growth did not cross the capacity boundary"
            )
        if errors:
            raise AssertionError(
                f"rebuild leg: {len(errors)} shed/lost request(s) during "
                f"the background rebuild (first: {errors[0]!r})"
            )
        if compile_events:
            raise AssertionError(
                f"rebuild leg: {len(compile_events)} parent-side compile "
                f"event(s) (first: {compile_events[0]}) — the surviving "
                "path recompiled"
            )
        if counter_sum(session, "serving.fleet_rebuilds") != 1:
            raise AssertionError("rebuild leg: fleet_rebuilds != 1")
        # Post-cutover responses during the window must match ONE of the
        # two published models (old before the atomic cut, grown after).
        for req, scores in served[:: max(1, len(served) // 64)]:
            worst = min(
                float(np.abs(np.asarray(scores, np.float64)
                             - host_score_request(m, req)).max())
                for m in (model, grown)
            )
            if worst > parity_bound:
                raise AssertionError(
                    f"rebuild leg: mixed-model response ({worst:.2e})"
                )
        # The grown entities actually serve from the rebuilt tables.
        from photon_tpu.serving.supervisor import probe_request_for
        probe = probe_request_for(grown, spec, rows=4, seed=9)
        got = fleet.score(probe)
        want = host_score_request(grown, probe)
        if float(np.abs(np.asarray(got, np.float64) - want).max()) \
                > parity_bound:
            raise AssertionError(
                "rebuild leg: grown-vocabulary probe parity broke"
            )
        cells["rebuild"] = {
            "served_during_rebuild": len(served),
            "rebuilds": int(counter_sum(
                session, "serving.replica_rebuilds"
            )),
        }
    finally:
        fleet.close()

    _emit("game_fleet_chaos_matrix", float(len(cells)), "cells passed", {
        "backend": "subprocess",
        "table_dtype": table_dtype,
        "platform": platform,
        **{f"{cell}_{k}": (round(v, 8) if isinstance(v, float) else v)
           for cell, info in cells.items() for k, v in info.items()},
    })


def _tenant_clone(model, seed: int):
    """A tenant model for the multi-model arena bench: SAME coordinate
    structure and entity vocabulary as ``model`` (one arena layout hosts
    them all), freshly seeded coefficient tables (so per-tenant parity
    actually distinguishes the tenants)."""
    import dataclasses as _dc

    from photon_tpu.game.model import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, model_for_task

    rng = np.random.default_rng(seed)
    coords = {}
    for name, coord in model.coordinates.items():
        if isinstance(coord, RandomEffectModel):
            coords[name] = _dc.replace(
                coord,
                table=rng.standard_normal(
                    np.asarray(coord.table).shape
                ).astype(np.float32),
            )
        else:
            dim = int(np.asarray(coord.coefficients.means).shape[0])
            coords[name] = FixedEffectModel(
                model_for_task(model.task_type, Coefficients(
                    rng.standard_normal(dim).astype(np.float32)
                )),
                coord.shard_name,
            )
    return GameModel(coordinates=coords, task_type=model.task_type)


def _bench_fleet_multimodel(table_dtype: str = "f32",
                            n_models: int = 8) -> None:
    """Multi-model arena macro-bench (``--mode fleet --models N`` — the
    ISSUE 18 tentpole's measurement).

    Hosts ``n_models`` tenant models in ONE fleet replica — one shared
    gather-table arena allocation, one compiled bucket ladder — and
    serves seeded mixed-tenant traffic (hash-of-user split arms route
    each request to its tenant).  In-bench acceptance (raises on
    violation):

    - ZERO jax compile events across the whole mixed-tenant serve (model
      identity is a per-request offset vector, never a program key);
    - per-tenant score parity vs a SOLO single-model ``GameScorer`` of
      the same storage dtype ≤ the codec's declared bound on every
      sampled served request;
    - arena bytes ≤ 1.15x the sum of the tenants' solo table bytes (the
      shared allocation carries headroom, not duplication);
    - the seeded split assignment is deterministic (regenerating the
      stream reproduces every arm) and every tenant receives traffic.
    """
    import jax.monitoring
    from jax._src import monitoring as monitoring_src

    from photon_tpu.game.lowp import parity_tol_for
    from photon_tpu.serving import (
        AdmissionPolicy,
        ServingFleet,
        TrafficSpec,
        generate_traffic,
        request_spec_for_dataset,
        run_closed_loop_outcomes,
    )
    from photon_tpu.serving.scorer import GameScorer
    from photon_tpu.telemetry import TelemetrySession

    platform, base_model, data = _serving_fixture()
    models = {
        f"m{i}": _tenant_clone(base_model, seed=100 + i)
        for i in range(n_models)
    }
    parity_bound = parity_tol_for(table_dtype)
    spec = request_spec_for_dataset(base_model, data)
    max_batch, clients = 128, 8
    n_requests = 600 if platform != "cpu" else 240
    splits = {mid: 1.0 / n_models for mid in models}
    tspec = TrafficSpec(
        requests=n_requests, mean_rows=8.0, max_rows=max_batch,
        popularity="powerlaw", alpha=1.1, storm_frac=0.0, seed=0,
        splits=splits,
    )
    traffic = generate_traffic(data, base_model, tspec)
    # Split determinism + coverage: the same seed reproduces every arm,
    # and the uniform split actually reaches every tenant.
    arms = [item.arm for item in traffic.items]
    if arms != [item.arm for item in
                generate_traffic(data, base_model, tspec).items]:
        raise AssertionError("seeded split arms are not deterministic")
    arm_counts = {mid: arms.count(mid) for mid in models}
    missing = [mid for mid, c in arm_counts.items() if c == 0]
    if missing:
        raise AssertionError(
            f"tenants {missing} received no traffic from the uniform split"
        )

    # Solo baseline: ONE single-model scorer, swapped per tenant — its
    # scores are the isolation oracle, its table bytes the per-tenant
    # allocation the arena must not exceed in sum.
    solo_session = TelemetrySession("bench-multimodel-solo")
    solo = GameScorer(
        models["m0"], request_spec=spec, max_batch=max_batch,
        telemetry=solo_session, table_dtype=table_dtype,
    ).warmup()
    solo_bytes = 0
    solo_scores: dict = {}
    sample_per_tenant = 15
    for mid, m in models.items():
        if mid != "m0":
            solo.swap_model(m)
        solo_bytes += sum(
            leaf.nbytes
            for leaf in jax.tree_util.tree_leaves(solo._tables)
        )
        picked = [
            item for item in traffic.items if item.arm == mid
        ][:sample_per_tenant]
        solo_scores[mid] = {
            id(item): solo.score_batch(item.request) for item in picked
        }

    session = TelemetrySession("bench-fleet-multimodel")
    fleet = ServingFleet(
        None, models=models, replicas=1, request_spec=spec,
        max_batch=max_batch, max_delay_s=0.001, telemetry=session,
        table_dtype=table_dtype, admission=AdmissionPolicy(safety=2.0),
    ).warmup()
    arena = fleet.replicas[0].scorer.arena
    arena_bytes = arena.arena_bytes()
    compiled_programs = fleet.compilations
    if arena_bytes > 1.15 * solo_bytes:
        raise AssertionError(
            f"arena allocates {arena_bytes} bytes for {n_models} tenants "
            f"> 1.15x the {solo_bytes} bytes their solo tables sum to"
        )

    compile_events: list = []

    def listener(event, **kwargs):
        if "compile" in event:
            compile_events.append(event)

    def factory(tid):
        return lambda item: fleet.score(item.request)

    jax.monitoring.register_event_listener(listener)
    try:
        outcomes, wall = run_closed_loop_outcomes(
            factory, traffic.items, clients=clients
        )
    finally:
        monitoring_src._unregister_event_listener_by_callback(listener)
        fleet.close()
    bad = [o for o in outcomes if o.status != "ok"]
    if bad:
        raise AssertionError(
            f"{len(bad)} mixed-tenant requests failed/shed; first: "
            f"{bad[0].reason}"
        )
    if compile_events:
        raise AssertionError(
            f"{len(compile_events)} jax compile events across the "
            f"{n_models}-tenant mixed serve (first: {compile_events[0]}) "
            "— model identity leaked into a program key"
        )
    worst, compared = 0.0, 0
    for out in outcomes:
        want = solo_scores.get(out.item.arm, {}).get(id(out.item))
        if want is None:
            continue
        compared += 1
        worst = max(worst, float(np.max(np.abs(
            np.asarray(out.scores, np.float64)
            - np.asarray(want, np.float64)
        ))))
    if compared < n_models:
        raise AssertionError(
            f"parity sample covered only {compared} requests across "
            f"{n_models} tenants"
        )
    if worst > parity_bound:
        raise AssertionError(
            f"arena/solo per-tenant parity broke ({table_dtype} tables): "
            f"max |delta| {worst:.2e} > {parity_bound:g} over {compared} "
            "sampled requests"
        )
    qps = len(outcomes) / wall if wall > 0 else 0.0
    _emit("game_fleet_multimodel_qps", qps, "req/s", {
        "models": n_models,
        "requests": len(outcomes),
        "clients": clients,
        "table_dtype": table_dtype,
        "arena_bytes": int(arena_bytes),
        "solo_bytes_sum": int(solo_bytes),
        "bytes_ratio": round(arena_bytes / solo_bytes, 4),
        "compiled_programs": compiled_programs,
        "parity_sampled": compared,
        "max_parity_delta": worst,
        "arm_counts": arm_counts,
        "platform": platform,
    })


def _bench_online() -> None:
    """Online-learning refresh micro-bench (``--mode online`` — ISSUE 15).

    Builds a synthetic GAME fixture, fits + serves it on a 2-replica
    fleet, then drives TWO online refresh rounds through the
    :class:`~photon_tpu.online.service.OnlineLearningService` — each
    appending rows for BOTH existing and new entities — measuring the
    append→published refresh latency (``game_online_refresh_secs``, lower
    is better; the second round is the steady-state number: the first pays
    the grown-shape fixed-effect compile).

    Asserts IN-BENCH:
    - refreshed model ≡ a full offline retrain on the merged dataset
      (rebuilt-from-scratch layouts, same warm start/iterations) to ≤1e-4
      on scores — the in-place-growth data path changes NOTHING;
    - zero full random-effect layout rebuilds
      (``estimator.device_data_rebuilds{kind=random}`` == 0) and >0 rows
      grown in place;
    - zero serving-side compile events across both publishes
      (``fleet.compilations`` unchanged after warmup).
    """
    import numpy as np

    from photon_tpu.data.synthetic import make_game_data
    from photon_tpu.game.data import DenseShard, GameDataset
    from photon_tpu.game.estimator import GameEstimator
    from photon_tpu.game.model import GameModel
    from photon_tpu.online import (
        OnlineLearningService,
        QueueFeed,
        RefreshPolicy,
    )
    from photon_tpu.serving.fleet import ServingFleet
    from photon_tpu.serving.scorer import request_spec_for_dataset
    from photon_tpu.telemetry import TelemetrySession

    platform, _sizes, _data, config = _game_bench_fixture(
        n_random_coords=2, descent_iterations=3
    )
    task = "linear_regression"

    def cut(n_ent, seed, keep=None):
        raw = make_game_data(n_ent, 6, 32, 8, seed=seed, n_random_coords=2)
        sel = (
            slice(None) if keep is None
            else keep(raw["entity_ids"]["re0"])
        )
        return GameDataset.create(
            raw["label"][sel],
            {
                "global": DenseShard(raw["x_fixed"][sel]),
                "re0": DenseShard(raw["x_random"]["re0"][sel]),
                "re1": DenseShard(raw["x_random"]["re1"][sel]),
            },
            id_columns={
                "re0": raw["entity_ids"]["re0"][sel],
                "re1": raw["entity_ids"]["re1"][sel],
            },
        )

    n_entities = 2000
    base = cut(n_entities, 0)
    session = TelemetrySession("bench-online")
    estimator = GameEstimator(task, base, telemetry=session)
    model0 = estimator.fit([config])[0].model
    fleet = ServingFleet(
        model0, replicas=2,
        request_spec=request_spec_for_dataset(model0, base),
        telemetry=session, table_capacity_factor=2,
    ).warmup()
    compiles0 = fleet.compilations
    feed = QueueFeed()
    service = OnlineLearningService(
        estimator, config, feed, model=model0, fleet=fleet,
        policy=RefreshPolicy(refresh_iterations=3), telemetry=session,
    )

    latencies = []
    grow = int(n_entities * 1.05)
    try:
        # Round 1: parity round — its merged dataset and refreshed model
        # feed the full-retrain oracle below.
        feed.append(cut(
            grow, 1,
            keep=lambda ids: (ids < n_entities // 10)
            | (ids >= n_entities),
        ))
        result1 = service.refresh_once()
        assert result1 is not None and result1.published
        latencies.append(result1.latency_s)
        merged1 = estimator.training_data
        # Round 2: steady-state latency (round 1 pays the grown-shape
        # fixed-effect compile; the bins themselves never recompile).
        feed.append(cut(
            grow, 2,
            keep=lambda ids: (ids < n_entities // 10)
            | (ids >= n_entities),
        ))
        result2 = service.refresh_once()
        assert result2 is not None and result2.published
        latencies.append(result2.latency_s)
        assert fleet.compilations == compiles0, (
            f"serving-side compiles during online publish: "
            f"{fleet.compilations - compiles0}"
        )
    finally:
        fleet.close()

    # Full-retrain oracle for round 1: rebuilt-from-scratch layouts over
    # the SAME merged dataset, warm-started from the same grown serving
    # model, same iteration budget, no locks — the in-place-growth data
    # path must change nothing.
    fresh = GameEstimator(task, merged1)
    warm_coords = {}
    for name, m in model0.coordinates.items():
        cc = config.coordinates[name]
        if hasattr(m, "with_entities"):
            warm_coords[name] = m.with_entities(
                fresh.device_layout(cc).dataset.keys
            )
        else:
            warm_coords[name] = m
    full_model = fresh.fit(
        [config], initial_model=GameModel(warm_coords, task)
    )[0].model
    parity = float(np.abs(
        result1.model.score(merged1) - full_model.score(merged1)
    ).max())
    assert parity <= 1e-4, (
        f"online refresh diverged from the full offline retrain: {parity}"
    )

    def counter_total(name, **labels):
        return sum(
            m["value"] for m in session.registry.snapshot()["counters"]
            if m["name"] == name
            and all((m.get("labels") or {}).get(k) == v
                    for k, v in labels.items())
        )

    random_rebuilds = counter_total(
        "estimator.device_data_rebuilds", kind="random"
    )
    rows_in_place = counter_total("onboard.rows_in_place")
    assert random_rebuilds == 0, random_rebuilds
    assert rows_in_place > 0

    _emit("game_online_refresh_secs", latencies[-1], "s", {
        "rows_base": base.num_examples,
        "rows_ingested": int(counter_total("online.rows_ingested")),
        "entities": n_entities,
        "rounds": 2,
        "first_round_secs": round(latencies[0], 4),
        "steady_round_secs": round(latencies[-1], 4),
        "refresh_iterations": 3,
        "parity_vs_full_retrain": parity,
        "rows_grown_in_place": int(rows_in_place),
        "rows_migrated": int(counter_total("onboard.rows_migrated")),
        "entities_new": int(counter_total("onboard.entities_new")),
        "random_layout_rebuilds": int(random_rebuilds),
        "serving_compiles_during_publish": fleet.compilations - compiles0,
        "platform": platform,
    })


def _bench_recovery() -> None:
    """Checkpoint write/restore overhead micro-bench (``--mode recovery``).

    Fits the shared synthetic GAME fixture four ways on one estimator:
    plain (no checkpointing), with SYNCHRONOUS per-outer-iteration descent
    checkpoints (``--checkpoint-async off`` — the inline serialize + fsync
    + rename the loop used to pay), with the ASYNC publisher (staging on
    the loop, publish behind the next iteration's compute), and resumed
    from the completed checkpoint (pure load + rebuild, no solves).  Emits
    ``game_checkpoint_secs`` (mean loop-side write seconds per iteration,
    sync mode — the insurance premium baseline) and
    ``game_checkpoint_overhead_pct`` — the async fit's measured
    per-iteration checkpoint premium as a percentage of the sync fit's
    (the ISSUE 5 acceptance number: <= 20 means the publisher hides at
    least 80% of the premium).
    """
    import shutil
    import tempfile

    from photon_tpu.game.estimator import GameEstimator
    from photon_tpu.telemetry import TelemetrySession

    iters = 3
    platform, sizes, data, config = _game_bench_fixture(
        n_random_coords=2, descent_iterations=iters
    )
    n_entities, rows_mean = sizes
    tmp = tempfile.mkdtemp(prefix="photon-bench-recovery-")
    try:
        session = TelemetrySession("bench-recovery")
        estimator = GameEstimator(
            "logistic_regression", data, telemetry=session
        )
        estimator.fit([config])  # warm-up: compile + device-data upload
        t0 = time.perf_counter()
        estimator.fit([config])
        plain = time.perf_counter() - t0

        ckpt_sync = os.path.join(tmp, "ckpt-sync")
        t0 = time.perf_counter()
        estimator.fit([config], checkpoint_dir=ckpt_sync,
                      checkpoint_async="off")
        with_sync = time.perf_counter() - t0
        # Snapshot the mean NOW: the histogram is live on the shared
        # session, and the async fit below observes its own near-zero
        # loop-side write times into it (same reason saves is int()-ed).
        sync_write_mean = float(
            session.histogram("checkpoint.write_seconds").mean or 0.0
        )
        sync_writes = int(session.counter("checkpoint.saves").value)

        ckpt_async = os.path.join(tmp, "ckpt-async")
        t0 = time.perf_counter()
        estimator.fit([config], checkpoint_dir=ckpt_async,
                      checkpoint_async="on")
        with_async = time.perf_counter() - t0

        t0 = time.perf_counter()
        estimator.fit([config], checkpoint_dir=ckpt_sync, resume="auto")
        restore = time.perf_counter() - t0

        # Elastic restore: the SAME checkpoint restored in a subprocess
        # under a forced 2-device CPU mesh — a different device count than
        # wrote it (checkpoints are mesh-shape portable; the restored
        # tables re-pad/re-shard onto the new mesh).  Subprocess because a
        # device count cannot change after jax initializes in-process.
        resharded_restore = None
        worker_err = None
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import bench; bench._resharded_restore_worker"
                 f"({ckpt_sync!r}, {n_entities}, {rows_mean}, {iters})"],
                capture_output=True, text=True, timeout=900, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if proc.returncode == 0 and proc.stdout.strip():
                payload = json.loads(proc.stdout.strip().splitlines()[-1])
                resharded_restore = float(payload["restore_secs"])
            else:
                worker_err = (proc.stderr or "worker failed").strip()[-500:]
        except Exception as ex:  # noqa: BLE001 — sub-metric isolation
            worker_err = f"{type(ex).__name__}: {ex}"[:500]

        sync_premium = max(with_sync - plain, 0.0)
        async_premium = max(with_async - plain, 0.0)
        overhead_pct = (
            100.0 * async_premium / sync_premium if sync_premium > 0 else 0.0
        )
        detail = {
            "rows": data.num_examples,
            "entities": n_entities,
            "coordinates": 3,
            "descent_iterations": iters,
            "plain_fit_seconds": round(plain, 4),
            "sync_fit_seconds": round(with_sync, 4),
            "async_fit_seconds": round(with_async, 4),
            "sync_premium_seconds": round(sync_premium, 4),
            "async_premium_seconds": round(async_premium, 4),
            "restore_seconds": round(restore, 4),
            "checkpoint_writes": sync_writes,
            "publish_lag_mean_s": round(
                session.histogram("checkpoint.publish_lag_s").mean or 0.0, 4
            ),
            "blocked_mean_s": round(
                session.histogram("checkpoint.blocked_s").mean or 0.0, 4
            ),
            "platform": platform,
        }
        _emit("game_checkpoint_secs", sync_write_mean, "s/iter", detail)
        _emit("game_checkpoint_overhead_pct", overhead_pct, "%", detail)
        if resharded_restore is not None:
            _emit("game_resharded_restore_secs", resharded_restore, "s", {
                **detail,
                "restore_devices": 2,
                "restore_platform": "cpu (forced 2-device)",
            })
        else:
            _emit("game_resharded_restore_error", 0.0, "error", {
                "error": worker_err or "unknown",
            })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _resharded_restore_worker(ckpt_dir: str, n_entities: int,
                              rows_mean: int, iters: int) -> None:
    """Subprocess entry of the ``--mode recovery`` resharded-restore
    sub-metric: rebuild the recovery fixture, construct a mesh over this
    process's (forced, different) device count, and restore the completed
    checkpoint chain onto it — no solves, pure load + re-pad + re-shard +
    rebuild.  Prints one JSON line ``{"restore_secs": ...}``."""
    import jax

    from photon_tpu.game.estimator import GameEstimator
    from photon_tpu.parallel.mesh import create_mesh

    platform, _, data, config = _game_bench_fixture(
        n_random_coords=2, descent_iterations=iters,
        sizes=(n_entities, rows_mean),
    )
    assert platform == "cpu", "resharded restore is a forced-CPU check"
    mesh = create_mesh()
    estimator = GameEstimator("logistic_regression", data, mesh=mesh)
    t0 = time.perf_counter()
    estimator.fit([config], checkpoint_dir=ckpt_dir, resume="auto")
    secs = time.perf_counter() - t0
    print(json.dumps({
        "restore_secs": round(secs, 4), "devices": len(jax.devices()),
    }))


def _generate_stream_files(
    out_dir: str, total_rows: int, n_files: int, k: int, d: int, seed: int = 0
) -> list:
    """Generate LIBSVM part files for the streaming-scale bench (vectorized
    formatting; cached by a manifest so repeat runs skip the write).

    Feature ids are drawn one-per-stride (id_j in [j*d/k, (j+1)*d/k)), so
    rows are ascending-unique by construction — vectorizable, and shaped
    like a hashed/bucketed production feature space."""
    import json as _json

    manifest = os.path.join(out_dir, "manifest.json")
    spec = {"total_rows": total_rows, "n_files": n_files, "k": k, "d": d,
            "seed": seed}
    if os.path.exists(manifest):
        try:
            with open(manifest) as f:
                if _json.load(f) == spec:
                    return sorted(
                        os.path.join(out_dir, f) for f in os.listdir(out_dir)
                        if f.startswith("part-")
                    )
        except Exception:  # noqa: BLE001 — stale manifest: regenerate
            pass
    os.makedirs(out_dir, exist_ok=True)
    # Invalidate BEFORE mutating parts: a crash mid-generation must not
    # leave an old manifest validating a half-written part set.
    if os.path.exists(manifest):
        os.unlink(manifest)
    for f in os.listdir(out_dir):
        if f.startswith("part-"):
            os.unlink(os.path.join(out_dir, f))
    rows_per_file = -(-total_rows // n_files)
    stride = d // k
    rng = np.random.default_rng(seed)
    w_true = (rng.standard_normal(k) * 0.5).astype(np.float32)  # one per stride
    files = []
    for fi in range(n_files):
        n = min(rows_per_file, total_rows - fi * rows_per_file)
        if n <= 0:
            break
        ids = (
            np.arange(k, dtype=np.int64)[None, :] * stride
            + rng.integers(0, stride, size=(n, k))
            + 1  # libsvm ids are 1-based
        )
        vals = rng.standard_normal((n, k)).astype(np.float32)
        margin = vals @ w_true
        label = np.where(rng.random(n) < 1.0 / (1.0 + np.exp(-margin)), 1, -1)
        path = os.path.join(out_dir, f"part-{fi:05d}.libsvm")
        files.append(path)
        acc = np.char.mod("%d", label.astype(np.int64))
        for j in range(k):
            acc = np.char.add(acc, " ")
            acc = np.char.add(acc, np.char.add(
                np.char.mod("%d:", ids[:, j]), np.char.mod("%.4f", vals[:, j])
            ))
        with open(path, "w") as f:
            f.write("\n".join(acc.tolist()))
            f.write("\n")
    with open(manifest, "w") as f:
        _json.dump(spec, f)
    return files


def _stream_kernel_report() -> tuple:
    """(kernel, why) the streamed pass runs with — the VERDICT r5 item-3
    ask: a reader of the stream-scale line can state which kernel ran
    and why."""
    from photon_tpu.data.stream_layouts import stream_kernel, stream_kernel_why

    k = stream_kernel()
    return k, stream_kernel_why(k)


def _stream_scale() -> None:
    """Streaming-ingestion scale proof (VERDICT r3 item 3): stream
    PHOTON_STREAM_SCALE_ROWS (default 10M) generated LIBSVM rows
    file-at-a-time through the production streamed-objective path
    (LibsvmFileSource -> stream_chunks prefetch -> jitted per-chunk
    value+grad), report sustained rows/s, and assert peak RSS stays
    bounded (< PHOTON_STREAM_SCALE_RSS_GB, default 4) — host memory must
    not scale with dataset size.  Invoke: ``python bench.py --stream-scale``.
    """
    import resource

    import jax
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext
    from photon_tpu.data.streaming import LibsvmFileSource, StreamingObjective

    rss_cap_gb = float(os.environ.get("PHOTON_STREAM_SCALE_RSS_GB", "4"))
    stream_kernel_name, stream_kernel_why = _stream_kernel_report()
    t_gen = time.perf_counter()
    files, _, _, _, k, d = _stream_scale_spec()
    gen_s = time.perf_counter() - t_gen

    t_scan = time.perf_counter()
    source = LibsvmFileSource(files, intercept=True, feature_dim=d)
    scan_s = time.perf_counter() - t_scan
    objective = StreamingObjective(
        GlmObjective.create("logistic", RegularizationContext("l2", 1.0)),
        source.chunk_iter_factory,
    )
    w = jnp.zeros(source.dim, jnp.float32)
    # Pass 1 warms the per-chunk compilation; passes 2..P are the sustained
    # measurement (every L-BFGS iteration in production is one such pass).
    v, g = objective.value_and_grad(w)
    np.asarray(g)
    passes = 2
    t0 = time.perf_counter()
    for _ in range(passes):
        w2 = w - 1e-3 * g  # new point each pass: no result can be reused
        v, g = objective.value_and_grad(w2)
    np.asarray(g)
    wall = time.perf_counter() - t0
    rows_per_sec = passes * source.num_examples / wall
    # ru_maxrss is kilobytes on Linux but BYTES on macOS.
    rss_unit = 1e9 if sys.platform == "darwin" else 1e6
    peak_rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / rss_unit
    _emit("config5_stream_rows_per_sec", rows_per_sec, "rows/s", {
        "rows": source.num_examples,
        "files": len(files),
        "nnz_per_row": k,
        "dim": source.dim,
        "passes_timed": passes,
        "seconds_per_pass": round(wall / passes, 2),
        "metadata_scan_s": round(scan_s, 2),
        "generate_s": round(gen_s, 2),
        "final_value": float(v),
        # What actually ran (first chunk's measured selection) vs. what
        # the attach intended — a reader must be able to state the
        # operative kernel from this line alone (VERDICT r5 item 3).
        "kernel": objective.last_kernel or "autodiff",
        "kernel_attach": stream_kernel_name,
        "kernel_why": stream_kernel_why,
        "peak_rss_gb": round(peak_rss_gb, 3),
        "rss_cap_gb": rss_cap_gb,
        "rss_bounded": peak_rss_gb < rss_cap_gb,
        "platform": jax.devices()[0].platform,
    })
    if peak_rss_gb >= rss_cap_gb:
        raise RuntimeError(
            f"streaming pass peak RSS {peak_rss_gb:.2f} GB exceeds the "
            f"{rss_cap_gb:.0f} GB bound — host memory is scaling with data"
        )


# Worker for --stream-scale-mp: one streamed value+grad pass, CPU-pinned.
# argv: repo coordinator nproc pid data_dir out_path d.  With nproc=1 it is
# the single-process reference (no distributed init, no all_reduce) on the
# IDENTICAL platform and code path as the 2-process run — cross-backend
# float comparisons are structurally impossible.
_MP_STREAM_WORKER = r"""
import json, os, sys, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
sys.path.insert(0, sys.argv[1])
coordinator, nproc, pid, data_dir, out_path, d = (
    sys.argv[2], int(sys.argv[3]), int(sys.argv[4]), sys.argv[5],
    sys.argv[6], int(sys.argv[7])
)
import jax
jax.config.update("jax_platforms", "cpu")
if nproc > 1:
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=nproc, process_id=pid)
import jax.numpy as jnp
import numpy as np

from photon_tpu.core.objective import GlmObjective, RegularizationContext
from photon_tpu.data.streaming import (
    LibsvmFileSource, StreamingObjective, shard_files_for_process,
)

files = sorted(os.path.join(data_dir, f) for f in os.listdir(data_dir)
               if f.startswith("part-"))
source = LibsvmFileSource(files, intercept=True, feature_dim=d)
all_reduce = None
local = source
if nproc > 1:
    from jax.experimental import multihost_utils

    local = source.with_files(shard_files_for_process(files))

    def all_reduce(x):
        return multihost_utils.process_allgather(x).sum(axis=0)

obj = StreamingObjective(
    GlmObjective.create("logistic", RegularizationContext("l2", 1.0)),
    local.chunk_iter_factory, all_reduce=all_reduce,
)
w = jnp.zeros(source.dim, jnp.float32)
v, g = obj.value_and_grad(w)          # warm (compile)
np.asarray(g)
t0 = time.perf_counter()
v, g = obj.value_and_grad(w)
g_host = np.asarray(g)
wall = time.perf_counter() - t0
if pid == 0:
    with open(out_path, "w") as f:
        json.dump({
            "value": float(v),
            "grad_l1": float(np.abs(g_host).sum()),
            "pass_seconds": wall,
            "rows": source.num_examples,
        }, f)
"""


def _stream_scale_spec() -> tuple:
    """Shared scenario of the streaming-scale proofs (--stream-scale and
    --stream-scale-mp): env knobs, shape constants, generated files."""
    total_rows = int(os.environ.get("PHOTON_STREAM_SCALE_ROWS", str(10_000_000)))
    n_files, k, d = 64, 16, 1 << 17
    data_dir = os.environ.get(
        "PHOTON_STREAM_SCALE_DIR",
        os.path.join(os.environ.get("TMPDIR", "/tmp"), "photon_stream_scale"),
    )
    files = _generate_stream_files(data_dir, total_rows, n_files, k, d)
    return files, data_dir, total_rows, n_files, k, d


def _run_stream_workers(nproc: int, data_dir: str, d: int, log_dir: str) -> dict:
    """Spawn ``nproc`` CPU-pinned streamed-pass workers, return rank 0's
    result JSON.  Worker output goes to files (PIPEs could deadlock the
    collective if one worker fills its buffer while the parent drains the
    other); on any failure or timeout every worker is killed, never
    orphaned mid-collective."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    out_path = os.path.join(log_dir, f"mp_result_{nproc}.json")
    repo = os.path.dirname(os.path.abspath(__file__))
    procs, logs = [], []
    try:
        for pid in range(nproc):
            log = open(os.path.join(log_dir, f"worker_{nproc}_{pid}.log"), "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _MP_STREAM_WORKER, repo, coordinator,
                 str(nproc), str(pid), data_dir, out_path, str(d)],
                stdout=log, stderr=log,
            ))
        for p in procs:
            p.wait(timeout=1200)
        for pid, p in enumerate(procs):
            if p.returncode != 0:
                tail = open(
                    os.path.join(log_dir, f"worker_{nproc}_{pid}.log")
                ).read()[-2000:]
                # Surface the platform-limitation signature up front: the
                # emitted bench_error detail is truncated, and consumers
                # (tests, the BENCH parser) must still be able to tell "this
                # jaxlib cannot do multi-process CPU" from a real failure.
                for marker in MP_UNSUPPORTED_MARKERS:
                    if marker in tail:
                        raise RuntimeError(
                            f"{marker} on this jaxlib's CPU backend"
                        )
                raise RuntimeError(
                    f"stream worker {pid}/{nproc} failed:\n{tail}"
                )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for log in logs:
            log.close()
    with open(out_path) as f:
        return json.load(f)


def _stream_scale_mp() -> None:
    """Two-process streamed objective at the full streaming-proof scale:
    each process streams its file shard, per-shard gradients allgather-sum
    across processes (the reference's treeAggregate-across-hosts analog),
    and the distributed (value, |grad|_1) must match a single-process pass
    over all files (rel <= 1e-5; float32 accumulation order differs between
    the 64-term sequential sum and the two 32-term shard sums).  Completes
    VERDICT r3 item 3's "on 1-2 processes" at 10M rows; invoke:
    ``python bench.py --stream-scale-mp``.  Both runs are CPU-pinned
    subprocesses by design — this proves the multi-process ingestion +
    collective path on identical hardware, not chip compute (two processes
    cannot share the one tunneled chip).
    """
    import tempfile

    files, data_dir, _, _, _, d = _stream_scale_spec()
    log_dir = tempfile.mkdtemp(prefix="photon_stream_mp_")
    sp = _run_stream_workers(1, data_dir, d, log_dir)
    mp = _run_stream_workers(2, data_dir, d, log_dir)
    value_match = abs(mp["value"] - sp["value"]) <= 1e-5 * max(
        abs(sp["value"]), 1.0
    )
    grad_match = abs(mp["grad_l1"] - sp["grad_l1"]) <= 1e-5 * max(
        sp["grad_l1"], 1.0
    )
    _emit("config5_stream_mp_rows_per_sec",
          mp["rows"] / mp["pass_seconds"], "rows/s", {
              "processes": 2,
              "rows": mp["rows"],
              "files": len(files),
              "pass_seconds": round(mp["pass_seconds"], 2),
              "value_mp": mp["value"],
              "value_single": sp["value"],
              "value_match": value_match,
              "grad_l1_match": grad_match,
              "platform": "cpu (by design: multi-process ingestion proof)",
          })
    if not (value_match and grad_match):
        raise RuntimeError(
            f"2-process streamed objective diverged from single-process: "
            f"value {mp['value']} vs {sp['value']}, "
            f"grad_l1 {mp['grad_l1']} vs {sp['grad_l1']}"
        )


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache (repo-local, gitignored): repeat
    bench runs measure compute, not recompilation — the analog of the
    reference benchmarking on a warmed JVM.  First run still compiles."""
    from photon_tpu.utils.compilation_cache import enable

    enable(
        "PHOTON_BENCH_COMPILATION_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_bench_cache"),
        respect_existing=False,  # bench always measures against ITS cache
    )


def main() -> None:
    _acquire_backend()
    _enable_compilation_cache()
    # Kernel attribution (VERDICT r3 weak 2): every emitted line names the
    # kernel its numbers belong to.  An explicit PHOTON_SPARSE_GRAD is the
    # operator's pin; otherwise the headline stays in auto mode but raises
    # the selection probe's size cap to the FULL headline entry count, so
    # the one-time eager measurement (ops/sparse_grad_select) compares
    # fm/autodiff/pallas at the true shape on the live backend and the
    # round-end number automatically belongs to the day's fastest kernel.
    # The resolved choice is recorded in the emitted JSON ("kernel").
    if os.environ.get("PHOTON_SPARSE_GRAD", "auto") == "auto":
        os.environ.setdefault(
            "PHOTON_SPARSE_PROBE_MAX_ENTRIES", str(1 << 25)
        )
    if len(sys.argv) > 1 and sys.argv[1] == "--stream-scale":
        _stream_scale()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--stream-scale-mp":
        _stream_scale_mp()
        return
    if len(sys.argv) > 2 and sys.argv[1] == "--config":
        _bench_config(int(sys.argv[2]))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--mode":
        mode = sys.argv[2] if len(sys.argv) > 2 else ""
        modes = {
            "descent": _bench_descent,
            "validation": _bench_validation,
            "recovery": _bench_recovery,
            "entities": _bench_entities,
            "serving": _bench_serving,
            "fleet": _bench_fleet,
            "ooc": _bench_ooc,
            "online": _bench_online,
        }
        def flag_value(name):
            rest = sys.argv[3:]
            if name in rest and rest.index(name) + 1 < len(rest):
                return rest[rest.index(name) + 1]
            return None

        if mode == "ooc" and "--spill" in sys.argv[3:]:
            # ``--mode ooc --spill``: add the disk-tier leg (ISSUE 11) —
            # forced-eviction spilled fit, in-bench parity assertions,
            # game_ooc_disk_rows_per_sec, plus the ISSUE 17 bf16/int8
            # codec legs (``--tile-dtype`` restricts them to one).
            modes["ooc"] = lambda: _bench_ooc(
                spill=True, tile_dtype=flag_value("--tile-dtype")
            )
        if mode == "serving" and flag_value("--table-dtype"):
            # ``--mode serving --table-dtype bf16``: only that lossy leg
            # (f32 always runs as the denominator).
            modes["serving"] = lambda: _bench_serving(
                dtypes=(flag_value("--table-dtype"),)
            )
        if mode == "fleet" and flag_value("--table-dtype"):
            # ``--mode fleet --table-dtype bf16``: the whole fleet cycle
            # (capacity/saturation/chaos) on reduced-precision tables,
            # parity-gated at the codec's declared bound.
            modes["fleet"] = lambda: _bench_fleet(
                table_dtype=flag_value("--table-dtype")
            )
        if mode == "fleet" and "--chaos-matrix" in sys.argv[3:]:
            # ``--mode fleet --chaos-matrix``: the ISSUE 19 partition-
            # tolerance sweep — five deterministic network-fault cells
            # (lease-tolerated partition, lease expiry, zombie fencing,
            # duplicate frames, slow replica) plus the capacity-boundary
            # background-rebuild leg, each with in-bench acceptance.
            modes["fleet"] = lambda: _bench_fleet_chaos_matrix(
                table_dtype=flag_value("--table-dtype") or "f32"
            )
        if mode == "fleet" and flag_value("--models"):
            # ``--mode fleet --models N``: the ISSUE 18 multi-model arena
            # leg alone — N tenants, one arena, one ladder; zero-recompile
            # + per-tenant-parity + arena-bytes bars in-bench.
            modes["fleet"] = lambda: _bench_fleet_multimodel(
                table_dtype=flag_value("--table-dtype") or "f32",
                n_models=int(flag_value("--models")),
            )
        if mode not in modes:
            # An unknown mode must not silently fall through to the full
            # (minutes-long) default run; the raise reaches the top-level
            # handler and emits a bench_error JSON line.
            raise ValueError(
                f"unknown bench mode {mode!r}; valid: {', '.join(modes)}"
            )
        modes[mode]()
        return
    if len(sys.argv) <= 1 or sys.argv[1] != "--headline-only":
        # Default run: all five SURVEY.md §6 configs first (one JSON line
        # each; a failing config emits its own error line and never blocks
        # the others), then the headline metric LAST — drivers that parse a
        # single line take the final one.  A soft wall-clock budget guards
        # the headline: on a cold accelerator each config pays real compile
        # time, and an external runner's timeout must never expire before
        # the headline (the one number tracked round-over-round) prints.
        budget_s = float(os.environ.get("PHOTON_BENCH_BUDGET_S", "480"))
        t_start = time.perf_counter()
        for num in (1, 2, 3, 4, 5):
            elapsed = time.perf_counter() - t_start
            if elapsed > budget_s:
                _emit(f"config{num}_skipped", 0.0, "skipped", {
                    "reason": f"bench budget exhausted after {elapsed:.0f}s "
                              f"(PHOTON_BENCH_BUDGET_S={budget_s:.0f}); "
                              "run `bench.py --config "
                              f"{num}` individually",
                })
                continue
            try:
                _bench_config(num)
            except Exception as ex:  # noqa: BLE001 — config isolation
                _emit(f"config{num}_error", 0.0, "error", {
                    "error": f"{type(ex).__name__}: {ex}"[:500],
                })
        # The GAME residual-engine, validation-pipeline, and checkpoint-
        # recovery micro-benches ride the full run (their JSON lines land
        # next to the headline), same budget guard + isolation as the
        # numbered configs.
        # The entity-scaling bench rides the default run CAPPED at 100k
        # entities (the full 10k -> 1M curve is the standalone
        # `--mode entities` invocation; the 1M point alone costs minutes).
        import functools as _functools

        for label, fn in (("game_descent", _bench_descent),
                          ("game_validation", _bench_validation),
                          ("game_recovery", _bench_recovery),
                          ("game_serving", _bench_serving),
                          # Fleet serving (ISSUE 12): replicated scorers
                          # over the TCP ingest, traffic replay, admission
                          # control — the serving number going forward.
                          ("game_fleet", _bench_fleet),
                          # Multi-model arena (ISSUE 18): N tenants in one
                          # gather-table allocation and one compiled
                          # bucket ladder, mixed split-arm traffic.
                          ("game_fleet_multimodel",
                           _bench_fleet_multimodel),
                          # Online learning (ISSUE 15): append->serving
                          # refresh latency + refreshed-vs-full-retrain
                          # parity on the CPU fixture.
                          ("game_online", _bench_online),
                          # spill=True: game_ooc_disk_rows_per_sec + the
                          # per-tier stall fractions ride the default run
                          # (ISSUE 11).
                          ("game_ooc",
                           _functools.partial(_bench_ooc, spill=True)),
                          ("game_entities",
                           _functools.partial(_bench_entities, 100_000))):
            elapsed = time.perf_counter() - t_start
            if elapsed > budget_s:
                _emit(f"{label}_skipped", 0.0, "skipped", {
                    "reason": f"bench budget exhausted after {elapsed:.0f}s; "
                              f"run `bench.py --mode "
                              f"{label.split('_', 1)[1]}` individually",
                })
                continue
            try:
                fn()
            except Exception as ex:  # noqa: BLE001 — config isolation
                _emit(f"{label}_error", 0.0, "error", {
                    "error": f"{type(ex).__name__}: {ex}"[:500],
                })
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext

    platform = jax.devices()[0].platform
    # Problem size: ~32M nonzeros on an accelerator keeps the gather/scatter
    # hot loop HBM-bound like production GLM batches; small on CPU so the
    # driver's sanity runs stay fast.
    if platform == "cpu":
        n, k, d = 1 << 16, 16, 1 << 14
    else:
        n, k, d = 1 << 20, 32, 1 << 18

    batch = _build_batch(n, k, d)
    bench_dtype = os.environ.get("PHOTON_BENCH_DTYPE", "float32")
    try:
        jnp.dtype(bench_dtype)
    except TypeError:
        # An invalid dtype must not kill the run before the headline prints
        # (the budget guard's whole purpose); fall back and say so.
        print(
            f"WARNING: invalid PHOTON_BENCH_DTYPE={bench_dtype!r}; "
            "benchmarking float32",
            file=sys.stderr,
        )
        bench_dtype = "float32"
    if bench_dtype != "float32":
        from photon_tpu.data.batch import batch_astype

        batch = batch_astype(batch, bench_dtype)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    w = jnp.zeros(d, jnp.float32)

    # Each "grad step" is one full value+gradient over all n rows followed by
    # a small coefficient update — chaining steps through w gives a real
    # optimizer-trajectory dependency so no execution can be elided.
    @jax.jit
    def step(w, batch):
        v, g = obj.value_and_grad(w, batch)
        return w - 1e-3 * g, v

    reps = 20 if platform != "cpu" else 5
    # PHOTON_BENCH_FUSED=1 runs all reps inside ONE dispatch (lax.scan over
    # the same chained step) — the shape real fits take (optimizers are
    # fully jitted while_loops, one dispatch per fit), and the honest view
    # once per-step time approaches the ~9 ms tunnel dispatch overhead.
    # Default stays per-step dispatch: comparable with the r1 baseline.
    fused = os.environ.get("PHOTON_BENCH_FUSED", "0") == "1"
    if fused:
        from jax import lax

        @jax.jit
        def run_all(w, batch):
            def body(w, _):
                w2, v = step(w, batch)
                return w2, v
            return lax.scan(body, w, None, length=reps)

    # Warm up: compile + one execution.  np.asarray (device_get) rather than
    # block_until_ready: on the tunneled TPU platform block_until_ready
    # returns before execution finishes, which once inflated this benchmark
    # ~20000x; a host copy of the result cannot lie.
    if fused:
        w0, vs = run_all(w, batch)
        np.asarray(w0)
        t0 = time.perf_counter()
        w, vs = run_all(w, batch)
        np.asarray(w)
    else:
        w, v = step(w, batch)
        np.asarray(w)
        t0 = time.perf_counter()
        for _ in range(reps):
            w, v = step(w, batch)
        np.asarray(w)
    wall = time.perf_counter() - t0
    steps_per_sec = reps / wall

    # Effective bandwidth: per step the sparse hot loop must touch ids+vals
    # once in each direction (fwd gather products, bwd segment reduction).
    nnz = n * k
    val_bytes = jnp.dtype(bench_dtype).itemsize
    eff_gb_s = steps_per_sec * nnz * 2 * (4 + val_bytes) / 1e9  # 2 passes x (id + val)
    hbm_gb_s = 819.0  # v5e HBM peak; CPU numbers are sanity-only
    # Attribute the number to the kernel that actually ran: in auto mode
    # select_kernel's cache already holds the measured winner for this
    # shape (the timed steps above used it), so this lookup is a cache hit.
    kernel = os.environ.get("PHOTON_SPARSE_GRAD", "auto")
    if kernel == "auto":
        from photon_tpu.ops.sparse_grad_select import select_kernel

        kernel = "auto:" + select_kernel(
            nnz, d, n, has_fm=batch.fm is not None,
            has_aligned=batch.al is not None,
            has_xchg=batch.xchg is not None,
        )
    _emit("glm_grad_steps_per_sec", steps_per_sec, "steps/s", {
        "rows": n,
        "nnz_per_row": k,
        "dim": d,
        "dtype": bench_dtype,
        "kernel": kernel,
        **({"xchg_reduce": os.environ.get("PHOTON_XCHG_REDUCE", "aligned")}
           if "xchg" in kernel else {}),
        "dispatch": "fused" if fused else "per-step",
        "skew": os.environ.get("PHOTON_BENCH_SKEW", "uniform"),
        "platform": platform,
        "rows_per_sec": round(steps_per_sec * n, 1),
        "effective_gb_per_sec": round(eff_gb_s, 2),
        "pct_hbm_roofline": round(100.0 * eff_gb_s / hbm_gb_s, 2)
        if _is_tpu_platform(platform) else None,
    })


if __name__ == "__main__":
    try:
        main()
    except Exception as ex:  # noqa: BLE001 — the driver must always get JSON
        # Mode-specific metric name so a failed --config run is never
        # mistaken for a collapse of the headline benchmark.
        if len(sys.argv) > 2 and sys.argv[1] == "--config":
            metric = f"config{sys.argv[2]}_error"
        else:
            metric = "bench_error"
        _emit(metric, 0.0, "error", {
            "error": f"{type(ex).__name__}: {ex}"[:500],
        })
