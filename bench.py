"""Headline benchmark: GLM grad-steps/sec (BASELINE.json primary metric).

Times the innermost distributed operation of the framework — one full
value-and-gradient evaluation of a logistic-GLM objective over a sparse
batch (the rebuild of the reference's ``DistributedGLMLossFunction.calculate``
treeAggregate hot path, SURVEY.md §3.4) — as a jit-compiled XLA program on
whatever backend JAX exposes (one real TPU chip under the driver; CPU
elsewhere).

Prints ONE JSON line:
    {"metric": "glm_grad_steps_per_sec", "value": N, "unit": "steps/s",
     "vs_baseline": N}

``vs_baseline`` is vs. the reference's published numbers — of which there are
none (``BASELINE.json.published == {}``), so it reports the ratio against a
recorded prior run in ``BENCH_BASELINE.json`` when present and 1.0 otherwise.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _build_batch(n: int, k: int, d: int, seed: int = 0):
    """Synthetic sparse logistic data in the framework's padded-COO layout."""
    import jax.numpy as jnp

    from photon_tpu.data.batch import SparseBatch

    rng = np.random.default_rng(seed)
    ids = rng.integers(1, d, size=(n, k), dtype=np.int32)  # id 0 = pad/intercept
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32) * 0.1
    margin = (w_true[ids] * vals).sum(axis=1)
    label = (rng.random(n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    return SparseBatch(
        ids=jnp.asarray(ids),
        vals=jnp.asarray(vals),
        label=jnp.asarray(label),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
    )


def main() -> None:
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext

    platform = jax.devices()[0].platform
    # Problem size: ~32M nonzeros on an accelerator keeps the gather/scatter
    # hot loop HBM-bound like production GLM batches; small on CPU so the
    # driver's sanity runs stay fast.
    if platform == "cpu":
        n, k, d = 1 << 16, 16, 1 << 14
    else:
        n, k, d = 1 << 20, 32, 1 << 18

    batch = _build_batch(n, k, d)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    w = jnp.zeros(d, jnp.float32)

    # Each "grad step" is one full value+gradient over all n rows followed by
    # a small coefficient update — chaining steps through w gives a real
    # optimizer-trajectory dependency so no execution can be elided.
    @jax.jit
    def step(w, batch):
        v, g = obj.value_and_grad(w, batch)
        return w - 1e-3 * g, v

    # Warm up: compile + one execution.
    w, v = step(w, batch)
    jax.block_until_ready(w)

    reps = 20 if platform != "cpu" else 5
    t0 = time.perf_counter()
    for _ in range(reps):
        w, v = step(w, batch)
    jax.block_until_ready(w)
    wall = time.perf_counter() - t0
    steps_per_sec = reps / wall

    vs_baseline = 1.0
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as f:
                prior = json.load(f)
            if prior.get("value"):
                vs_baseline = steps_per_sec / float(prior["value"])
        except (ValueError, KeyError):
            pass

    print(
        json.dumps(
            {
                "metric": "glm_grad_steps_per_sec",
                "value": round(steps_per_sec, 3),
                "unit": "steps/s",
                "vs_baseline": round(vs_baseline, 3),
                "detail": {
                    "rows": n,
                    "nnz_per_row": k,
                    "dim": d,
                    "platform": platform,
                    "rows_per_sec": round(steps_per_sec * n, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
