"""Headline benchmark: GLM grad-steps/sec (BASELINE.json primary metric).

Times the innermost distributed operation of the framework — one full
value-and-gradient evaluation of a logistic-GLM objective over a sparse
batch (the rebuild of the reference's ``DistributedGLMLossFunction.calculate``
treeAggregate hot path, SURVEY.md §3.4) — as a jit-compiled XLA program on
whatever backend JAX exposes (one real TPU chip under the driver; CPU
elsewhere).

Prints ONE JSON line:
    {"metric": "glm_grad_steps_per_sec", "value": N, "unit": "steps/s",
     "vs_baseline": N}

``vs_baseline`` is vs. the reference's published numbers — of which there are
none (``BASELINE.json.published == {}``), so it reports the ratio against a
recorded prior run in ``BENCH_BASELINE.json`` when present and 1.0 otherwise.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _build_batch(n: int, k: int, d: int, seed: int = 0):
    """Synthetic sparse logistic data in the framework's padded-COO layout."""
    import jax.numpy as jnp

    from photon_tpu.data.batch import SparseBatch

    rng = np.random.default_rng(seed)
    ids = rng.integers(1, d, size=(n, k), dtype=np.int32)  # id 0 = pad/intercept
    vals = rng.standard_normal((n, k)).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32) * 0.1
    margin = (w_true[ids] * vals).sum(axis=1)
    label = (rng.random(n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    return SparseBatch(
        ids=jnp.asarray(ids),
        vals=jnp.asarray(vals),
        label=jnp.asarray(label),
        offset=jnp.zeros(n, jnp.float32),
        weight=jnp.ones(n, jnp.float32),
    )


def _emit(metric: str, value: float, unit: str, detail: dict) -> None:
    vs_baseline = 1.0
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as f:
                prior = json.load(f)
            if prior.get("metric") == metric and prior.get("value"):
                vs_baseline = value / float(prior["value"])
        except (ValueError, KeyError):
            pass
    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3),
        "detail": detail,
    }))


def _bench_config(num: int) -> None:
    """The five BASELINE.json bench configs (SURVEY.md §6), scaled to the
    local platform (full scale on accelerators, small on CPU sanity runs).
    Each run is a REAL driver invocation end-to-end (read -> fit -> eval).
    """
    import tempfile
    import jax

    import numpy as np

    from photon_tpu.data.synthetic import make_game_data, make_glm_data, write_libsvm

    platform = jax.devices()[0].platform
    big = platform != "cpu"
    tmp = tempfile.mkdtemp(prefix="photon_bench_")

    if num in (1, 2, 3):
        # (1) a1a-shaped logistic + L-BFGS; (2) linear elastic-net OWL-QN;
        # (3) Poisson TRON.  All through the legacy-driver path.
        from photon_tpu.drivers import train

        task, opt, reg = {
            1: ("logistic_regression", "lbfgs", "l2"),
            2: ("linear_regression", "owlqn", "elastic_net"),
            3: ("poisson_regression", "tron", "l2"),
        }[num]
        n, d = (1605, 123) if num == 1 else ((200_000, 1024) if big else (5000, 128))
        batch, _ = make_glm_data(n, d, task=task, seed=0)
        path = os.path.join(tmp, "train.libsvm")
        write_libsvm(path, np.asarray(batch.x)[:, :-1], np.asarray(batch.label))
        t0 = time.perf_counter()
        summary = train.run(train.build_parser().parse_args([
            "--input", path, "--task", task, "--optimizer", opt,
            "--reg-type", reg, "--reg-weights", "1.0",
            "--max-iterations", "100",
            "--output-dir", os.path.join(tmp, "out"),
        ]))
        wall = time.perf_counter() - t0
        entry = summary["sweep"][0]
        _emit(f"config{num}_fit_seconds", wall, "s", {
            "task": task, "optimizer": opt, "rows": n, "dim": d,
            "iterations": entry["iterations"],
            "reason": entry["convergence_reason"],
            "platform": platform,
        })
        return

    # (4) GAME fixed + user random effect (MovieLens-1M shape);
    # (5) GAME fixed + user + item random effects (LinkedIn-scale, scaled
    #     to the chip: rows/sec is the comparable number).
    from photon_tpu.drivers import train_game

    if num == 4:
        spec = "synthetic-game:6040:166:64:16:1:0" if big else \
            "synthetic-game:600:16:32:8:1:0"
        coords = [
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=30",
            "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=20",
        ]
    else:
        spec = "synthetic-game:20000:100:128:16:2:0" if big else \
            "synthetic-game:400:12:32:8:2:0"
        coords = [
            "--coordinate", "fixed:type=fixed,shard=global,max_iters=20",
            "--coordinate", "per_user:type=random,shard=re0,entity=re0,max_iters=15",
            "--coordinate", "per_item:type=random,shard=re1,entity=re1,max_iters=15",
        ]
    t0 = time.perf_counter()
    summary = train_game.run(train_game.build_parser().parse_args([
        "--input", spec, *coords,
        "--descent-iterations", "2",
        "--validation-split", "0.2",
        "--output-dir", os.path.join(tmp, "out"),
    ]))
    wall = time.perf_counter() - t0
    n_rows = int(spec.split(":")[1]) * int(spec.split(":")[2])
    _emit(f"config{num}_game_epoch_seconds", wall / 2.0, "s/epoch", {
        "spec": spec,
        "metrics": summary["best_metrics"],
        "approx_rows": n_rows,
        "rows_per_sec": round(2.0 * n_rows / wall, 1),
        "platform": jax.devices()[0].platform,
    })


def main() -> None:
    import sys

    if len(sys.argv) > 2 and sys.argv[1] == "--config":
        _bench_config(int(sys.argv[2]))
        return
    import jax
    import jax.numpy as jnp

    from photon_tpu.core.objective import GlmObjective, RegularizationContext

    platform = jax.devices()[0].platform
    # Problem size: ~32M nonzeros on an accelerator keeps the gather/scatter
    # hot loop HBM-bound like production GLM batches; small on CPU so the
    # driver's sanity runs stay fast.
    if platform == "cpu":
        n, k, d = 1 << 16, 16, 1 << 14
    else:
        n, k, d = 1 << 20, 32, 1 << 18

    batch = _build_batch(n, k, d)
    obj = GlmObjective.create("logistic", RegularizationContext("l2", 1.0))
    w = jnp.zeros(d, jnp.float32)

    # Each "grad step" is one full value+gradient over all n rows followed by
    # a small coefficient update — chaining steps through w gives a real
    # optimizer-trajectory dependency so no execution can be elided.
    @jax.jit
    def step(w, batch):
        v, g = obj.value_and_grad(w, batch)
        return w - 1e-3 * g, v

    # Warm up: compile + one execution.  np.asarray (device_get) rather than
    # block_until_ready: on the tunneled TPU platform block_until_ready
    # returns before execution finishes, which once inflated this benchmark
    # ~20000x; a host copy of the result cannot lie.
    w, v = step(w, batch)
    np.asarray(w)

    reps = 20 if platform != "cpu" else 5
    t0 = time.perf_counter()
    for _ in range(reps):
        w, v = step(w, batch)
    np.asarray(w)
    wall = time.perf_counter() - t0
    steps_per_sec = reps / wall

    vs_baseline = 1.0
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            with open(base_path) as f:
                prior = json.load(f)
            if prior.get("value"):
                vs_baseline = steps_per_sec / float(prior["value"])
        except (ValueError, KeyError):
            pass

    print(
        json.dumps(
            {
                "metric": "glm_grad_steps_per_sec",
                "value": round(steps_per_sec, 3),
                "unit": "steps/s",
                "vs_baseline": round(vs_baseline, 3),
                "detail": {
                    "rows": n,
                    "nnz_per_row": k,
                    "dim": d,
                    "platform": platform,
                    "rows_per_sec": round(steps_per_sec * n, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
