"""Append-only feeds for the online-learning service.

Two sources produce :class:`AppendBatch`es — appended row sets the service
ingests, grows device data with, and refreshes from (ISSUE 15):

- :class:`QueueFeed` — an in-process producer/consumer queue: callers
  ``append()`` ready-made :class:`~photon_tpu.game.data.GameDataset`
  batches (tests, embedded pipelines).
- :class:`DirectoryFeed` — a directory watch over part files (Avro/LIBSVM
  or anything the caller's ``loader`` reads): new files become pending
  batches, read under the ``retry_call``/watchdog triangle with the
  ``online:ingest`` fault site, and a DURABLE consumed cursor
  (``_consumed.txt``, atomic temp+fsync+rename) makes the feed restart-
  safe — a service killed mid-refresh re-ingests exactly the parts it
  never published.

Both speak the same peek/commit protocol: :meth:`poll` returns the pending
batches WITHOUT consuming them; :meth:`mark_consumed` commits them only
after the refresh that ingested them has published.  A refresh that dies
between the two leaves its batches pending — the crash-consistency
contract the mid-refresh kill tests pin.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, List, Optional

from photon_tpu.game.data import GameDataset

CURSOR_NAME = "_consumed.txt"


@dataclasses.dataclass
class AppendBatch:
    """One appended row set: the data, when it arrived (monotonic clock —
    the base of the append→serving refresh-latency measurement), and the
    source token the feed's consumed cursor records."""

    data: GameDataset
    appended_at: float
    source: str = "queue"


class QueueFeed:
    """In-process append feed (producer threads → the service's loop)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: List[AppendBatch] = []
        self._seq = 0

    def append(self, data: GameDataset, source: Optional[str] = None
               ) -> AppendBatch:
        with self._lock:
            self._seq += 1
            batch = AppendBatch(
                data=data,
                appended_at=time.monotonic(),
                source=source or f"queue-{self._seq:06d}",
            )
            self._pending.append(batch)
            return batch

    def poll(self) -> List[AppendBatch]:
        with self._lock:
            return list(self._pending)

    def mark_consumed(self, batches: List[AppendBatch]) -> None:
        consumed = {id(b) for b in batches}
        with self._lock:
            self._pending = [
                b for b in self._pending if id(b) not in consumed
            ]

    def pending_rows(self) -> int:
        with self._lock:
            return sum(b.data.num_examples for b in self._pending)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


class DirectoryFeed:
    """Directory-watch append feed over part files.

    ``loader(path) -> GameDataset`` reads one part (the driver wires the
    Avro/LIBSVM readers through it); ``suffixes`` filters which files are
    parts.  Files are ingested in sorted-name order — the deterministic
    replay order a killed-and-restarted service reproduces exactly.
    """

    def __init__(
        self,
        path: str,
        loader: Callable[[str], GameDataset],
        suffixes: tuple = (".avro", ".libsvm", ".txt"),
        telemetry=None,
        logger=None,
    ):
        from photon_tpu.telemetry import NULL_SESSION

        self.path = path
        self.loader = loader
        self.suffixes = tuple(suffixes)
        self.telemetry = telemetry or NULL_SESSION
        self.logger = logger
        self._lock = threading.Lock()
        self._loaded: dict = {}  # name -> AppendBatch (pending)
        self._consumed = self._read_cursor()

    # -- durable cursor ------------------------------------------------------
    def _cursor_path(self) -> str:
        return os.path.join(self.path, CURSOR_NAME)

    def _read_cursor(self) -> set:
        try:
            with open(self._cursor_path()) as f:
                return {line.strip() for line in f if line.strip()}
        except FileNotFoundError:
            return set()

    def _write_cursor(self) -> None:
        """Atomic cursor publish (``fault.atomic.atomic_write_bytes`` —
        mkstemp + fsync + rename + directory fsync): a kill mid-write
        leaves the previous complete cursor, never a torn one — worst case
        the restarted service re-ingests an already-published part, and the
        refresh it drives is idempotent training work, not corruption."""
        from photon_tpu.fault.atomic import atomic_write_bytes

        atomic_write_bytes(
            self._cursor_path(),
            ("\n".join(sorted(self._consumed)) + "\n").encode(),
        )

    def consumed_sources(self) -> List[str]:
        """Source tokens (part-file names) already published, in sorted
        order — what a RESTARTED owner must re-merge into its base
        training data to reconstruct the full dataset (the feed skips
        them; the merged training data itself is not durable)."""
        with self._lock:
            return sorted(self._consumed)

    # -- feed protocol -------------------------------------------------------
    def _part_names(self) -> List[str]:
        # "_"/"."-prefixed names are bookkeeping (the consumed cursor, temp
        # files mid-rename), never parts — the Hadoop part-file convention.
        return sorted(
            name for name in os.listdir(self.path)
            if name.endswith(self.suffixes)
            and not name.startswith(("_", "."))
        )

    def poll(self) -> List[AppendBatch]:
        """Pending batches, loading any newly arrived parts.  Part reads go
        through ``retry_call`` (site ``online:ingest``): transient IO
        faults retry with backoff under the watchdog's per-attempt stall
        timeout — the same triangle every other ingest edge rides.  The
        (potentially slow, multi-attempt) loads run OUTSIDE the feed lock,
        so ``mark_consumed``/``pending_rows`` callers never stall behind a
        faulting part; only the bookkeeping reads/writes lock."""
        from photon_tpu.fault.injection import fault_point
        from photon_tpu.fault.retry import retry_call

        with self._lock:
            fresh = [
                name for name in self._part_names()
                if name not in self._consumed and name not in self._loaded
            ]
        for name in fresh:
            path = os.path.join(self.path, name)

            def attempt(path=path, name=name):
                fault_point("online:ingest", path=name)
                return self.loader(path)

            data = retry_call(
                attempt, site="online:ingest",
                telemetry=self.telemetry, logger=self.logger,
            )
            batch = AppendBatch(
                data=data, appended_at=time.monotonic(), source=name
            )
            with self._lock:
                # A concurrent poll may have raced us to this part; first
                # writer wins (the losing load is dropped, not doubled).
                if name not in self._loaded and name not in self._consumed:
                    self._loaded[name] = batch
                    self.telemetry.counter("online.parts_ingested").inc()
        with self._lock:
            return [self._loaded[n] for n in sorted(self._loaded)]

    def mark_consumed(self, batches: List[AppendBatch]) -> None:
        with self._lock:
            for batch in batches:
                self._consumed.add(batch.source)
                self._loaded.pop(batch.source, None)
            self._write_cursor()

    def pending_rows(self) -> int:
        with self._lock:
            return sum(b.data.num_examples for b in self._loaded.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._loaded)
