"""Append-batch delta: which coordinates and entities a batch touches.

The online-learning loop (ISSUE 15) exploits the GAME decomposition:
appended rows touch a small set of coordinates/entities, so a warm-started
partial refresh — touched coordinates retrain, the rest stay locked on the
serving model — is dramatically cheaper than a full fit (Snap ML,
1803.06333, makes the same argument for hierarchical incremental GLMs).
This module computes that delta on host numpy, before any device work:

- :func:`merge_append` concatenates an append batch onto the base training
  dataset (append-only).  A batch may OMIT an id column — records that
  carry no id for a random effect simply do not participate in it (the
  reference's ``GameDatum`` semantics); the merged column is filled with a
  dtype-appropriate missing marker and the bool mask of filled rows rides
  back so device-data growth skips them (per-row entity index -1: zero
  margin, no bin membership).
- :func:`compute_delta` classifies every coordinate of a configuration:
  touched or not, and a touched one's NEW vs EXISTING entity keys against
  the current vocabularies — the lock list and the growth summary of one
  refresh round.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from photon_tpu.game.data import (
    DenseShard,
    GameDataset,
    Shard,
    SparseShard,
    entity_index_for,
)
# Canonical marker definitions live next to the dataset builders now (the
# cold-rebuild path resolves them too — ISSUE 19 satellite); re-exported
# here for the established import path.
from photon_tpu.game.data import (  # noqa: F401
    MISSING_INT64,
    missing_key,
    missing_mask,
)


def _to_base_layout(base: Shard, b: Shard) -> Shard:
    """Coerce an append shard to the base's storage layout.  Avro parts
    arrive padded-COO sparse while a base built from dense blocks stores
    dense (and vice versa); the conversion touches only the DELTA's rows."""
    if type(base) is type(b):
        return b
    if isinstance(base, DenseShard):
        # sparse append -> dense rows (padding ids are 0 with val 0: inert;
        # add.at folds duplicate ids like the sparse margin kernel's sum).
        x = np.zeros((b.ids.shape[0], b.dim), np.float32)
        np.add.at(x, (np.arange(len(b.ids))[:, None], b.ids), b.vals)
        return DenseShard(x)
    n = b.x.shape[0]
    counts = (b.x != 0).sum(axis=1)
    k = max(int(counts.max()) if n else 1, 1)
    ids = np.zeros((n, k), np.int32)
    vals = np.zeros((n, k), np.float32)
    for i in range(n):  # delta-sized loop (appended rows only)
        nz = np.nonzero(b.x[i])[0]
        ids[i, : len(nz)] = nz
        vals[i, : len(nz)] = b.x[i][nz]
    return SparseShard(ids, vals, base.dim_)


def _concat_shards(name: str, a: Shard, b: Shard) -> Shard:
    """Row-concatenate two feature shards, coercing the append side to the
    base's layout first.  Sparse shards may differ in padded-COO nonzero
    width (Avro parts pad to their own max); the narrower pads up — zero
    ids/vals are inert."""
    if a.dim != b.dim:
        raise ValueError(
            f"append batch shard {name!r} has dim {b.dim}, base has {a.dim}"
        )
    b = _to_base_layout(a, b)
    if isinstance(a, DenseShard):
        return DenseShard(np.concatenate([a.x, b.x]))
    k = max(a.ids.shape[1], b.ids.shape[1])

    def pad(arr):
        if arr.shape[1] == k:
            return arr
        return np.pad(arr, [(0, 0), (0, k - arr.shape[1])])

    return SparseShard(
        np.concatenate([pad(a.ids), pad(b.ids)]),
        np.concatenate([pad(a.vals), pad(b.vals)]),
        a.dim_,
    )


def merge_append(
    base: GameDataset, batch: GameDataset
) -> tuple[GameDataset, Dict[str, np.ndarray]]:
    """Append ``batch``'s rows onto ``base`` (append-only merge).

    Returns ``(merged, absent_tail)`` where ``absent_tail`` maps each id
    column to a bool mask over the APPENDED rows marking rows that carry no
    id for that column — either because the batch omitted the column
    entirely (filled with the missing marker here) or because the batch
    itself shipped marker values.  The mask is what
    ``GameEstimator.onboard_training_data`` forwards into device-data
    growth.  Every feature shard of the base must ride along (all rows
    train the fixed effect); unknown shards or id columns in the batch are
    refused loudly.
    """
    unknown = set(batch.shards) - set(base.shards)
    if unknown:
        raise ValueError(
            f"append batch carries unknown feature shard(s) "
            f"{sorted(unknown)}; base has {sorted(base.shards)}"
        )
    missing_shards = set(base.shards) - set(batch.shards)
    if missing_shards:
        raise ValueError(
            f"append batch must carry every feature shard (appended rows "
            f"train the fixed effect too); missing {sorted(missing_shards)}"
        )
    unknown_cols = set(batch.id_columns) - set(base.id_columns)
    if unknown_cols:
        raise ValueError(
            f"append batch carries unknown id column(s) "
            f"{sorted(unknown_cols)}; base has {sorted(base.id_columns)}"
        )
    n_tail = batch.num_examples
    shards = {
        name: _concat_shards(name, shard, batch.shards[name])
        for name, shard in base.shards.items()
    }
    id_columns = {}
    absent_tail: Dict[str, np.ndarray] = {}
    for name, col in base.id_columns.items():
        if name in batch.id_columns:
            # host-sync: id columns are host numpy by construction.
            tail = np.asarray(batch.id_columns[name])
            if len(tail) and tail.dtype.kind != col.dtype.kind:
                # The coercion entity_index_for applies, done once at merge:
                # mixed-kind concatenation would silently stringify ints.
                if col.dtype.kind in "iu":
                    tail = tail.astype(np.int64)
                else:
                    tail = tail.astype(str)
            if (len(tail) and col.dtype.kind in "iu"
                    and tail.dtype != col.dtype):
                # The merged column keeps the BASE dtype forever: letting
                # np.concatenate promote (int32 base + int64 tail) would
                # strand earlier rounds' missing markers as valid-looking
                # ids.  The tail's own markers translate to the base
                # dtype's marker; real ids must fit the base dtype.
                marker = missing_mask(tail)
                info = np.iinfo(col.dtype)
                bad = ~marker & ((tail < info.min) | (tail > info.max))
                if bad.any():
                    raise ValueError(
                        f"append batch id column {name!r} carries values "
                        f"outside the base column's {col.dtype} range"
                    )
                tail = tail.astype(col.dtype)
                tail[marker] = missing_key(col.dtype)
            absent_tail[name] = missing_mask(tail)
        else:
            tail = np.full(n_tail, missing_key(col.dtype))
            tail = tail.astype(col.dtype) if col.dtype.kind in "iu" else tail
            absent_tail[name] = np.ones(n_tail, bool)
        id_columns[name] = np.concatenate([col, tail])
    merged = GameDataset(
        label=np.concatenate([base.label, batch.label]),
        offset=np.concatenate([base.offset, batch.offset]),
        weight=np.concatenate([base.weight, batch.weight]),
        shards=shards,
        id_columns=id_columns,
    )
    return merged, absent_tail


@dataclasses.dataclass(frozen=True)
class CoordinateDelta:
    """One coordinate's slice of an append batch."""

    name: str
    kind: str  # fixed | random | factored_random
    touched: bool
    new_keys: np.ndarray       # entity keys NOT in the current vocabulary
    existing_keys: np.ndarray  # entity keys already in the vocabulary

    @property
    def rows_grow_existing(self) -> bool:
        return len(self.existing_keys) > 0


_EMPTY = np.zeros(0, np.int64)


@dataclasses.dataclass(frozen=True)
class BatchDelta:
    """What one append batch touches, per coordinate of a configuration."""

    rows: int
    coordinates: Dict[str, CoordinateDelta]

    @property
    def touched(self) -> list:
        return [n for n, c in self.coordinates.items() if c.touched]

    @property
    def untouched(self) -> list:
        return [n for n, c in self.coordinates.items() if not c.touched]


def compute_delta(
    coordinate_configs: Dict[str, object],
    vocabs: Dict[str, np.ndarray],
    batch: GameDataset,
    absent_tail: Optional[Dict[str, np.ndarray]] = None,
) -> BatchDelta:
    """Classify every coordinate of a configuration against one append
    batch.  ``vocabs`` maps entity column -> current entity vocabulary;
    ``absent_tail`` (as returned by :func:`merge_append`) masks rows that
    carry no id for a column.  A fixed-effect coordinate is touched by any
    row (every row enters its batch); a random coordinate is touched when
    at least one appended row carries an id for its column."""
    n = batch.num_examples
    absent_tail = absent_tail or {}
    out: Dict[str, CoordinateDelta] = {}
    for name, cc in coordinate_configs.items():
        kind = getattr(cc, "kind", "fixed")
        column = getattr(cc, "entity_column", None)
        if column is None:
            out[name] = CoordinateDelta(name, kind, n > 0, _EMPTY, _EMPTY)
            continue
        if column not in batch.id_columns:
            out[name] = CoordinateDelta(name, kind, False, _EMPTY, _EMPTY)
            continue
        # host-sync: id columns are host numpy by construction.
        tail = np.asarray(batch.id_columns[column])
        mask = absent_tail.get(column)
        live = tail[~mask] if mask is not None else tail[~missing_mask(tail)]
        if len(live) == 0:
            out[name] = CoordinateDelta(name, kind, False, _EMPTY, _EMPTY)
            continue
        vocab = vocabs.get(column)
        if vocab is not None and len(vocab):
            idx = entity_index_for(live, vocab)
        else:
            idx = np.full(len(live), -1, np.int32)
        out[name] = CoordinateDelta(
            name, kind, True,
            np.unique(live[idx < 0]), np.unique(live[idx >= 0]),
        )
    return BatchDelta(rows=n, coordinates=out)


def merge_deltas(deltas: list) -> BatchDelta:
    """Union of several batches' deltas (one refresh round may drain more
    than one pending batch)."""
    if not deltas:
        return BatchDelta(0, {})
    rows = sum(d.rows for d in deltas)
    names = list(deltas[0].coordinates)
    coordinates = {}
    for name in names:
        parts = [d.coordinates[name] for d in deltas]
        coordinates[name] = CoordinateDelta(
            name, parts[0].kind,
            any(p.touched for p in parts),
            np.unique(np.concatenate([p.new_keys for p in parts]))
            if any(len(p.new_keys) for p in parts) else _EMPTY,
            np.unique(np.concatenate([p.existing_keys for p in parts]))
            if any(len(p.existing_keys) for p in parts) else _EMPTY,
        )
    return BatchDelta(rows=rows, coordinates=coordinates)
