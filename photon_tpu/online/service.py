"""Online learning service: continual training with zero-downtime refresh.

The ISSUE 15 tentpole — the loop that joins the pieces the ROADMAP said
existed separately into one data-in → model-out subsystem:

1. **Ingest** — drain an append-only feed (:mod:`photon_tpu.online.feed`:
   in-process queue or directory watch, IO under the retry/watchdog
   triangle's ``online:ingest`` site).
2. **Delta** — which coordinates and which entities the appended rows
   touch (:mod:`photon_tpu.online.delta`).
3. **Grow** — device data extends IN PLACE for both new and existing
   entities (``GameEstimator.onboard_training_data`` → per-bin
   row-capacity headroom + entity migration; ZERO full random-effect
   layout rebuilds, asserted via ``estimator.device_data_rebuilds``).
4. **Refresh** — a warm-started partial ``CoordinateDescent``: untouched
   coordinates stay LOCKED on the serving model, touched ones retrain
   warm-started from it.  Checkpointable mid-refresh through the PR 4/5
   stack (``descent:kill`` → restart → ``resume auto`` → exact parity).
5. **Publish** — ``ServingFleet.rollout``: the canary-gated staggered
   ``swap_model`` under live traffic — zero recompiles (serving-table
   capacity headroom), zero dropped or mixed-model responses,
   parity-probed.  The ``online:refresh:kill`` fault site sits between
   train and publish: a kill there resumes the COMPLETED fit from its
   checkpoint and publishes on restart.

Telemetry (``online.*``): refresh latency append→serving
(``online.refresh_latency_s``), rows/batches ingested, coordinates
refreshed vs locked, a staleness gauge (age of the oldest unpublished
append), publish and failure counters.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from photon_tpu.fault.checkpoint import CheckpointError
from photon_tpu.game.estimator import (
    GameEstimator,
    GameOptimizationConfiguration,
)
from photon_tpu.game.model import GameModel, RandomEffectModel
from photon_tpu.online.delta import (
    BatchDelta,
    compute_delta,
    merge_append,
    merge_deltas,
)
from photon_tpu.telemetry import NULL_SESSION

ROUNDS_NAME = "rounds.txt"


@dataclasses.dataclass(frozen=True)
class RefreshPolicy:
    """Online-refresh knobs.

    ``refresh_iterations`` — outer descent iterations per refresh (the
    partial retrain is warm-started, so a small number converges).
    ``min_rows`` — pending-row threshold below which a poll is a no-op.
    ``lock_untouched`` — lock coordinates the drained batches do not touch
    (False retrains everything every refresh).
    ``max_quarantined`` — the descent quarantine budget per refresh.
    ``rollout_parity_tol`` — the canary parity gate of each publish;
    ``None`` (default) derives the gate from the fleet's serving table
    dtype (``lowp.parity_tol_for`` — f32 keeps the historical 1e-3, a
    bf16/int8 fleet gates at its measured codec bound).
    ``poll_interval_s`` — the background loop's cadence.
    """

    refresh_iterations: int = 2
    min_rows: int = 1
    lock_untouched: bool = True
    max_quarantined: Optional[int] = 8
    rollout_parity_tol: Optional[float] = None
    poll_interval_s: float = 0.2


@dataclasses.dataclass
class RefreshResult:
    """Outcome of one refresh round."""

    round: int
    model: GameModel
    delta: BatchDelta
    locked: List[str]
    rows: int
    latency_s: float
    published: bool


class OnlineLearningService:
    """Background continual training over a :class:`GameEstimator` + feed,
    publishing through a :class:`~photon_tpu.serving.fleet.ServingFleet`.

    ``estimator`` owns the training data and the device layouts (they grow
    in place, refresh over refresh); ``configuration`` is the ONE
    configuration refreshed (online refresh is not a sweep); ``model`` is
    the currently served model — the warm-start seed of the first refresh.
    ``fleet`` is optional: without one the service trains and updates
    ``self.model`` but publishes nowhere (a trainer-only deployment).

    ``checkpoint_dir`` makes every refresh preemption-safe: round ``k``
    checkpoints under ``round-00000k/`` and a restarted service (same
    estimator data, same feed backlog) resumes it exactly — the feed's
    consumed cursor advances only after publish, so the restart drains the
    same batches and the descent checkpoint carries the rest.  The merged
    training data itself is NOT durable: on restart the owner must
    reconstruct it as base data + the feed's ``consumed_sources()`` parts
    in order (``drivers/online_game`` does) before re-ingesting the
    backlog — otherwise published rows silently drop from training.

    Drive it synchronously (:meth:`refresh_once` — tests, benches, drain
    loops) or as a background thread (:meth:`start`/:meth:`stop`).
    """

    def __init__(
        self,
        estimator: GameEstimator,
        configuration: GameOptimizationConfiguration,
        feed,
        model: GameModel,
        fleet=None,
        checkpoint_dir: Optional[str] = None,
        policy: Optional[RefreshPolicy] = None,
        telemetry=None,
        logger=None,
        model_id: Optional[str] = None,
    ):
        self.estimator = estimator
        self.configuration = configuration
        self.feed = feed
        self.model = model
        self.fleet = fleet
        # Multi-model arena fleets: which tenant slice this service's
        # refreshes publish INTO (None = the fleet's default model — the
        # single-model shape).  Each refresh then rolls out as a
        # slice-scatter swap of that tenant only.
        self.model_id = model_id
        self.checkpoint_dir = checkpoint_dir
        self.policy = policy or RefreshPolicy()
        self.telemetry = telemetry or NULL_SESSION
        self.logger = logger
        self._round = self._read_completed_rounds()
        # Batches already folded into the estimator's training data but
        # not yet published (a refresh that failed AFTER onboarding): the
        # retry must not merge them twice.  In-memory only — a RESTART
        # rebuilds the estimator from base data + the feed's CONSUMED
        # parts (the owner re-merges them: the merged training data is
        # not durable; see drivers/online_game's replay-consumed-parts
        # step and DirectoryFeed.consumed_sources) and then re-ingests
        # the pending backlog.
        self._onboarded: set = set()
        # The batch set of the CURRENT round, snapshotted on its first
        # attempt: a retry after a failed publish must train the SAME
        # round (the round checkpoint's fingerprint pins the row count
        # and lock list) — parts arriving mid-round wait for the next.
        self._round_batches: Optional[List] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- durable round counter ----------------------------------------------
    def _rounds_path(self) -> Optional[str]:
        if self.checkpoint_dir is None:
            return None
        return os.path.join(self.checkpoint_dir, ROUNDS_NAME)

    def _read_completed_rounds(self) -> int:
        path = self._rounds_path()
        if path is None:
            return 0
        try:
            with open(path) as f:
                return sum(1 for line in f if line.strip())
        except FileNotFoundError:
            return 0

    def _complete_round(self) -> None:
        """Durably record a published round (atomic rewrite): a restart
        resumes at the right ``round-NNNNNN`` checkpoint subdirectory.
        Written AFTER publish, BEFORE the feed cursor — a kill between the
        two re-ingests already-published rows into the next round, which
        is idempotent training work, never a lost refresh."""
        path = self._rounds_path()
        self._round += 1
        if path is None:
            return
        from photon_tpu.fault.atomic import atomic_write_bytes

        os.makedirs(self.checkpoint_dir, exist_ok=True)
        atomic_write_bytes(
            path,
            "".join(
                f"round-{i:06d}\n" for i in range(self._round)
            ).encode(),
        )

    # -- warm start ----------------------------------------------------------
    def _vocabs(self) -> Dict[str, np.ndarray]:
        """Current entity vocabularies per id column, from the estimator's
        live device layouts (fallback: the serving model's keys)."""
        vocabs: Dict[str, np.ndarray] = dict(
            self.estimator.entity_vocabularies()
        )
        for m in self.model.coordinates.values():
            if isinstance(m, RandomEffectModel):
                # host-sync: entity vocabularies are host numpy by
                # construction (model build time, not the serving path).
                vocabs.setdefault(m.entity_column, np.asarray(m.keys))
        return vocabs

    def _grown_warm_start(self) -> GameModel:
        """The serving model grown to the onboarded vocabularies ON DEVICE
        (``RandomEffectModel.with_entities`` — existing entities keep their
        rows, new entities start at zero, the cold-start value)."""
        coords = {}
        for name, m in self.model.coordinates.items():
            cc = self.configuration.coordinates.get(name)
            if isinstance(m, RandomEffectModel) and cc is not None:
                dd = self.estimator.device_layout(cc)
                if len(dd.dataset.keys) != len(m.keys):
                    m = m.with_entities(dd.dataset.keys)
            coords[name] = m
        return GameModel(coords, self.model.task_type)

    # -- the loop body -------------------------------------------------------
    def refresh_once(self) -> Optional[RefreshResult]:
        """One full refresh round: drain the feed, grow device data, run
        the warm-started partial fit, publish through the canary gate, and
        commit the feed cursor.  Returns None when the backlog is below
        ``policy.min_rows``."""
        pending = self.feed.poll()
        # Staleness from the batches just polled (one feed scan per tick).
        self.telemetry.gauge("online.staleness_s").set(
            time.monotonic() - min(b.appended_at for b in pending)
            if pending else 0.0
        )
        if self._round_batches is None:
            batches = pending
        else:
            # Retry of a failed round: replay EXACTLY its batch set, so
            # the round checkpoint's fingerprint (row count, lock list)
            # still matches; newer arrivals join the NEXT round.
            batches = self._round_batches
        pending_rows = sum(b.data.num_examples for b in batches)
        if not batches or pending_rows < self.policy.min_rows:
            return None
        self._round_batches = batches
        t_append = min(b.appended_at for b in batches)
        round_id = self._round
        with self.telemetry.span("online.refresh", round=round_id,
                                 rows=pending_rows):
            # 1+2. Ingest + delta: merge every pending batch onto the
            # current training data, accumulating the per-column absent
            # masks and the per-batch coordinate deltas.  A batch a FAILED
            # previous attempt already folded into the estimator (onboard
            # succeeded, fit/publish did not) is skipped here — merging it
            # again would double its rows' weight in the model; its delta
            # still counts toward this round's lock list.
            vocabs = self._vocabs()
            merged = self.estimator.training_data
            n_base = merged.num_examples
            absent: Dict[str, list] = {}
            deltas = []
            fresh_batches = []
            for batch in batches:
                deltas.append(compute_delta(
                    self.configuration.coordinates, vocabs, batch.data,
                ))
                if id(batch) in self._onboarded:
                    continue
                fresh_batches.append(batch)
                merged, batch_absent = merge_append(merged, batch.data)
                for colname, mask in batch_absent.items():
                    absent.setdefault(colname, []).append(mask)
            absent_tail = {
                colname: np.concatenate(masks)
                for colname, masks in absent.items()
            }
            delta = merge_deltas(deltas)
            self.telemetry.counter("online.batches_ingested").inc(
                len(fresh_batches)
            )
            self.telemetry.counter("online.rows_ingested").inc(
                merged.num_examples - n_base
            )
            # 3. Grow device data in place (new + existing entities).
            if fresh_batches:
                self.estimator.onboard_training_data(
                    merged, absent_tail=absent_tail
                )
                self._onboarded.update(id(b) for b in fresh_batches)
            # 4. Warm-started partial refresh with untouched coordinates
            # locked on the serving model.
            warm = self._grown_warm_start()
            locked = []
            if self.policy.lock_untouched:
                locked = [
                    name for name in delta.untouched
                    if name in warm.coordinates
                ]
            round_dir = (
                os.path.join(self.checkpoint_dir, f"round-{round_id:06d}")
                if self.checkpoint_dir else None
            )
            config = dataclasses.replace(
                self.configuration,
                descent_iterations=self.policy.refresh_iterations,
                name=f"refresh-{round_id:06d}",
            )
            with self.telemetry.span("online.train", round=round_id):
                try:
                    results = self.estimator.fit(
                        [config],
                        initial_model=warm,
                        locked_coordinates=locked,
                        checkpoint_dir=round_dir,
                        resume="auto" if round_dir else None,
                        max_quarantined=self.policy.max_quarantined,
                    )
                except CheckpointError:
                    # The round checkpoint no longer matches this round's
                    # shape (a RESTARTED service drained a different batch
                    # set than the killed attempt — e.g. parts arrived
                    # between the kill and the restart).  The checkpoint
                    # was an optimization, not a correctness requirement:
                    # train the round fresh, overwriting the stale chain,
                    # instead of wedging on the refusal forever.
                    self.telemetry.counter(
                        "online.checkpoint_refused"
                    ).inc()
                    if self.logger is not None:
                        self.logger.warning(
                            "online refresh %d: round checkpoint does not "
                            "match this round's batch set; training fresh",
                            round_id,
                        )
                    results = self.estimator.fit(
                        [config],
                        initial_model=warm,
                        locked_coordinates=locked,
                        checkpoint_dir=round_dir,
                        max_quarantined=self.policy.max_quarantined,
                    )
            model = results[0].model
            self.telemetry.counter("online.coordinates_refreshed").inc(
                len(config.coordinates) - len(locked)
            )
            if locked:
                self.telemetry.counter("online.coordinates_locked").inc(
                    len(locked)
                )
            # 5. Publish through the canary gate.  The kill window between
            # train and publish: a restart finds the round's fit COMPLETE
            # in its checkpoint (rebuilt without re-running) and publishes.
            from photon_tpu.fault.injection import fault_point

            fault_point("online:refresh:kill", iteration=round_id)
            published = False
            if self.fleet is not None:
                with self.telemetry.span("online.publish", round=round_id):
                    self._publish(model)
                published = True
                self.telemetry.counter("online.publishes").inc()
            self.model = model
            self.telemetry.counter("online.refreshes").inc()
            self._complete_round()
            self.feed.mark_consumed(batches)
            self._onboarded.difference_update(id(b) for b in batches)
            self._round_batches = None
            latency = time.monotonic() - t_append
            self.telemetry.histogram("online.refresh_latency_s").observe(
                latency
            )
            self.telemetry.gauge("online.staleness_s").set(0.0)
        if self.logger is not None:
            self.logger.info(
                "online refresh %d: %d rows in %d batch(es), %d/%d "
                "coordinates refreshed (%s locked), append->serving "
                "%.3fs%s",
                round_id, pending_rows, len(batches),
                len(config.coordinates) - len(locked),
                len(config.coordinates),
                ",".join(locked) or "none", latency,
                ", published" if published else "",
            )
        return RefreshResult(
            round=round_id, model=model, delta=delta, locked=locked,
            rows=pending_rows, latency_s=latency, published=published,
        )

    def _publish(self, model: GameModel) -> None:
        """Fleet-wide canary rollout of the refreshed model.  Probe traffic
        is the router's mirror of recently admitted live requests; a cold
        fleet (no traffic yet) probes with the supervisor's synthetic
        known-answer request instead."""
        parity_tol = self.policy.rollout_parity_tol
        if parity_tol is None:
            # Per-dtype gate: refresh preserves the fleet's storage tier
            # (the scorers re-encode the published f32 model at their own
            # dtype), so the publish gate is that tier's measured bound.
            from photon_tpu.game.lowp import parity_tol_for

            parity_tol = parity_tol_for(
                getattr(self.fleet, "table_dtype", "f32")
            )
        probes = None
        if not self.fleet.router.recent_requests():
            from photon_tpu.serving.supervisor import probe_request_for

            spec = None
            for replica in self.fleet.replicas:
                spec = getattr(replica.scorer, "request_spec", None)
                if spec:
                    break
            if spec is None:
                raise RuntimeError(
                    "no replica exposes a request spec to probe with"
                )
            probes = [probe_request_for(model, spec)]
        rollout_kwargs = {}
        # getattr: _publish is duck-typed (tests drive it with a bare
        # namespace standing in for the service).
        model_id = getattr(self, "model_id", None)
        if model_id is not None:
            rollout_kwargs["model_id"] = model_id
        observer = getattr(self.fleet, "observer", None)
        if observer is None:
            self.fleet.rollout(
                model, probe_requests=probes, parity_tol=parity_tol,
                **rollout_kwargs,
            )
            return
        # Traced publish: refresh -> canary -> swap becomes ONE linked
        # trace.  The publish span's context is activated as the ambient
        # trace, so the router parents its serving.rollout span under it
        # and the canary probes carry the same trace id down to the
        # subprocess children.
        from photon_tpu.telemetry.distributed import (
            SpanRecord, activate_trace, current_trace, new_trace_id,
        )

        ambient = current_trace()
        span = SpanRecord(
            trace_id=ambient.trace_id if ambient else new_trace_id(),
            name="online.publish",
            process=observer.process,
            parent_id=ambient.span_id if ambient else None,
        )
        span.attrs["version"] = getattr(model, "version", None)
        try:
            with activate_trace(span.context()):
                self.fleet.rollout(
                    model, probe_requests=probes, parity_tol=parity_tol,
                    **rollout_kwargs,
                )
            span.finish()
        except BaseException:
            span.finish("error")
            raise
        finally:
            observer.collector.add(span)

    # -- background loop -----------------------------------------------------
    def start(self) -> "OnlineLearningService":
        if self._thread is not None:
            raise RuntimeError("service already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="photon-online-refresh", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.policy.poll_interval_s):
            try:
                self.refresh_once()
            except Exception:  # noqa: BLE001 — the loop must survive a bad
                # round (a poisoned batch, a failed rollout); the backlog
                # stays pending and the failure is counted + logged, so a
                # transient cause retries on the next poll.
                self.telemetry.counter("online.refresh_failures").inc()
                if self.logger is not None:
                    self.logger.exception("online refresh failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    def __enter__(self) -> "OnlineLearningService":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
