"""Online learning: continual training with zero-downtime model refresh.

The ISSUE 15 subsystem closing the ROADMAP's "close the loop" item — an
append-only feed drains into in-place device-data growth, a warm-started
partial coordinate-descent refresh, and a canary-gated fleet publish.  See
:mod:`photon_tpu.online.service` for the loop, :mod:`~.feed` for the
sources, :mod:`~.delta` for the touched-coordinate/entity computation.
"""

from photon_tpu.online.delta import (
    BatchDelta,
    CoordinateDelta,
    compute_delta,
    merge_append,
    merge_deltas,
    missing_key,
    missing_mask,
)
from photon_tpu.online.feed import AppendBatch, DirectoryFeed, QueueFeed
from photon_tpu.online.service import (
    OnlineLearningService,
    RefreshPolicy,
    RefreshResult,
)

__all__ = [
    "AppendBatch",
    "BatchDelta",
    "CoordinateDelta",
    "DirectoryFeed",
    "OnlineLearningService",
    "QueueFeed",
    "RefreshPolicy",
    "RefreshResult",
    "compute_delta",
    "merge_append",
    "merge_deltas",
    "missing_key",
    "missing_mask",
]
