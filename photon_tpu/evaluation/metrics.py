"""Metric functions on (scores, labels, weights) arrays.

All metrics treat ``weight == 0`` rows as absent — the padding convention —
so they compose directly with padded/sharded batches.  The headline metrics
are jit-compatible vectorized JAX; per-entity (sharded) aggregation runs
host-side in numpy (evaluation is off the hot path, matching the reference
where evaluators are a separate Spark pass).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.utils import pow2_at_least

from photon_tpu.core.losses import get_loss

Array = jax.Array


def _weights_or_ones(scores, weights):
    if weights is None:
        return jnp.ones_like(scores)
    return weights


@jax.jit
def area_under_roc_curve(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted, tie-corrected AUC (Mann-Whitney U formulation).

    AUC = sum_i w+_i * (W-_below(s_i) + W-_tied(s_i)/2) / (W+ * W-), computed
    by sorting once and using searchsorted for tie groups — O(n log n), fully
    vectorized (the reference's AreaUnderROCCurveEvaluator computes the same
    statistic via Spark's ranking).
    """
    w = _weights_or_ones(scores, weights)
    pos_w = w * labels
    neg_w = w * (1.0 - labels)
    order = jnp.argsort(scores)
    s_sorted = scores[order]
    posw_sorted = pos_w[order]
    negw_sorted = neg_w[order]
    csneg = jnp.cumsum(negw_sorted)
    lo = jnp.searchsorted(s_sorted, s_sorted, side="left")
    hi = jnp.searchsorted(s_sorted, s_sorted, side="right")
    csneg_ex = jnp.concatenate([jnp.zeros(1, csneg.dtype), csneg])
    below = csneg_ex[lo]
    tied = csneg_ex[hi] - csneg_ex[lo]
    num = jnp.sum(posw_sorted * (below + 0.5 * tied))
    wpos = jnp.sum(pos_w)
    wneg = jnp.sum(neg_w)
    return jnp.where((wpos > 0) & (wneg > 0), num / (wpos * wneg), 0.5)


@jax.jit
def rmse(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    w = _weights_or_ones(scores, weights)
    se = w * (scores - labels) ** 2
    return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(w), 1e-30))


def _mean_loss(loss_name: str) -> Callable:
    loss = get_loss(loss_name)

    @jax.jit
    def metric(scores: Array, labels: Array, weights: Array | None = None) -> Array:
        w = _weights_or_ones(scores, weights)
        return jnp.sum(w * loss.value(scores, labels)) / jnp.maximum(
            jnp.sum(w), 1e-30
        )

    return metric


logistic_loss_metric = _mean_loss("logistic")
poisson_loss_metric = _mean_loss("poisson")
squared_loss_metric = _mean_loss("squared")
smoothed_hinge_loss_metric = _mean_loss("smoothed_hinge")


def precision_at_k(
    scores: Array, labels: Array, weights: Array | None = None, k: int = 10
) -> Array:
    """Fraction of positives among the k highest-scoring (non-padded) rows."""
    w = _weights_or_ones(scores, weights)
    masked = jnp.where(w > 0, scores, -jnp.inf)
    k_eff = min(k, int(scores.shape[0]))
    _, top_idx = jax.lax.top_k(masked, k_eff)
    valid = jnp.take(w, top_idx) > 0
    hits = jnp.take(labels, top_idx) * valid
    return jnp.sum(hits) / jnp.maximum(jnp.sum(valid), 1)


def sharded_metric(
    metric: Callable,
    scores: np.ndarray,
    labels: np.ndarray,
    entity_ids: np.ndarray,
    weights: np.ndarray | None = None,
    require_both_classes: bool = False,
    **kw,
) -> float:
    """Average a metric over entity groups (the reference's sharded
    evaluators, e.g. per-query AUC averaged over queries).

    Groups where the metric is undefined (e.g. single-class for AUC when
    ``require_both_classes``) are skipped, matching the reference.
    """
    scores = np.asarray(scores)
    labels = np.asarray(labels)
    entity_ids = np.asarray(entity_ids)
    w = np.ones_like(scores) if weights is None else np.asarray(weights)
    live = w > 0
    scores, labels, entity_ids, w = (
        scores[live], labels[live], entity_ids[live], w[live]
    )
    total, count = 0.0, 0
    for eid in np.unique(entity_ids):
        sel = entity_ids == eid
        if require_both_classes:
            pos = float(np.sum(w[sel] * labels[sel]))
            neg = float(np.sum(w[sel] * (1.0 - labels[sel])))
            if pos <= 0 or neg <= 0:
                continue
        # Pad each group to a power-of-two size with weight-0 rows so the
        # jitted metric compiles O(log max_group) times, not once per
        # distinct group size.
        n = int(sel.sum())
        padded = pow2_at_least(n)
        s = np.zeros(padded, scores.dtype)
        l = np.zeros(padded, labels.dtype)
        ww = np.zeros(padded, w.dtype)
        s[:n], l[:n], ww[:n] = scores[sel], labels[sel], w[sel]
        total += float(metric(s, l, ww, **kw))
        count += 1
    return total / count if count else float("nan")
