"""Metric functions on (scores, labels, weights) arrays.

All metrics treat ``weight == 0`` rows as absent — the padding convention —
so they compose directly with padded/sharded batches.  The headline metrics
are jit-compatible vectorized JAX.  Per-entity (sharded) aggregation has two
paths: :func:`sharded_metric` is the host numpy reference (one jitted metric
call per entity group — the reference's separate Spark evaluator pass), and
:func:`sharded_metric_device` is a single jitted segment-reduce program over
integer entity codes — the on-device validation pipeline's path
(``game.descent``), which under a sharded mesh lets GSPMD place the sort /
psum collectives (the DrJAX shape, arXiv:2403.07128) and syncs exactly one
scalar per metric.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.utils import pow2_at_least

from photon_tpu.core.losses import get_loss

Array = jax.Array


def _weights_or_ones(scores, weights):
    if weights is None:
        return jnp.ones_like(scores)
    return weights


@jax.jit
def area_under_roc_curve(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted, tie-corrected AUC (Mann-Whitney U formulation).

    AUC = sum_i w+_i * (W-_below(s_i) + W-_tied(s_i)/2) / (W+ * W-), computed
    by sorting once and using searchsorted for tie groups — O(n log n), fully
    vectorized (the reference's AreaUnderROCCurveEvaluator computes the same
    statistic via Spark's ranking).
    """
    w = _weights_or_ones(scores, weights)
    pos_w = w * labels
    neg_w = w * (1.0 - labels)
    order = jnp.argsort(scores)
    s_sorted = scores[order]
    posw_sorted = pos_w[order]
    negw_sorted = neg_w[order]
    csneg = jnp.cumsum(negw_sorted)
    lo = jnp.searchsorted(s_sorted, s_sorted, side="left")
    hi = jnp.searchsorted(s_sorted, s_sorted, side="right")
    csneg_ex = jnp.concatenate([jnp.zeros(1, csneg.dtype), csneg])
    below = csneg_ex[lo]
    tied = csneg_ex[hi] - csneg_ex[lo]
    num = jnp.sum(posw_sorted * (below + 0.5 * tied))
    wpos = jnp.sum(pos_w)
    wneg = jnp.sum(neg_w)
    return jnp.where((wpos > 0) & (wneg > 0), num / (wpos * wneg), 0.5)


@jax.jit
def rmse(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    w = _weights_or_ones(scores, weights)
    se = w * (scores - labels) ** 2
    return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(w), 1e-30))


def _mean_loss(loss_name: str) -> Callable:
    loss = get_loss(loss_name)

    @jax.jit
    def metric(scores: Array, labels: Array, weights: Array | None = None) -> Array:
        w = _weights_or_ones(scores, weights)
        return jnp.sum(w * loss.value(scores, labels)) / jnp.maximum(
            jnp.sum(w), 1e-30
        )

    return metric


logistic_loss_metric = _mean_loss("logistic")
poisson_loss_metric = _mean_loss("poisson")
squared_loss_metric = _mean_loss("squared")
smoothed_hinge_loss_metric = _mean_loss("smoothed_hinge")


def precision_at_k(
    scores: Array, labels: Array, weights: Array | None = None, k: int = 10
) -> Array:
    """Fraction of positives among the k highest-scoring (non-padded) rows."""
    w = _weights_or_ones(scores, weights)
    masked = jnp.where(w > 0, scores, -jnp.inf)
    k_eff = min(k, int(scores.shape[0]))
    _, top_idx = jax.lax.top_k(masked, k_eff)
    valid = jnp.take(w, top_idx) > 0
    hits = jnp.take(labels, top_idx) * valid
    return jnp.sum(hits) / jnp.maximum(jnp.sum(valid), 1)


def sharded_metric(
    metric: Callable,
    scores: np.ndarray,
    labels: np.ndarray,
    entity_ids: np.ndarray,
    weights: np.ndarray | None = None,
    require_both_classes: bool = False,
    **kw,
) -> float:
    """Average a metric over entity groups (the reference's sharded
    evaluators, e.g. per-query AUC averaged over queries).

    Groups where the metric is undefined (e.g. single-class for AUC when
    ``require_both_classes``) are skipped, matching the reference.
    """
    # host-sync: the HOST sharded path — device callers use
    # sharded_metric_device instead.
    scores = np.asarray(scores)
    labels = np.asarray(labels)
    entity_ids = np.asarray(entity_ids)
    w = np.ones_like(scores) if weights is None else np.asarray(weights)
    live = w > 0
    scores, labels, entity_ids, w = (
        scores[live], labels[live], entity_ids[live], w[live]
    )
    total, count = 0.0, 0
    for eid in np.unique(entity_ids):
        sel = entity_ids == eid
        if require_both_classes:
            pos = float(np.sum(w[sel] * labels[sel]))
            neg = float(np.sum(w[sel] * (1.0 - labels[sel])))
            if pos <= 0 or neg <= 0:
                continue
        # Pad each group to a power-of-two size with weight-0 rows so the
        # jitted metric compiles O(log max_group) times, not once per
        # distinct group size.
        n = int(sel.sum())
        padded = pow2_at_least(n)
        s = np.zeros(padded, scores.dtype)
        l = np.zeros(padded, labels.dtype)
        ww = np.zeros(padded, w.dtype)
        s[:n], l[:n], ww[:n] = scores[sel], labels[sel], w[sel]
        total += float(metric(s, l, ww, **kw))
        count += 1
    return total / count if count else float("nan")


def _segment_starts(order_key: Array) -> Array:
    """For a SORTED key vector, the index of each row's segment start
    (``cummax`` over the boundary indices — O(n), no host sync)."""
    n = order_key.shape[0]
    idx = jnp.arange(n)
    new = jnp.concatenate(
        [jnp.ones(1, bool), order_key[1:] != order_key[:-1]]
    )
    return jax.lax.cummax(jnp.where(new, idx, 0))


def _segmented_cumsum(x: Array, new_seg: Array) -> Array:
    """Inclusive cumulative sum that RESETS at each segment boundary.

    A segmented-sum associative scan — the sums stay segment-local, so late
    segments never pay the cancellation error a global-cumsum-and-subtract
    would (difference of two large prefixes in f32)."""

    def combine(a, b):
        a_sum, a_new = a
        b_sum, b_new = b
        return jnp.where(b_new, b_sum, a_sum + b_sum), a_new | b_new

    total, _ = jax.lax.associative_scan(combine, (x, new_seg))
    return total


@functools.partial(jax.jit, static_argnames=("num_segments",))
def _sharded_auc_kernel(
    scores: Array, labels: Array, weights: Array, codes: Array,
    num_segments: int,
) -> tuple[Array, Array]:
    """Per-entity weighted tie-corrected AUC, averaged over entities with
    both classes present, as ONE program: sort by (entity, score), take
    segment-local cumulative negative weight with tie-group correction, and
    segment-sum the Mann-Whitney numerators.  Matches ``sharded_metric(
    area_under_roc_curve, ..., require_both_classes=True)``."""
    pos = weights * labels
    neg = weights * (1.0 - labels)
    order = jnp.lexsort((scores, codes))
    s, e = scores[order], codes[order]
    pw, nw = pos[order], neg[order]
    n = s.shape[0]
    idx = jnp.arange(n)
    new_seg = jnp.concatenate([jnp.ones(1, bool), e[1:] != e[:-1]])
    new_tie = new_seg | jnp.concatenate(
        [jnp.ones(1, bool), s[1:] != s[:-1]]
    )
    tie_start = jax.lax.cummax(jnp.where(new_tie, idx, 0))
    # Segment-local EXCLUSIVE negative-weight prefix, evaluated at each
    # row's tie-group start: the weight of strictly-lower-scored negatives
    # in the same entity.
    csneg_ex = _segmented_cumsum(nw, new_seg) - nw
    below = csneg_ex[tie_start]
    tie_gid = jnp.cumsum(new_tie) - 1
    tied = jax.ops.segment_sum(nw, tie_gid, num_segments=n)[tie_gid]
    num = jax.ops.segment_sum(
        pw * (below + 0.5 * tied), e, num_segments=num_segments
    )
    wpos = jax.ops.segment_sum(pw, e, num_segments=num_segments)
    wneg = jax.ops.segment_sum(nw, e, num_segments=num_segments)
    valid = (wpos > 0) & (wneg > 0)
    auc = jnp.where(valid, num / jnp.maximum(wpos * wneg, 1e-30), 0.0)
    count = jnp.sum(valid)
    return jnp.sum(auc) / jnp.maximum(count, 1), count


@functools.partial(jax.jit, static_argnames=("num_segments", "k"))
def _sharded_precision_kernel(
    scores: Array, labels: Array, weights: Array, codes: Array,
    num_segments: int, k: int,
) -> tuple[Array, Array]:
    """Per-entity precision@k averaged over entities with any live row:
    sort by (entity, -masked score); a row is selected when its within-
    segment rank is below ``k`` and its weight is live.  Matches
    ``sharded_metric(precision_at_k, ..., k=k)``."""
    masked = jnp.where(weights > 0, scores, -jnp.inf)
    order = jnp.lexsort((-masked, codes))
    e, l, w = codes[order], labels[order], weights[order]
    idx = jnp.arange(scores.shape[0])
    rank = idx - _segment_starts(e)
    sel = (rank < k) & (w > 0)
    hits = jax.ops.segment_sum(l * sel, e, num_segments=num_segments)
    cnt = jax.ops.segment_sum(
        sel.astype(jnp.float32), e, num_segments=num_segments
    )
    live = jax.ops.segment_sum(
        (w > 0).astype(jnp.float32), e, num_segments=num_segments
    )
    valid = live > 0
    prec = jnp.where(valid, hits / jnp.maximum(cnt, 1.0), 0.0)
    count = jnp.sum(valid)
    return jnp.sum(prec) / jnp.maximum(count, 1), count


def sharded_metric_device(
    kind: str,
    scores: Array,
    labels: Array,
    entity_codes: Array,
    num_segments: int,
    weights: Array | None = None,
    k: int = 10,
) -> Array:
    """Device-resident :func:`sharded_metric`: per-entity metric averaged
    over entities, as one jitted segment-reduce program on integer entity
    codes (``kind``: ``auc`` | ``precision``).

    Inputs stay device arrays end to end (sharded inputs run SPMD — GSPMD
    inserts the sort/psum collectives); the return value is a device scalar,
    NaN when no entity qualifies — ``float()`` it for the one host sync.
    Weight-0 rows (padding) are invisible, and segments holding only
    weight-0 rows don't count, matching the host path's live-row filter.
    """
    w = jnp.ones_like(scores) if weights is None else weights
    if kind == "auc":
        mean, count = _sharded_auc_kernel(
            scores, labels, w, entity_codes, num_segments
        )
    elif kind == "precision":
        mean, count = _sharded_precision_kernel(
            scores, labels, w, entity_codes, num_segments, k
        )
    else:
        raise KeyError(f"unknown device sharded metric {kind!r}")
    return jnp.where(count > 0, mean, jnp.nan)
