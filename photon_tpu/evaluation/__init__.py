"""Evaluators: validation metrics including grouped/per-entity variants.

Equivalent of the reference's ``evaluation`` package (Evaluator,
AreaUnderROCCurveEvaluator, RMSEEvaluator, PoissonLossEvaluator,
LogisticLossEvaluator, SquaredLossEvaluator, PrecisionAtKEvaluator,
sharded per-entity evaluators, MultiEvaluator — SURVEY.md §2.2).
"""

from photon_tpu.evaluation.metrics import (  # noqa: F401
    area_under_roc_curve,
    logistic_loss_metric,
    poisson_loss_metric,
    precision_at_k,
    rmse,
    sharded_metric,
    squared_loss_metric,
)
from photon_tpu.evaluation.evaluators import (  # noqa: F401
    Evaluator,
    MultiEvaluator,
    get_evaluator,
)
