"""Evaluator objects: named metrics with comparison direction + registry.

Mirrors the reference's ``Evaluator`` trait (``evaluate``, ``betterThan``) and
``MultiEvaluator`` (SURVEY.md §2.2).  Names accepted by :func:`get_evaluator`
follow the reference's CLI vocabulary: ``AUC``, ``RMSE``, ``LOGISTIC_LOSS``,
``POISSON_LOSS``, ``SQUARED_LOSS``, ``SMOOTHED_HINGE_LOSS``,
``PRECISION@k`` (e.g. ``PRECISION@10``), and sharded variants
``SHARDED_AUC:<id_col>`` / ``SHARDED_PRECISION@k:<id_col>``.

Evaluators accept DEVICE arrays throughout (the on-device validation
pipeline — ``game.descent``): the headline metrics are jitted JAX already,
and the sharded variants route to ``metrics.sharded_metric_device`` when
handed ``(entity_codes, num_segments)`` instead of raw entity ids — one
jitted segment-reduce per metric, one scalar host sync each.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Optional, Sequence

import numpy as np

from photon_tpu.evaluation import metrics as M


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """A named metric.  ``maximize`` gives the comparison direction
    (AUC/precision up; losses/RMSE down), used for best-model selection."""

    name: str
    fn: Callable
    maximize: bool
    entity_column: Optional[str] = None  # set for sharded evaluators
    requires_both_classes: bool = False
    # Device segment-reduce routing for sharded evaluators: the
    # metrics.sharded_metric_device kind ("auc" | "precision") and its k.
    device_kind: Optional[str] = None
    device_k: int = 10

    def evaluate(
        self,
        scores,
        labels,
        weights=None,
        entity_ids=None,
    ) -> float:
        if self.entity_column is not None:
            if entity_ids is None:
                raise ValueError(
                    f"evaluator {self.name} needs entity ids ({self.entity_column})"
                )
            if isinstance(entity_ids, tuple):
                # (entity_codes, num_segments) — the device validation
                # pipeline's pre-coded ids: one jitted segment-reduce, one
                # scalar sync (the float()).
                if self.device_kind is None:
                    raise ValueError(
                        f"evaluator {self.name} has no device sharded path"
                    )
                codes, num_segments = entity_ids
                return float(
                    M.sharded_metric_device(
                        self.device_kind, scores, labels, codes,
                        num_segments, weights, k=self.device_k,
                    )
                )
            return float(
                M.sharded_metric(
                    self.fn,
                    scores,
                    labels,
                    entity_ids,
                    weights,
                    require_both_classes=self.requires_both_classes,
                )
            )
        return float(self.fn(scores, labels, weights))

    def better_than(self, a: float, b: float) -> bool:
        """Is metric value ``a`` strictly better than ``b``? NaNs lose."""
        if np.isnan(a):
            return False
        if np.isnan(b):
            return True
        return a > b if self.maximize else a < b


class MultiEvaluator:
    """Evaluate several metrics at once; the first is the selection metric."""

    def __init__(self, evaluators: Sequence[Evaluator]):
        if not evaluators:
            raise ValueError("MultiEvaluator needs at least one evaluator")
        self.evaluators = list(evaluators)

    @property
    def primary(self) -> Evaluator:
        return self.evaluators[0]

    def evaluate(self, scores, labels, weights=None, entity_ids=None) -> dict:
        out = {}
        for ev in self.evaluators:
            ids = None
            if ev.entity_column is not None and entity_ids is not None:
                ids = (
                    entity_ids.get(ev.entity_column)
                    if isinstance(entity_ids, dict)
                    else entity_ids
                )
            out[ev.name] = ev.evaluate(scores, labels, weights, ids)
        return out


_PRECISION_RE = re.compile(r"^precision@(\d+)$")
_SHARDED_RE = re.compile(r"^sharded_(auc|precision@(\d+))(?::(\w+))?$", re.IGNORECASE)


def get_evaluator(name: str) -> Evaluator:
    key = name.strip().lower()
    if key == "auc":
        return Evaluator("AUC", M.area_under_roc_curve, maximize=True)
    if key == "rmse":
        return Evaluator("RMSE", M.rmse, maximize=False)
    if key == "logistic_loss":
        return Evaluator("LOGISTIC_LOSS", M.logistic_loss_metric, maximize=False)
    if key == "poisson_loss":
        return Evaluator("POISSON_LOSS", M.poisson_loss_metric, maximize=False)
    if key == "squared_loss":
        return Evaluator("SQUARED_LOSS", M.squared_loss_metric, maximize=False)
    if key == "smoothed_hinge_loss":
        return Evaluator(
            "SMOOTHED_HINGE_LOSS", M.smoothed_hinge_loss_metric, maximize=False
        )
    m = _PRECISION_RE.match(key)
    if m:
        k = int(m.group(1))
        return Evaluator(
            f"PRECISION@{k}",
            lambda s, l, w=None, k=k: M.precision_at_k(s, l, w, k),
            maximize=True,
        )
    # Match sharded names against the original string: the entity column
    # name is case-sensitive (only the metric part is case-folded).
    m = _SHARDED_RE.match(name.strip())
    if m:
        base, k_str, col = m.group(1).lower(), m.group(2), m.group(3) or "entity"
        if base == "auc":
            return Evaluator(
                f"SHARDED_AUC:{col}",
                M.area_under_roc_curve,
                maximize=True,
                entity_column=col,
                requires_both_classes=True,
                device_kind="auc",
            )
        k = int(k_str)
        return Evaluator(
            f"SHARDED_PRECISION@{k}:{col}",
            lambda s, l, w=None, k=k: M.precision_at_k(s, l, w, k),
            maximize=True,
            entity_column=col,
            device_kind="precision",
            device_k=k,
        )
    raise KeyError(f"unknown evaluator {name!r}")


def default_evaluators_for_task(task_type: str) -> list[Evaluator]:
    """The reference's default evaluator per task type."""
    task = task_type.lower()
    if task == "logistic_regression":
        return [get_evaluator("auc"), get_evaluator("logistic_loss")]
    if task == "linear_regression":
        return [get_evaluator("rmse"), get_evaluator("squared_loss")]
    if task == "poisson_regression":
        return [get_evaluator("poisson_loss")]
    if task == "smoothed_hinge_loss_linear_svm":
        return [get_evaluator("auc"), get_evaluator("smoothed_hinge_loss")]
    raise KeyError(f"unknown task type {task_type!r}")
