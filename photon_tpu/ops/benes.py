"""The `benes` sparse kernel: value/gradient/Hv with NO random E-access.

Fourth production kernel behind ops/sparse_grad_select (after fm /
autodiff / pallas).  The round-4 hardware windows pinned every existing
kernel to ~0.1% of HBM roofline because each pays at least one random
E-element gather or scatter (ops/KERNEL_NOTES.md, round-4 verdicts); this
kernel eliminates them:

- FORWARD (margins / ``X u``): per-entry products come from the
  slab-aligned Pallas gather (``w[dup_map]`` is a small dictionary
  gather; the per-entry indexing is Mosaic's in-VMEM ``dynamic_gather``),
  then ONE static Clos permutation (ops/clos.py — row-local shuffles +
  transposes) carries them into row-major order where per-row sums are a
  reshape-sum.
- GRADIENT / Hv reduce: per-entry products are computed in row-major
  order (a broadcast multiply — sequential), carried by the INVERSE Clos
  permutation into the aligned layout's slot order, and reduced by the
  existing Pallas position-reduce + tiny sorted segment-sum
  (ops/pallas_gather.aligned_reduce).

Both permutations come from ONE host-side edge-coloring
(clos.invert_route).  Everything the device touches is sequential
streams, lane-local shuffles, matrix transposes, and an [8,128]-table
dynamic gather — the design goal set in KERNEL_NOTES.md after the
2026-07-31 window.

The reference has no analog of any of this: its Spark shuffle IS a random
exchange (SURVEY.md §2.6); this is the TPU-native re-design of the same
data movement.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp
from jax import tree_util

from photon_tpu.ops.clos import (
    ClosRoute,
    apply_clos_grid,
    default_grid,
    invert_route,
    route_permutation,
)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class BenesAux:
    """Static routing attached to a SparseBatch for the `benes` kernel.

    ``to_slots`` permutes the zero-padded row-major entry stream (length
    ``a * b``) into aligned-layout slot order; ``to_rows`` is its inverse.
    ``n_rowmajor = n * k`` and ``n_slots = total_sub * 128`` are the real
    prefix lengths on each side of the exchange.
    """

    to_slots: ClosRoute
    to_rows: ClosRoute
    n_rowmajor: int
    n_slots: int

    @property
    def grid(self) -> int:
        return self.to_slots.a * self.to_slots.b


tree_util.register_dataclass(
    BenesAux,
    data_fields=("to_slots", "to_rows"),
    meta_fields=("n_rowmajor", "n_slots"),
)


def build_benes_aux(layout, n: int, k: int, *, a: int | None = None,
                    b: int | None = None) -> BenesAux:
    """Route the row-major <-> aligned-slot exchange for one batch layout.

    ``layout`` is the host :class:`ops.pallas_gather.AlignedLayout` (must
    carry ``src``).  Host cost is the edge-coloring
    (native/src/clos_route.cpp) — one-time per dataset, like the layout
    build itself.
    """
    n_rowmajor = n * k
    slots_src = layout.src.reshape(-1)
    n_slots = int(slots_src.size)
    need = max(n_rowmajor, n_slots)
    if a is None or b is None:
        a, b = default_grid(need)
    total = a * b
    if total < need:
        raise ValueError(f"grid {a}x{b} < required {need}")

    # Full-grid bijection: slot t takes source slots_src[t] (its row-major
    # entry) when real; pad slots and the grid tail take the unused
    # sources (row-major pad entries dropped by the layout's val != 0
    # filter, plus the zero-padded tail) in order — they only ever carry
    # zeros.  (Construction shared with the xchg route.)
    from photon_tpu.ops.vperm import full_bijection

    perm = full_bijection(slots_src, n_rowmajor, total)
    to_slots = route_permutation(perm, a, b)
    return BenesAux(
        to_slots=to_slots,
        to_rows=invert_route(to_slots),
        n_rowmajor=n_rowmajor,
        n_slots=n_slots,
    )


def _pad_to_grid(x: Array, aux: BenesAux) -> Array:
    total = aux.grid
    if x.shape[0] < total:
        x = jnp.concatenate([x, jnp.zeros(total - x.shape[0], x.dtype)])
    return x


def benes_xu_product(u: Array, al, aux: BenesAux, n: int, k: int,
                     interpret: bool | None = None) -> Array:
    """Per-row ``X u`` sums (margins minus offset) — the forward."""
    from photon_tpu.ops.pallas_gather import LANES, aligned_gather_products

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    u2d = jnp.take(u, al.dup_map, axis=0).reshape(-1, LANES)
    pw = aligned_gather_products(
        u2d, al.slab_of_tile, al.lo, al.vals, interpret=bool(interpret)
    )
    flat = _pad_to_grid(pw.reshape(-1).astype(jnp.float32), aux)
    rowmajor = apply_clos_grid(flat, aux.to_rows)[: aux.n_rowmajor]
    return rowmajor.reshape(n, k).sum(axis=1)


def benes_segment_grad(per_row: Array, vals_rowmajor: Array, al,
                       aux: BenesAux, dim: int,
                       interpret: bool | None = None) -> Array:
    """``g[f] = sum_e per_row[row_e] * val_e`` — the backward reduce."""
    from photon_tpu.ops.pallas_gather import aligned_reduce

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pv_row = (per_row[:, None] * vals_rowmajor).astype(jnp.float32)
    flat = _pad_to_grid(pv_row.reshape(-1), aux)
    slots = apply_clos_grid(flat, aux.to_slots)[: aux.n_slots]
    return aligned_reduce(
        slots.reshape(al.lo.shape), al, dim, interpret=interpret
    )
