"""Slab-aligned sparse gather — a Pallas TPU kernel that LOWERS on v5e.

This is the measured-fast building block for the fused sparse-GLM objective
(the reference's ``ValueAndGradientAggregator`` hot loop, SURVEY.md §3.4;
the reference delegates the same inner loop to native BLAS via netlib JNI —
SURVEY.md §2.4 — this module is the TPU-native analog).  It restructures the
per-entry ``w[f] * val`` computation around the one vectorized
indexed-access primitive Mosaic/v5e actually has: ``tpu.dynamic_gather``, a
per-lane sublane gather whose table is a SINGLE (8, 128) vreg.

Design (full analysis + measurement log: photon_tpu/ops/KERNEL_NOTES.md):

- Entries are laid out host-side (static, once per dataset) in tiles of
  ``TILE_SUBLANES x 128``.  Every tile reads exactly one (8, 128) *slab* of
  coefficients, selected by a scalar-prefetched slab id; each entry's lane
  holds its value and the 3-bit *position* (``lo``) of its feature within
  the slab.
- A slab is a **virtual dictionary**, not a range of consecutive features:
  ``dup_map`` names the feature stored at each (slab, position, lane), with
  duplication allowed.  The slab array is materialized per evaluation by
  one small XLA gather ``w2d = w[dup_map]`` (n_slabs*1024 elements, far
  smaller than the entry count).
- The layout builder bin-packs feature *chunks* (<= ``CHUNK_CAP`` entries)
  onto (slab, lane, position) by sorted snake placement, so hot features
  split across many lanes with zero padding and rare features share lanes
  (8 positions per lane).  Slab tile-counts are variable
  (``ceil(max-lane-load / 128)``), so one skewed lane never inflates other
  slabs — this is the fix for the round-2 layout whose padding was 34.7x
  on zipf(1.3) ids (judge-measured; see KERNEL_NOTES.md).

``AlignedLayout.padding_factor`` exposes padded/real entries; tests assert
<= 1.5x on zipf(1.3).  Both directions of the crossing stage analyzed in
KERNEL_NOTES.md are built here: :func:`aligned_segment_grad` over the
standard layout is the production GRADIENT (third kernel of
ops/sparse_grad_select, ``PHOTON_SPARSE_GRAD=pallas``), and the same
function over :func:`build_row_aligned_layout`'s transposed layout is the
FORWARD — per-row margin sums (``PHOTON_SPARSE_MARGIN=pallas``).  Default
routing stays with the pre-sorted segment-sum path (core/objective.py)
until hardware measurement picks the winner.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util
from jax.experimental import pallas as pl

Array = jax.Array

LANES = 128
SUBLANES = 8
SLAB_POSITIONS = LANES * SUBLANES  # 1024 dictionary positions per slab
TILE_SUBLANES = 128  # entry sublanes per grid step (16 vregs, 16384 entries)
CHUNK_CAP = SUBLANES * LANES  # max entries of one feature chunk (one lane, 8 tiles)


@dataclasses.dataclass(frozen=True)
class AlignedLayout:
    """Static, host-built slab-aligned entry layout for one sparse batch.

    Arrays (all ``[total_sublanes, 128]`` unless noted):

    - ``lo``: int32 slab position (0..7) of each entry's feature; arbitrary
      for pad slots.
    - ``vals``: float32 entry values; 0.0 for pad slots.
    - ``rows``: int32 source row of each entry; 0 for pad slots (safe with
      val=0).
    - ``slab_of_tile`` ``[n_tiles]``: int32 slab read by each tile.
    - ``dup_map`` ``[n_slabs * 1024]``: int32 feature id stored at each slab
      position (0 for unused positions — they gather ``w[0]`` but only ever
      multiply pad zeros).
    - ``src``: int64 ORIGINAL flat entry index (row-major ``r * k + j``)
      each slot was filled from; -1 for pad slots.  Host-only — consumed by
      the ``benes`` kernel's static-permutation routing (ops/clos.py),
      never shipped to device.
    - ``n_entries``: real (unpadded) entry count.
    """

    lo: np.ndarray
    vals: np.ndarray
    rows: np.ndarray
    slab_of_tile: np.ndarray
    dup_map: np.ndarray
    src: np.ndarray
    n_entries: int

    @property
    def n_tiles(self) -> int:
        return int(self.slab_of_tile.shape[0])

    @property
    def n_slabs(self) -> int:
        return int(self.dup_map.shape[0]) // SLAB_POSITIONS

    @property
    def padded_entries(self) -> int:
        return int(self.lo.shape[0] * LANES)

    @property
    def padding_factor(self) -> float:
        """Padded-to-real entry ratio; the layout's skew-robustness metric."""
        return self.padded_entries / max(self.n_entries, 1)


def build_aligned_layout(ids: np.ndarray, vals: np.ndarray, dim: int) -> AlignedLayout:
    """Build the slab-aligned layout from a padded-COO batch (host side).

    ``ids``/``vals`` are the framework's ``[n, k]`` padded sparse layout
    (photon_tpu.data.batch.SparseBatch); pad entries (val == 0) are dropped.
    Cost: one argsort over the nonzeros plus vectorized bin-packing — run
    once per dataset, amortized over every optimizer iteration.  Any ``dim``
    is supported (the slab dictionary decouples the layout from the feature
    space).
    """
    n, k = ids.shape
    flat_f = ids.reshape(-1).astype(np.int64)
    flat_v = vals.reshape(-1).astype(np.float32)
    flat_r = np.repeat(np.arange(n, dtype=np.int64), k)
    return _build_aligned_from_flat(flat_f, flat_r, flat_v, dim)


def build_row_aligned_layout(
    ids: np.ndarray, vals: np.ndarray
) -> AlignedLayout:
    """The TRANSPOSED layout: rows are the slab dictionary, features the
    per-entry payload.  With it the position-reduce kernel runs the
    FORWARD direction — ``aligned_segment_grad(w, row_layout, n)`` yields
    per-row sums ``sum_e w[f_e] * val_e`` (margins minus offset) — because
    the reduction is role-symmetric: it groups entries by dictionary id and
    gathers ``per_row`` at the payload index (KERNEL_NOTES.md 'crossing
    stage', option (a))."""
    n, k = ids.shape
    flat_f = ids.reshape(-1).astype(np.int64)
    flat_v = vals.reshape(-1).astype(np.float32)
    flat_r = np.repeat(np.arange(n, dtype=np.int64), k)
    return _build_aligned_from_flat(flat_r, flat_f, flat_v, n, key_role="row")


_LAYOUT_CACHE_VERSION = 1


def layout_content_hash(ids: np.ndarray, vals: np.ndarray):
    """Base sha256 over the layout-determining array content (shape +
    ids + f32 vals).  Computed ONCE per (ids, vals) and ``copy()``-ed
    per direction by :func:`_layout_cache_path` — at production scale
    the content hash is the dominant hit-path cost, and the gradient +
    transposed layouts share it."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr(ids.shape).encode())
    h.update(np.ascontiguousarray(ids).tobytes())
    h.update(np.ascontiguousarray(vals, np.float32).tobytes())
    return h


def _layout_cache_path(ids: np.ndarray, vals: np.ndarray, dim: int,
                       transposed: bool, base_hash=None):
    """Disk-cache path for an aligned layout, or None when disabled or
    below the size floor.  Layouts are pure functions of (ids, vals
    zero-pattern and values, dim); at production scale the bin-packing
    build costs tens of host-seconds per evaluation-window run, while a
    content hash plus npz load costs ~1 s — the same economics as the
    route cache, which this cache lives beside."""
    import hashlib
    import os

    from photon_tpu.utils.env import env_int

    from photon_tpu.utils.caches import resolve_cache_dir

    if ids.size < env_int("PHOTON_LAYOUT_CACHE_FLOOR", 1 << 22, minimum=1):
        return None  # small layouts rebuild faster than they hash+load
    root = resolve_cache_dir("PHOTON_LAYOUT_CACHE", "layouts")
    if root is None:
        return None
    h = (base_hash or layout_content_hash(ids, vals)).copy()
    # The transposed (row-dictionary) layout ignores ``dim`` — its
    # dictionary is the row count, already covered by ids.shape — so dim
    # stays out of that key (a dim sweep over one dataset would
    # otherwise re-build and re-store byte-identical multi-MB entries).
    h.update(
        f"|{0 if transposed else dim}|{int(transposed)}"
        f"|v{_LAYOUT_CACHE_VERSION}".encode()
    )
    return os.path.join(root, "lay_" + h.hexdigest()[:32] + ".npz")


def load_or_build_aligned_layout(
    ids: np.ndarray, vals: np.ndarray, dim: int, transposed: bool = False,
    base_hash=None,
) -> AlignedLayout:
    """:func:`build_aligned_layout` / :func:`build_row_aligned_layout`
    behind the content-keyed disk cache.  ``base_hash`` (from
    :func:`layout_content_hash`) lets a caller building BOTH directions
    pay the content hash once."""
    import logging
    import os

    ids = np.asarray(ids)
    vals = np.asarray(vals, np.float32)
    path = _layout_cache_path(ids, vals, dim, transposed, base_hash)
    if path is not None and os.path.exists(path):
        try:
            with np.load(path) as z:
                return AlignedLayout(
                    lo=z["lo"], vals=z["vals"], rows=z["rows"],
                    slab_of_tile=z["slab_of_tile"], dup_map=z["dup_map"],
                    src=z["src"], n_entries=int(z["n_entries"]),
                )
        except Exception as exc:  # noqa: BLE001 — corrupt cache = rebuild
            logging.getLogger("photon_tpu.pallas_gather").warning(
                "layout cache read failed (%s); rebuilding", exc
            )
    layout = (
        build_row_aligned_layout(ids, vals) if transposed
        else build_aligned_layout(ids, vals, dim)
    )
    if path is not None:
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.savez(
                    f, lo=layout.lo, vals=layout.vals, rows=layout.rows,
                    slab_of_tile=layout.slab_of_tile,
                    dup_map=layout.dup_map, src=layout.src,
                    n_entries=np.int64(layout.n_entries),
                )
            os.replace(tmp, path)
        except Exception as exc:  # noqa: BLE001 — best-effort cache
            logging.getLogger("photon_tpu.pallas_gather").warning(
                "layout cache write failed (%s)", exc
            )
    return layout


def _build_aligned_from_flat(
    flat_key: np.ndarray,
    flat_payload: np.ndarray,
    flat_v: np.ndarray,
    dim: int,
    key_role: str = "feature",
) -> AlignedLayout:
    """Core bin-packing builder over flat entry streams.

    ``flat_key`` is the grouping id each entry reduces into (stored in the
    slab dictionary / ``dup_map``); ``flat_payload`` is the id whose vector
    element the entry multiplies (stored in ``AlignedLayout.rows``, gathered
    at runtime as ``per_row[rows]``).  The standard gradient layout uses
    (key=feature, payload=row); the transposed forward layout swaps them.
    Pad entries (val == 0) are dropped.
    """
    keep = flat_v != 0.0
    orig = np.flatnonzero(keep)  # original flat (row-major) entry index
    flat_f, flat_v, flat_r = flat_key[keep], flat_v[keep], flat_payload[keep]
    if flat_f.size and (flat_f.min() < 0 or flat_f.max() >= dim):
        raise ValueError(f"{key_role} id out of range for dim {dim}")
    e_total = int(flat_f.size)
    if e_total == 0:
        return AlignedLayout(
            lo=np.zeros((TILE_SUBLANES, LANES), np.int32),
            vals=np.zeros((TILE_SUBLANES, LANES), np.float32),
            rows=np.zeros((TILE_SUBLANES, LANES), np.int32),
            slab_of_tile=np.zeros(1, np.int32),
            dup_map=np.zeros(SLAB_POSITIONS, np.int32),
            src=np.full((TILE_SUBLANES, LANES), -1, np.int64),
            n_entries=0,
        )

    # Feature-sorted entry order: each feature's entries are contiguous.
    order = np.argsort(flat_f, kind="stable")
    f_s, v_s, r_s = flat_f[order], flat_v[order], flat_r[order]
    orig_s = orig[order]
    counts = np.bincount(f_s, minlength=dim)
    present = np.flatnonzero(counts)
    feat_start = np.concatenate(([0], np.cumsum(counts)))[present]
    cnt = counts[present]

    # Chunk features into pieces of <= CHUNK_CAP entries.
    pieces = (cnt + CHUNK_CAP - 1) // CHUNK_CAP
    chunk_feat = np.repeat(present, pieces)
    chunk_piece = np.arange(int(pieces.sum()), dtype=np.int64) - np.repeat(
        np.concatenate(([0], np.cumsum(pieces)))[:-1], pieces
    )
    chunk_src = np.repeat(feat_start, pieces) + chunk_piece * CHUNK_CAP
    chunk_size = np.minimum(
        np.repeat(cnt, pieces) - chunk_piece * CHUNK_CAP, CHUNK_CAP
    )

    # Sorted snake placement over S slabs x 128 lanes x 8 positions.
    desc = np.argsort(-chunk_size, kind="stable")
    chunk_feat, chunk_src, chunk_size = (
        chunk_feat[desc], chunk_src[desc], chunk_size[desc]
    )
    n_chunks = chunk_size.size
    s_pos = (n_chunks + SLAB_POSITIONS - 1) // SLAB_POSITIONS
    s_ent = (e_total + TILE_SUBLANES * SLAB_POSITIONS - 1) // (
        TILE_SUBLANES * SLAB_POSITIONS
    )
    n_slabs = int(max(s_pos, s_ent, 1))
    lanes_total = n_slabs * LANES
    j = np.arange(n_chunks, dtype=np.int64)
    pos = j // lanes_total  # 0..7 by construction of n_slabs
    lane_in_pass = j % lanes_total
    lane_global = np.where(pos % 2 == 0, lane_in_pass, lanes_total - 1 - lane_in_pass)
    slab = lane_global // LANES
    lane = lane_global % LANES

    # Variable slab heights: tiles per slab from its max lane load.
    load = np.zeros((n_slabs, LANES), np.int64)
    np.add.at(load, (slab, lane), chunk_size)
    tiles_per_slab = np.maximum(
        (load.max(axis=1) + TILE_SUBLANES - 1) // TILE_SUBLANES, 1
    )
    sub_base = np.zeros(n_slabs + 1, np.int64)
    np.cumsum(tiles_per_slab * TILE_SUBLANES, out=sub_base[1:])
    total_sub = int(sub_base[-1])

    # Chunk offsets within their (slab, lane): exclusive cumsum per cell.
    cell = slab * LANES + lane
    cell_order = np.argsort(cell, kind="stable")
    sizes_o = chunk_size[cell_order]
    cell_o = cell[cell_order]
    csum = np.cumsum(sizes_o) - sizes_o
    first = np.empty(n_chunks, bool)
    first[0] = True
    np.not_equal(cell_o[1:], cell_o[:-1], out=first[1:])
    run_ids = np.cumsum(first) - 1
    off_o = csum - csum[np.flatnonzero(first)][run_ids]

    # Scatter entries into the tile arrays.
    lo_arr = np.zeros((total_sub, LANES), np.int32)
    val_arr = np.zeros((total_sub, LANES), np.float32)
    row_arr = np.zeros((total_sub, LANES), np.int32)
    rep = np.repeat  # entries expanded chunk-by-chunk (in cell_order)
    idx_in_chunk = np.arange(int(sizes_o.sum()), dtype=np.int64) - rep(csum, sizes_o)
    src = rep(chunk_src[cell_order], sizes_o) + idx_in_chunk
    dst_sub = rep(sub_base[slab[cell_order]] + off_o, sizes_o) + idx_in_chunk
    dst_lane = rep(lane[cell_order], sizes_o)
    lo_arr[dst_sub, dst_lane] = rep(pos[cell_order], sizes_o).astype(np.int32)
    val_arr[dst_sub, dst_lane] = v_s[src]
    row_arr[dst_sub, dst_lane] = r_s[src].astype(np.int32)
    src_arr = np.full((total_sub, LANES), -1, np.int64)
    src_arr[dst_sub, dst_lane] = orig_s[src]

    dup_map = np.zeros(n_slabs * SLAB_POSITIONS, np.int32)
    dup_map[slab * SLAB_POSITIONS + pos * LANES + lane] = chunk_feat.astype(np.int32)
    slab_of_tile = np.repeat(
        np.arange(n_slabs, dtype=np.int32), tiles_per_slab
    )
    return AlignedLayout(
        lo=lo_arr, vals=val_arr, rows=row_arr,
        slab_of_tile=slab_of_tile, dup_map=dup_map, src=src_arr,
        n_entries=e_total,
    )


def pad_aligned_layout(
    layout: AlignedLayout, n_slabs: int, n_tiles: int
) -> AlignedLayout:
    """Pad a layout to a common (``n_slabs``, ``n_tiles``) geometry so
    per-shard layouts can be STACKED into one leading-axis pytree for
    ``shard_map`` (VERDICT r5 item 2: per-shard aligned layouts).

    Pad tiles carry only zero values (contributing nothing) and are
    assigned slab ids so that (a) ``slab_of_tile`` stays non-decreasing —
    the position-reduce kernel re-zeroes an output block exactly when the
    tile's slab differs from its predecessor's, so a DECREASE would
    re-zero an already-accumulated real slab — and (b) every pad slab
    gets at least one tile, so its output block is initialized rather
    than left as undefined memory that would poison the gradient
    epilogue.  Pad dictionary positions hold feature 0; their partial
    sums are exact zeros, so they add nothing to ``g[0]``.
    """
    s0, t0 = layout.n_slabs, layout.n_tiles
    if n_slabs < s0 or n_tiles < t0:
        raise ValueError(
            f"target geometry ({n_slabs} slabs, {n_tiles} tiles) smaller "
            f"than the layout's ({s0}, {t0})"
        )
    pad_slabs = n_slabs - s0
    pad_tiles = n_tiles - t0
    if pad_tiles < pad_slabs:
        raise ValueError(
            f"{pad_slabs} pad slabs need at least as many pad tiles "
            f"(got {pad_tiles}); choose n_tiles >= n_tiles_i + "
            f"(n_slabs - n_slabs_i) per shard"
        )
    if pad_slabs == 0 and pad_tiles == 0:
        return layout
    pad_rows = pad_tiles * TILE_SUBLANES
    # One tile per new pad slab (ascending — keeps slab_of_tile
    # non-decreasing and initializes each pad slab's output block), then
    # the remainder on the last slab of the padded set (accumulating
    # zeros into an already-initialized block is harmless).
    new_slab_ids = np.arange(s0, n_slabs, dtype=np.int32)
    tail = np.full(pad_tiles - pad_slabs, max(n_slabs - 1, 0), np.int32)
    if pad_slabs == 0 and t0 == 0:
        raise ValueError("cannot pad an empty layout with zero slabs")
    return AlignedLayout(
        lo=np.concatenate(
            [layout.lo, np.zeros((pad_rows, LANES), np.int32)]
        ),
        vals=np.concatenate(
            [layout.vals, np.zeros((pad_rows, LANES), np.float32)]
        ),
        rows=np.concatenate(
            [layout.rows, np.zeros((pad_rows, LANES), np.int32)]
        ),
        slab_of_tile=np.concatenate(
            [layout.slab_of_tile, new_slab_ids, tail]
        ),
        dup_map=np.concatenate([
            layout.dup_map,
            np.zeros(pad_slabs * SLAB_POSITIONS, np.int32),
        ]),
        src=np.concatenate(
            [layout.src, np.full((pad_rows, LANES), -1, np.int64)]
        ),
        n_entries=layout.n_entries,
    )


def common_layout_geometry_arr(geo: np.ndarray) -> tuple[int, int]:
    """The (n_slabs, n_tiles) target every row of ``geo`` (columns:
    per-layout n_slabs, n_tiles) can be padded to under
    :func:`pad_aligned_layout`'s pad-tile constraint — the array form
    serves the sharded attach, whose geometry rows may come from a
    cross-process allgather."""
    geo = np.asarray(geo, np.int64)
    s_max = int(geo[:, 0].max())
    t_max = int((geo[:, 1] + (s_max - geo[:, 0])).max())
    return s_max, t_max


def common_layout_geometry(
    layouts: "list[AlignedLayout]",
) -> tuple[int, int]:
    """The (n_slabs, n_tiles) target that every layout in the list can be
    padded to under :func:`pad_aligned_layout`'s pad-tile constraint."""
    return common_layout_geometry_arr(np.asarray(
        [[l.n_slabs, l.n_tiles] for l in layouts], np.int64
    ))


def stack_device_layouts(layouts: "list[AlignedLayout]") -> AlignedLayoutDev:
    """Pad per-shard layouts to a common geometry and stack them into ONE
    :class:`AlignedLayoutDev` whose every leaf has a leading shard axis —
    the form ``shard_map`` shards with ``P(axis, None, ...)`` specs so
    each device sees exactly its block's layout (after the leading-axis
    squeeze in photon_tpu.parallel.distributed).  Do not call the
    gradient kernels on the stacked form directly.
    """
    s_tgt, t_tgt = common_layout_geometry(layouts)
    padded = [pad_aligned_layout(l, s_tgt, t_tgt) for l in layouts]
    perms = [
        np.argsort(p.dup_map, kind="stable").astype(np.int32)
        for p in padded
    ]
    return AlignedLayoutDev(
        lo=jnp.asarray(np.stack([p.lo for p in padded])),
        vals=jnp.asarray(np.stack([p.vals for p in padded])),
        rows=jnp.asarray(np.stack([p.rows for p in padded])),
        slab_of_tile=jnp.asarray(
            np.stack([p.slab_of_tile for p in padded])
        ),
        dup_map=jnp.asarray(np.stack([p.dup_map for p in padded])),
        grad_perm=jnp.asarray(np.stack(perms)),
        sorted_feats=jnp.asarray(np.stack([
            p.dup_map[perm] for p, perm in zip(padded, perms)
        ])),
    )


def _gather_kernel(smap_ref, w_ref, lo_ref, v_ref, o_ref):
    """One tile: 16 single-vreg dynamic_gathers + multiply."""
    del smap_ref  # consumed by the index_map only
    w = w_ref[...]  # [8, 128] — this tile's coefficient slab
    for i in range(TILE_SUBLANES // SUBLANES):
        sl = slice(i * SUBLANES, (i + 1) * SUBLANES)
        o_ref[sl, :] = (
            jnp.take_along_axis(w, lo_ref[sl, :], axis=0) * v_ref[sl, :]
        )


@functools.partial(jax.jit, static_argnames=("interpret",))
def aligned_gather_products(
    w2d: Array,
    slab_of_tile: Array,
    lo: Array,
    vals: Array,
    interpret: bool = False,
) -> Array:
    """Per-entry ``w[f] * val`` over a slab-aligned layout, feature-major.

    ``w2d`` is the dup-gathered slab array ``w[dup_map].reshape(-1, 128)``
    (see :func:`gather_products`); the layout arrays come from
    :func:`build_aligned_layout` (device-put by the caller).  Returns
    ``[total_sublanes, 128]`` float32 products (0.0 in pad slots).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles = slab_of_tile.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((SUBLANES, LANES), lambda i, smap: (smap[i], 0)),
            pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, smap: (i, 0)),
            pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, smap: (i, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, smap: (i, 0)),
    )
    return pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((n_tiles * TILE_SUBLANES, LANES), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(slab_of_tile, w2d, lo, vals)


def gather_products(w: Array, layout: AlignedLayout, interpret: bool = False) -> Array:
    """Convenience wrapper: dup-gather the slab dictionary, run the kernel."""
    w2d = jnp.take(w, jnp.asarray(layout.dup_map), axis=0).reshape(-1, LANES)
    return aligned_gather_products(
        w2d,
        jnp.asarray(layout.slab_of_tile),
        jnp.asarray(layout.lo),
        jnp.asarray(layout.vals),
        interpret=interpret,
    )


@dataclasses.dataclass(frozen=True)
class AlignedLayoutDev:
    """Device-resident :class:`AlignedLayout` plus the gradient-reduction
    statics, registered as a jit pytree (all arrays are dynamic leaves —
    shapes are static per dataset, so one compiled program serves every
    optimizer iteration).

    ``grad_perm`` / ``sorted_feats`` are the host-precomputed epilogue of the
    aligned GRADIENT path (see :func:`aligned_segment_grad`): a stable
    argsort of ``dup_map`` so the per-dictionary-slot partial sums (one per
    (slab, position, lane) — ``n_slabs * 1024`` values, far fewer than the
    entry count) reduce into coefficients with a tiny
    ``segment_sum(indices_are_sorted=True)`` — no unsorted scatter anywhere.
    """

    lo: Array  # [total_sub, 128] int32
    vals: Array  # [total_sub, 128] float (storage dtype; f32 arithmetic)
    rows: Array  # [total_sub, 128] int32
    slab_of_tile: Array  # [n_tiles] int32, non-decreasing
    dup_map: Array  # [n_slabs * 1024] int32
    grad_perm: Array  # [n_slabs * 1024] int32 — stable argsort of dup_map
    sorted_feats: Array  # [n_slabs * 1024] int32 — dup_map[grad_perm]

    @property
    def n_slabs(self) -> int:
        return int(self.dup_map.shape[0]) // SLAB_POSITIONS


tree_util.register_dataclass(
    AlignedLayoutDev,
    data_fields=(
        "lo", "vals", "rows", "slab_of_tile", "dup_map", "grad_perm",
        "sorted_feats",
    ),
    meta_fields=(),
)


def device_layout(layout: AlignedLayout) -> AlignedLayoutDev:
    """Put an :class:`AlignedLayout` on device with the gradient statics."""
    perm = np.argsort(layout.dup_map, kind="stable").astype(np.int32)
    return AlignedLayoutDev(
        lo=jnp.asarray(layout.lo),
        vals=jnp.asarray(layout.vals),
        rows=jnp.asarray(layout.rows),
        slab_of_tile=jnp.asarray(layout.slab_of_tile),
        dup_map=jnp.asarray(layout.dup_map),
        grad_perm=jnp.asarray(perm),
        sorted_feats=jnp.asarray(layout.dup_map[perm]),
    )


def _position_reduce_kernel(smap_ref, pv_ref, lo_ref, o_ref):
    """One tile: fold per-entry products into the slab's [8, 128] partial
    sums — ``o[p, lane] += sum_sublane where(lo == p, products)``.

    Tiles of one slab are consecutive in the grid (``slab_of_tile`` is
    non-decreasing by construction), so the output block is revisited and
    accumulates across them; it is zeroed on the first tile of each slab.
    """
    i = pl.program_id(0)
    prev = smap_ref[jnp.maximum(i - 1, 0)]

    @pl.when(jnp.logical_or(i == 0, smap_ref[i] != prev))
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    pv = pv_ref[...]  # [TILE_SUBLANES, 128] per-entry products
    lo = lo_ref[...]  # [TILE_SUBLANES, 128] slab positions
    for p in range(SUBLANES):
        contrib = jnp.sum(
            jnp.where(lo == p, pv, 0.0), axis=0, keepdims=True
        )  # [1, 128]
        o_ref[p : p + 1, :] += contrib


@functools.partial(jax.jit, static_argnames=("n_slabs", "interpret"))
def _position_partial_sums(
    slab_of_tile: Array, pv: Array, lo: Array, n_slabs: int, interpret: bool
) -> Array:
    from jax.experimental.pallas import tpu as pltpu

    n_tiles = slab_of_tile.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, smap: (i, 0)),
            pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, smap: (i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (SUBLANES, LANES), lambda i, smap: (smap[i], 0)
        ),
    )
    return pl.pallas_call(
        _position_reduce_kernel,
        out_shape=jax.ShapeDtypeStruct((n_slabs * SUBLANES, LANES), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(slab_of_tile, pv, lo)


def aligned_segment_grad(
    per_row: Array,
    al: AlignedLayoutDev,
    dim: int,
    interpret: bool | None = None,
) -> Array:
    """``g[f] = sum_e per_row[row_e] * val_e`` over the aligned layout — the
    Pallas production gradient (third kernel of ops/sparse_grad_select).

    Stages (KERNEL_NOTES.md 'crossing stage', option b):

    1. XLA gather ``per_row[rows] * vals`` — same E-gather the fm path pays;
    2. Pallas per-tile 8-way masked position reduce → one partial sum per
       dictionary slot (``n_slabs * 1024`` values ≪ E) — this REPLACES the
       fm path's E-element segment sum;
    3. static-permutation gather + tiny sorted segment-sum over ``dup_map``
       into the ``dim`` coefficients (duplicated features merge here).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pv = (
        jnp.take(per_row, al.rows.reshape(-1), axis=0).reshape(al.rows.shape)
        * al.vals
    ).astype(jnp.float32)
    return aligned_reduce(pv, al, dim, interpret=interpret)


def aligned_reduce(
    pv: Array,
    al: AlignedLayoutDev,
    dim: int,
    interpret: bool | None = None,
) -> Array:
    """Stages 2+3 of :func:`aligned_segment_grad` alone: fold per-slot
    products ``pv`` (``[total_sub, 128]``, zeros in pad slots) into the
    ``dim`` coefficients.  The ``benes`` kernel (ops/benes.py) computes its
    products by static permutation instead of the E-gather and enters
    here."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    partial = _position_partial_sums(
        al.slab_of_tile, pv, al.lo, n_slabs=al.n_slabs, interpret=bool(interpret)
    )
    flat = jnp.take(partial.reshape(-1), al.grad_perm, axis=0)
    return jax.ops.segment_sum(
        flat, al.sorted_feats, num_segments=dim, indices_are_sorted=True
    )


_REDUCE_SUPPORTED: dict = {}


def reduce_kernel_supported() -> bool:
    """Eager Mosaic capability probe for the position-reduce kernel (cached
    per backend).  Same rationale as ops/pallas_sparse.kernel_supported: a
    lowering failure surfaces when the ENCLOSING jit compiles, so probe
    compiled (non-interpret) lowering once, eagerly, on a one-tile input."""
    backend = jax.default_backend()
    if backend not in _REDUCE_SUPPORTED:
        try:
            # Probe inputs under ensure_compile_time_eval: the first call
            # often happens while an enclosing jit is being traced
            # (kernel selection at trace time), where bare jnp.zeros
            # would become tracers, the probe would raise, and the except
            # would cache a spurious "unsupported" for the whole process.
            # The lower/compile itself stays OUTSIDE the escape hatch
            # (eval-trace has no rules for pallas primitives).
            with jax.ensure_compile_time_eval():
                probe_args = (
                    jnp.zeros(1, jnp.int32),
                    jnp.zeros((TILE_SUBLANES, LANES), jnp.float32),
                    jnp.zeros((TILE_SUBLANES, LANES), jnp.int32),
                )
            _position_partial_sums.lower(
                *probe_args, n_slabs=1, interpret=False
            ).compile()
            _REDUCE_SUPPORTED[backend] = True
        except Exception:  # noqa: BLE001 — any lowering failure means "no"
            _REDUCE_SUPPORTED[backend] = False
    return _REDUCE_SUPPORTED[backend]


def aligned_grad_reference(
    per_row: np.ndarray, layout: AlignedLayout, dim: int
) -> np.ndarray:
    """NumPy reference for tests: direct scatter over the layout's entries."""
    g = np.zeros(dim, np.float64)
    n_sub = layout.lo.shape[0]
    tile_of_sub = np.arange(n_sub) // TILE_SUBLANES
    s = layout.slab_of_tile[tile_of_sub]
    f = layout.dup_map[
        s[:, None] * SLAB_POSITIONS
        + layout.lo * LANES
        + np.arange(LANES)[None, :]
    ]
    np.add.at(
        g, f.reshape(-1),
        (np.asarray(per_row)[layout.rows] * layout.vals).reshape(-1),
    )
    return g.astype(np.float32)


def gather_products_reference(w: np.ndarray, layout: AlignedLayout) -> np.ndarray:
    """NumPy reference for tests: resolve each slot's feature via dup_map."""
    n_sub = layout.lo.shape[0]
    tile_of_sub = np.arange(n_sub) // TILE_SUBLANES
    s = layout.slab_of_tile[tile_of_sub]  # [n_sub]
    f = layout.dup_map[
        s[:, None] * SLAB_POSITIONS
        + layout.lo * LANES
        + np.arange(LANES)[None, :]
    ]
    return w[f] * layout.vals
