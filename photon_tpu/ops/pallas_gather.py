"""Group-aligned sparse gather — a Pallas TPU kernel that LOWERS on v5e.

This is the measured-fast building block for the fused sparse-GLM objective
(the reference's ``ValueAndGradientAggregator`` hot loop, SURVEY.md §3.4).
Plain XLA executes the ``w[ids]`` gather of a sparse margin computation at
~110M elements/s on v5e (scalar-latency bound: ~8 cycles per element); this
kernel runs the same gather at >2G elements/s (measured 2.46G/s on the bench
workload's 33.5M nonzeros — 22x) by restructuring the problem around the one
vectorized indexed-access primitive Mosaic/v5e actually has:
``tpu.dynamic_gather``, a per-lane sublane gather whose source is a SINGLE
(8, 128) vreg.

Design (see photon_tpu/ops/KERNEL_NOTES.md for the full analysis):

- The coefficient vector ``w`` (dim d) is viewed as ``W2[d//128, 128]`` with
  feature ``f`` at row ``f // 128``, lane ``f % 128``.  An (8, 128) vreg
  slab of W2 — one "feature group" ``g`` — covers the 1024 consecutive
  features ``[1024*g, 1024*(g+1))``.
- Nonzero entries are laid out host-side (static, once per dataset) in a
  group-aligned order: entry with feature ``f`` is placed in lane
  ``f % 128``, in a tile whose entries ALL belong to group ``f // 1024``,
  carrying its 3-bit sublane index ``(f // 128) % 8``.  Per-(group, lane)
  slots are padded (pad entries have value 0, so they contribute nothing).
- The kernel then needs exactly one ``dynamic_gather`` per entry vreg: the
  tile's W2 slab is selected by scalar-prefetched group id, and every lane
  fetches its own feature from its own column.

The output (per-entry ``w[f] * val``) is produced in this feature-major
layout.  That is directly what feature-space reductions need; routing the
products back to row-major order (for per-row margin sums) is the remaining
"crossing" stage documented in KERNEL_NOTES.md — which is why the full
objective does not yet route through this kernel by default.

Reference parity note: the reference delegates this inner loop to native
BLAS (netlib JNI) where the JVM is too slow (SURVEY.md §2.4); this module is
the TPU-native analog — a hand-written kernel where the XLA-compiled path is
measurably latency-bound.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

LANES = 128
SUBLANES = 8
GROUP_FEATURES = LANES * SUBLANES  # 1024 features per (8, 128) W2 slab
TILE_SUBLANES = 128  # entry sublanes per grid step (16 vregs, 16384 entries)


@dataclasses.dataclass(frozen=True)
class AlignedLayout:
    """Static, host-built group-aligned entry layout for one sparse batch.

    Arrays (all ``[n_tiles * TILE_SUBLANES, 128]`` unless noted):

    - ``lo``: int32 sublane index of each entry's feature within its group's
      W2 slab (``(f // 128) % 8``); arbitrary for pad slots.
    - ``vals``: float32 entry values; 0.0 for pad slots.
    - ``rows``: int32 source row of each entry; 0 for pad slots (safe with
      val=0).
    - ``group_of_tile`` ``[n_tiles]``: int32 feature group of each tile.
    - ``n_entries``: real (unpadded) entry count.
    """

    lo: np.ndarray
    vals: np.ndarray
    rows: np.ndarray
    group_of_tile: np.ndarray
    n_entries: int

    @property
    def n_tiles(self) -> int:
        return int(self.group_of_tile.shape[0])

    @property
    def padded_entries(self) -> int:
        return int(self.lo.shape[0] * LANES)


def build_aligned_layout(ids: np.ndarray, vals: np.ndarray, dim: int) -> AlignedLayout:
    """Build the group-aligned layout from a padded-COO batch (host side).

    ``ids``/``vals`` are the framework's ``[n, k]`` padded sparse layout
    (photon_tpu.data.batch.SparseBatch).  Pad entries (val == 0) are dropped
    here and re-padded per (group, lane) slot as needed.  Cost: one argsort
    over the nonzeros — run once per dataset, amortized over every optimizer
    iteration.
    """
    if dim % GROUP_FEATURES:
        raise ValueError(f"dim must be a multiple of {GROUP_FEATURES}, got {dim}")
    n, k = ids.shape
    flat_f = ids.reshape(-1).astype(np.int64)
    flat_v = vals.reshape(-1).astype(np.float32)
    flat_r = np.repeat(np.arange(n, dtype=np.int64), k)
    keep = flat_v != 0.0
    flat_f, flat_v, flat_r = flat_f[keep], flat_v[keep], flat_r[keep]

    group = flat_f // GROUP_FEATURES
    lane = flat_f % LANES
    lo = (flat_f // LANES) % SUBLANES

    # Sort by (group, lane); entries within a (group, lane) cell fill that
    # lane's sublane slots of the group's tiles.
    order = np.lexsort((lane, group))
    group, lane, lo, flat_v, flat_r = (
        group[order], lane[order], lo[order], flat_v[order], flat_r[order]
    )

    n_groups = dim // GROUP_FEATURES
    # counts[g, l] = entries in that cell; tiles per group sized by max lane.
    counts = np.zeros((n_groups, LANES), np.int64)
    np.add.at(counts, (group, lane), 1)
    sub_per_group = counts.max(axis=1)  # sublane slots needed per group
    # Round up to the tile granularity so every tile is group-pure.
    sub_per_group = np.ceil(sub_per_group / TILE_SUBLANES).astype(np.int64) * TILE_SUBLANES
    sub_per_group = np.maximum(sub_per_group, TILE_SUBLANES)
    sub_start = np.zeros(n_groups + 1, np.int64)
    np.cumsum(sub_per_group, out=sub_start[1:])
    total_sub = int(sub_start[-1])

    lo_arr = np.zeros((total_sub, LANES), np.int32)
    val_arr = np.zeros((total_sub, LANES), np.float32)
    row_arr = np.zeros((total_sub, LANES), np.int32)

    # Slot index of each entry within its (group, lane) cell = rank in the
    # lexsorted order (stable within cell).
    cell_key = group * LANES + lane
    first = np.empty_like(cell_key, dtype=bool)
    first[0] = True
    np.not_equal(cell_key[1:], cell_key[:-1], out=first[1:])
    run_start = np.repeat(np.flatnonzero(first), np.diff(
        np.append(np.flatnonzero(first), cell_key.size)))
    slot = np.arange(cell_key.size, dtype=np.int64) - run_start

    dest_sub = sub_start[group] + slot
    lo_arr[dest_sub, lane] = lo.astype(np.int32)
    val_arr[dest_sub, lane] = flat_v
    row_arr[dest_sub, lane] = flat_r.astype(np.int32)

    group_of_tile = np.repeat(
        np.arange(n_groups, dtype=np.int32), sub_per_group // TILE_SUBLANES
    )
    return AlignedLayout(
        lo=lo_arr, vals=val_arr, rows=row_arr,
        group_of_tile=group_of_tile, n_entries=int(flat_v.size),
    )


def _gather_kernel(gmap_ref, w_ref, lo_ref, v_ref, o_ref):
    """One tile: 16 single-vreg dynamic_gathers + multiply."""
    del gmap_ref  # consumed by the index_map only
    w = w_ref[...]  # [8, 128] — this tile's feature-group slab of W2
    for i in range(TILE_SUBLANES // SUBLANES):
        sl = slice(i * SUBLANES, (i + 1) * SUBLANES)
        o_ref[sl, :] = (
            jnp.take_along_axis(w, lo_ref[sl, :], axis=0) * v_ref[sl, :]
        )


@functools.partial(jax.jit, static_argnames=("interpret",))
def aligned_gather_products(
    w: Array,
    group_of_tile: Array,
    lo: Array,
    vals: Array,
    interpret: bool = False,
) -> Array:
    """Per-entry ``w[f] * val`` over a group-aligned layout, feature-major.

    ``w`` is the flat ``[d]`` coefficient vector; the layout arrays come from
    :func:`build_aligned_layout` (device-put by the caller).  Returns
    ``[total_sublanes, 128]`` float32 products (0.0 in pad slots).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    d = w.shape[0]
    w2 = w.reshape(d // LANES, LANES)
    n_tiles = group_of_tile.shape[0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((SUBLANES, LANES), lambda i, gmap: (gmap[i], 0)),
            pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, gmap: (i, 0)),
            pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, gmap: (i, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_SUBLANES, LANES), lambda i, gmap: (i, 0)),
    )
    return pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((n_tiles * TILE_SUBLANES, LANES), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(group_of_tile, w2, lo, vals)


def gather_products_reference(w: np.ndarray, layout: AlignedLayout) -> np.ndarray:
    """NumPy reference for tests: reconstruct f from (tile group, lo, lane)."""
    n_sub = layout.lo.shape[0]
    tile_of_sub = np.arange(n_sub) // TILE_SUBLANES
    g = layout.group_of_tile[tile_of_sub]  # [n_sub]
    f = (g[:, None] * GROUP_FEATURES
         + layout.lo * LANES
         + np.arange(LANES)[None, :])
    return w[f] * layout.vals
