"""Custom TPU kernels (Pallas/Mosaic) for the framework's hot ops.

The reference's innermost loops run per-partition on Breeze/BLAS via JNI
(SURVEY.md §2.4); here the device compute path is XLA, with Pallas kernels
where fusion beyond XLA's reach pays — currently the fused sparse GLM
value-and-gradient pass (:mod:`photon_tpu.ops.pallas_sparse`)."""

from photon_tpu.ops.pallas_sparse import (
    fused_value_and_grad,
    pallas_enabled,
)

__all__ = ["fused_value_and_grad", "pallas_enabled"]
