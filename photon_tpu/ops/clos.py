"""Static-permutation routing: arbitrary E-element permutations as
row-local shuffles + transposes (the `benes` kernel's host side).

Motivation (ops/KERNEL_NOTES.md, round-4 hardware verdicts): XLA lowers
random E-element gathers/scatters on TPU at ~125 Melem/s (~0.1% of HBM
roofline), and every sparse-GLM kernel pays one per direction for the
row-order <-> feature-order exchange.  That exchange is a STATIC
permutation of the entry array, so it can be pre-routed on the host into
a form with NO random device memory access:

    y = x[perm]   ==   P3_rows( T( P2_rows( T( P1_rows(x) ) ) ) )

where x is viewed as an [A, B] grid, T is a matrix transpose, and each
P*_rows applies an independent permutation per row (Clos/Slepian-Duguid
3-stage factorization; see native/src/clos_route.cpp for the
edge-coloring construction and proof sketch).  Row-local permutations in
turn either lower to lane shuffles inside a Pallas kernel or stay as
``jnp.take_along_axis`` (whose within-row gather XLA can tile better
than a flat E-gather — measured per backend, like every kernel choice in
this package).

Reference parity note: the reference has no analog — its Spark shuffle
IS the random exchange (SURVEY.md §2.6); this module is the TPU-native
replacement that makes the exchange bandwidth-bound.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

import jax.numpy as jnp
from jax import tree_util


@dataclasses.dataclass(frozen=True)
class ClosRoute:
    """Device-ready routing for one static permutation ``y = x[perm]``.

    ``p1`` [A, B], ``p2`` [B, A], ``p3`` [A, B] are within-row gather
    index arrays (int32): stage k computes
    ``x = jnp.take_along_axis(x, pk, axis=1)`` with transposes between
    stages.  ``n`` is the unpadded element count (perm length); the grid
    holds ``A * B >= n`` with an identity tail.
    """

    n: int
    a: int
    b: int
    p1: jnp.ndarray
    p2: jnp.ndarray
    p3: jnp.ndarray


# Jit pytree: index arrays are dynamic leaves; the grid shape is static so
# one compiled program serves every evaluation over the same layout.
tree_util.register_dataclass(
    ClosRoute, data_fields=("p1", "p2", "p3"), meta_fields=("n", "a", "b")
)


def default_grid(n: int) -> tuple[int, int]:
    """Most-square power-of-two (A, B) grid covering ``n`` elements.

    B must be a power of two for the Euler-split coloring; A powers of two
    keep the inter-stage transposes tile-friendly.  Shared by
    :func:`route_permutation` and ops/benes.build_benes_aux so the aux
    grid and the router default cannot diverge.
    """
    bits = max(1, int(np.ceil(np.log2(max(n, 2)))))
    a = 1 << ((bits + 1) // 2)
    b = 1 << (bits - (bits + 1) // 2)
    return a, b


def _edge_color_native(l: np.ndarray, r: np.ndarray, a: int,
                       b: int) -> Optional[np.ndarray]:
    from photon_tpu.native import build as native_build

    lib = native_build.get_lib()
    if lib is None:
        return None
    import ctypes

    e = np.int64(l.size)
    color = np.empty(l.size, dtype=np.int32)
    rc = lib.clos_edge_color(
        e, np.int32(a), np.int32(b),
        l.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        r.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        color.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc == -3:
        raise ValueError(
            f"permutation too large for the native router ({l.size:,} "
            f"edges > INT32_MAX/2 — head prefix sums reach 2E); shard "
            f"the layout before routing"
        )
    if rc != 0:
        raise RuntimeError(f"clos_edge_color failed: rc={rc}")
    return color


def _edge_color_python(l: np.ndarray, r: np.ndarray, a: int,
                       b: int) -> np.ndarray:
    """Pure-Python Euler-split coloring (fallback + test oracle).

    Same algorithm as the native version; fine for test sizes, far too
    slow for production E.
    """
    if b & (b - 1):
        raise ValueError(f"B must be a power of two, got {b}")
    color = np.empty(l.size, dtype=np.int32)

    def split(edges: np.ndarray, base: int, span: int) -> None:
        if span == 1:
            color[edges] = base
            return
        # Adjacency over 2a vertices: vertex -> list of (edge, other).
        adj: list[list[int]] = [[] for _ in range(2 * a)]
        for e in edges:
            adj[l[e]].append(int(e))
            adj[a + r[e]].append(int(e))
        cursor = [0] * (2 * a)
        used = {}
        halves: tuple[list[int], list[int]] = ([], [])
        for v0 in range(2 * a):
            while cursor[v0] < len(adj[v0]):
                if adj[v0][cursor[v0]] in used:
                    cursor[v0] += 1
                    continue
                circuit: list[int] = []
                vstack = [v0]
                estack: list[int] = [-1]
                while vstack:
                    v = vstack[-1]
                    while (cursor[v] < len(adj[v])
                           and adj[v][cursor[v]] in used):
                        cursor[v] += 1
                    if cursor[v] < len(adj[v]):
                        e = adj[v][cursor[v]]
                        used[e] = True
                        other = (a + r[e]) if v == l[e] else l[e]
                        vstack.append(other)
                        estack.append(e)
                    else:
                        e = estack.pop()
                        vstack.pop()
                        if e >= 0:
                            circuit.append(e)
                for i, e in enumerate(circuit):
                    halves[i % 2].append(e)
        assert len(halves[0]) == len(halves[1]) == edges.size // 2
        split(np.asarray(halves[0]), base, span // 2)
        split(np.asarray(halves[1]), base + span // 2, span // 2)

    split(np.arange(l.size, dtype=np.int64), 0, b)
    return color


def route_permutation(perm: np.ndarray, a: Optional[int] = None,
                      b: Optional[int] = None, *,
                      use_native: bool = True,
                      device: bool = True) -> ClosRoute:
    """Factor ``y = x[perm]`` into the 3-stage row-local form.

    ``a``/``b`` default to the most square power-of-two grid covering
    ``len(perm)`` (padded with an identity tail when a*b > n).
    ``device=False`` keeps the stage arrays as host numpy (callers that
    re-factor stages, like ops/vperm, avoid shipping hundreds of MB of
    intermediate routing through the device tunnel).
    """
    perm = np.ascontiguousarray(perm, dtype=np.int64)
    n = perm.size
    if a is None or b is None:
        a, b = default_grid(n)
    total = a * b
    if total < n:
        raise ValueError(f"grid {a}x{b} smaller than permutation ({n})")
    if perm.size and (
        perm.min() < 0 or perm.max() >= n
        or np.bincount(perm, minlength=n).max() != 1
    ):
        raise ValueError("perm is not a permutation of [0, n)")
    full = np.arange(total, dtype=np.int64)
    # Identity tail (when total > n) keeps padding elements in place; they
    # are part of the grid but never read back.
    full[:n] = perm

    src_row = (full // b).astype(np.int32)   # a_s per destination index
    dst_row = (np.arange(total, dtype=np.int64) // b).astype(np.int32)
    src_col = (full % b).astype(np.int32)
    dst_col = (np.arange(total, dtype=np.int64) % b).astype(np.int32)

    color = None
    if use_native:
        color = _edge_color_native(src_row, dst_row, a, b)
    if color is None:
        if total >= (1 << 18):
            # The Python fallback is a per-edge interpreter loop over
            # log2(b) levels — hours at production scale.  Fail fast
            # instead of silently stalling batch attach.
            raise RuntimeError(
                f"native clos_edge_color unavailable and permutation too "
                f"large ({total:,} elements) for the Python fallback; "
                f"build the native library (g++) or unset "
                f"PHOTON_SPARSE_GRAD=benes"
            )
        color = _edge_color_python(src_row, dst_row, a, b)

    # Stage index arrays (see clos_route.cpp header for the derivation):
    #   P1[a_s, c]   = b_s   (source-row shuffle into color columns)
    #   P2[c, a_d]   = a_s   (middle-row shuffle routing to dest rows)
    #   P3[a_d, b_d] = c     (dest-row shuffle into final columns)
    p1 = np.empty((a, b), dtype=np.int32)
    p2 = np.empty((b, a), dtype=np.int32)
    p3 = np.empty((a, b), dtype=np.int32)
    p1[src_row, color] = src_col
    p2[color, dst_row] = src_row
    p3[dst_row, dst_col] = color
    if not device:
        return ClosRoute(n=n, a=a, b=b, p1=p1, p2=p2, p3=p3)
    return ClosRoute(n=n, a=a, b=b, p1=jnp.asarray(p1), p2=jnp.asarray(p2),
                     p3=jnp.asarray(p3))


def apply_clos_grid(x: jnp.ndarray, route: ClosRoute) -> jnp.ndarray:
    """Apply the routed permutation to a FULL-GRID flat array (jit-safe):
    ``x`` has ``a * b`` elements and so does the result.  The device-side
    stage implementation lives here — one home, so swapping the
    take_along_axis stages for a Pallas lane-shuffle kernel (pending the
    next hardware window's probe) changes exactly this function."""
    total = route.a * route.b
    g = x.reshape(route.a, route.b)
    g = jnp.take_along_axis(g, route.p1, axis=1)
    g = g.T
    g = jnp.take_along_axis(g, route.p2, axis=1)
    g = g.T
    g = jnp.take_along_axis(g, route.p3, axis=1)
    return g.reshape(total)


def apply_clos(x: jnp.ndarray, route: ClosRoute) -> jnp.ndarray:
    """Apply the routed permutation to a flat array (jit-safe).

    Equivalent to ``x[perm]`` for the routed perm; pads with zeros to the
    grid, runs the 3 row-local stages + 2 transposes, and slices the
    result back to ``route.n``.
    """
    total = route.a * route.b
    if x.shape[0] != route.n:
        raise ValueError(f"length {x.shape[0]} != routed n {route.n}")
    if total > route.n:
        x = jnp.concatenate(
            [x, jnp.zeros((total - route.n,), dtype=x.dtype)]
        )
    return apply_clos_grid(x, route)[: route.n]


def invert_route(route: ClosRoute, n: Optional[int] = None) -> ClosRoute:
    """The inverse permutation's route, from the same routing.

    ``(P1 . T . P2 . T . P3)^-1 = P3^-1 . T . P2^-1 . T . P1^-1`` — the
    same 3-stage structure with each stage's rows inverted row-wise
    (``argsort`` of a permutation row is its inverse), so ONE edge-coloring
    serves both directions of an exchange.  ``n`` sets the unpadded length
    of the inverse (defaults to the forward's)."""

    def inv_rows(p: jnp.ndarray) -> jnp.ndarray:
        return jnp.argsort(p, axis=1).astype(p.dtype)

    return ClosRoute(
        n=route.n if n is None else n, a=route.a, b=route.b,
        p1=inv_rows(route.p3), p2=inv_rows(route.p2), p3=inv_rows(route.p1),
    )


