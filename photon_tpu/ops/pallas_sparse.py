"""Fused sparse GLM value-and-gradient as a Pallas TPU kernel.

The hot op of the whole framework is one objective evaluation over a padded
sparse batch (SURVEY.md §3.4: the reference's ValueAndGradientAggregator
fold — ``margin = w.x + offset; (l, dz) = loss; grad += weight*dz*x``).
Under plain XLA autodiff this runs as two passes with the gathered ``w[ids]``
block materialized in each (forward gather + transpose scatter).  This
kernel fuses the entire evaluation — gather, margin, pointwise loss and its
derivative, weighted reduction, and the gradient scatter — into ONE pass
over the nonzeros, streaming row blocks through VMEM while the coefficient
vector and the gradient accumulator stay resident on-chip.

Mosaic lowering notes: gathers/scatters are expressed on 2-D operands
(``w`` and the gradient live as ``[d, 1]``; Mosaic rejects 1-D gathers), and
grid iterations on a TPU core run sequentially, so the kernel accumulates
the loss scalar and the gradient across row blocks in its output refs (the
standard Pallas accumulation pattern).

The kernel is exact (no approximation): tests check it against
``jax.value_and_grad`` of the XLA objective to float tolerance.  On
non-TPU backends it runs in interpreter mode (slow — tests only); real use
is opt-in via ``PHOTON_TPU_PALLAS=1``, and the caller
(GlmObjective.value_and_grad) falls back to the XLA path if Mosaic cannot
lower the kernel on the local TPU generation.

Mosaic support status (measured on TPU v5e, jax 0.9): vector scatter-add is
``Unimplemented`` in the TC lowering and gathers only lower in restricted
``take_along_axis`` forms, so on that generation the flag falls back to XLA
— whose scatter lowering (sort-based segmented reduction) is the efficient
implementation of this op on TPU anyway.  The kernel is kept (a) as the
specification of the fused op, (b) for interpret-mode testing, and (c) for
Mosaic versions that add vector scatter.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from photon_tpu.core.losses import PointwiseLoss

Array = jax.Array


def pallas_enabled() -> bool:
    """Opt-in flag for routing GlmObjective through the fused kernel."""
    return os.environ.get("PHOTON_TPU_PALLAS", "") not in ("", "0")


_KERNEL_SUPPORTED: dict = {}


def kernel_supported(loss: PointwiseLoss, nnz_capacity: int, dim: int) -> bool:
    """Eager capability probe, cached per (loss, nnz capacity, coefficient
    dim): can Mosaic lower the fused kernel for THIS loss and layout?  A
    try/except around the traced call cannot catch lowering failures (they
    surface when the ENCLOSING jit compiles, e.g. inside the optimizer's
    while_loop), and support is shape-dependent — the kernel's scatter
    block shapes depend on the coefficient dimension, so probing a stand-in
    dim would cache the wrong answer (ADVICE r1) — so probe the
    configuration actually about to run, eagerly, once."""
    key = (loss.name, nnz_capacity, dim)
    if key not in _KERNEL_SUPPORTED:
        try:
            # Probe inputs under ensure_compile_time_eval: the first call
            # routinely happens while the optimizer's while_loop is being
            # traced, where bare jnp.zeros would be tracers and the probe
            # would raise, caching a spurious "unsupported".  The
            # .lower().compile() itself runs OUTSIDE the escape hatch —
            # under it, pallas kernel bodies hit eval-trace rules
            # (program_id has none) — and is ambient-trace-safe on its
            # own (AOT lowering opens a fresh trace).
            with jax.ensure_compile_time_eval():
                args = (
                    loss,
                    jnp.zeros(dim, jnp.float32),
                    jnp.zeros((8, nnz_capacity), jnp.int32),
                    jnp.zeros((8, nnz_capacity), jnp.float32),
                    jnp.zeros(8, jnp.float32),
                    jnp.zeros(8, jnp.float32),
                    jnp.ones(8, jnp.float32),
                )
            # Exercises the full Mosaic pipeline without polluting the
            # ambient trace (fused_value_and_grad is jitted).
            fused_value_and_grad.lower(*args).compile()
            _KERNEL_SUPPORTED[key] = True
        except Exception:
            _KERNEL_SUPPORTED[key] = False
    return _KERNEL_SUPPORTED[key]


def _kernel(loss: PointwiseLoss, w_ref, ids_ref, vals_ref, y_ref, off_ref,
            wt_ref, val_ref, grad_ref):
    """One row block: fused margin -> loss/dz -> loss sum + grad scatter."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        val_ref[...] = jnp.zeros_like(val_ref)
        grad_ref[...] = jnp.zeros_like(grad_ref)

    w = w_ref[...]  # [d, 1]
    ids = ids_ref[...]  # [bn, k] int32
    vals = vals_ref[...]  # [bn, k] f32
    flat_ids = ids.reshape(-1)
    # 2-D gather: rows of the [d, 1] coefficient column.
    gathered = jnp.take(w, flat_ids, axis=0).reshape(ids.shape)
    margin = jnp.sum(gathered * vals, axis=1) + off_ref[...][:, 0]
    y = y_ref[...][:, 0]
    wt = wt_ref[...][:, 0]
    val_ref[...] += jnp.sum(wt * loss.value(margin, y)).reshape(1, 1)
    coeff = wt * loss.d1(margin, y)  # [bn]
    contrib = (coeff[:, None] * vals).reshape(-1, 1)
    # 2-D scatter-add back into the [d, 1] gradient column.
    grad_ref[...] += jnp.zeros_like(grad_ref).at[flat_ids].add(contrib)


@functools.partial(
    jax.jit, static_argnames=("loss", "block_rows", "interpret")
)
def fused_value_and_grad(
    loss: PointwiseLoss,
    w: Array,
    ids: Array,
    vals: Array,
    label: Array,
    offset: Array,
    weight: Array,
    block_rows: int = 1024,
    interpret: Optional[bool] = None,
) -> tuple[Array, Array]:
    """(sum_i w_i * loss(margin_i, y_i), d/dw of same) in one fused pass.

    Excludes regularization (callers add the analytic L2 term, as the
    reference does — SURVEY.md §3.4).  Rows are padded to a block multiple
    with zero weight, which contributes exactly nothing.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    n, k = ids.shape
    d = w.shape[0]
    if n == 0:
        return jnp.zeros((), jnp.float32), jnp.zeros_like(w)
    bn = min(block_rows, n)
    pad = (-n) % bn
    if pad:
        ids = jnp.pad(ids, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        label = jnp.pad(label, (0, pad))
        offset = jnp.pad(offset, (0, pad))
        weight = jnp.pad(weight, (0, pad))
    grid = (ids.shape[0] // bn,)

    value, grad = pl.pallas_call(
        functools.partial(_kernel, loss),
        grid=grid,
        in_specs=[
            pl.BlockSpec((d, 1), lambda i: (0, 0)),  # w: resident every step
            pl.BlockSpec((bn, k), lambda i: (i, 0)),
            pl.BlockSpec((bn, k), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),  # loss accumulator
            pl.BlockSpec((d, 1), lambda i: (0, 0)),  # gradient accumulator
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((d, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        w.reshape(d, 1),
        ids,
        vals,
        label.reshape(-1, 1),
        offset.reshape(-1, 1),
        weight.reshape(-1, 1),
    )
    return value[0, 0], grad[:, 0]
