"""vperm: fast static E-element permutations from measured-fast primitives.

The round-4 chained hardware probes (ops/KERNEL_NOTES.md, third window)
showed this chip runs data-DEPENDENT XLA ops at 23–275 Melem/s (gather
68, row-wise gather 70, sort 275, 3-stage XLA Clos 23) while pallas
lane-local gathers run at 3.4 Gelem/s and XLA strided transposes at
14 GB/s.  The sparse-GLM hot loop needs exactly one data-dependent
movement per direction — the static row-order ↔ feature-order exchange
of the entry stream — so routing that exchange through the fast
primitives is the whole performance ballgame.

Decomposition (two-level Clos, all stages static, routed on host):

    y = x[perm]  over a padded domain  N = NC × CS,  CS = CH×128

      chunk stage R1   — arbitrary perm within each CS-element chunk,
                         itself a fused 5-stage in-VMEM micro-Clos
                         (lane-gather / VMEM transpose / wide row-gather
                         / VMEM transpose / lane-gather), one pallas
                         pass over HBM
      transpose        — [NC, CS] → [CS, NC] (XLA, strided, fast)
      lane stage  C    — per-column NC-perms of the transposed view,
                         lane-packed into [total/128, 128] tiles
                         (NC is a power of two ≤ 128, so 128/NC logical
                         rows pack per vreg row), one pallas pass
      transpose back   — [CS, NC] → [NC, CS]
      chunk stage R2   — as R1

CH adapts (2048 or 4096 sublane-rows) so domains up to 2^26 elements
route with NC ≤ 128.  Rectangular use (source and destination streams
of different lengths, e.g. row-major entries → padded layout slots) is
supported by a full-domain bijection: ``n_in`` real sources pad with
zeros, ``n_out`` real destinations slice off the front.

Host routing is three levels of bipartite edge-coloring (Slepian–Duguid
route construction, native/src/clos_route.cpp): one macro coloring on
the [NC, CS] grid and two micro colorings per chunk on [CH, 128].
Routing is one-time per dataset layout (the permutation is static data
layout, not step data) and is carried as int8/int16 index planes so the
per-step routing read is ~5 bytes/element.

The reference has no analog: its Spark shuffle IS a dynamic random
exchange (SURVEY.md §2.6).  This module is the TPU-native re-design
that makes the same data movement run at sequential-stream speeds.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import tree_util

from photon_tpu.ops.clos import route_permutation

Array = jax.Array

LANES = 128
SUBLANES_PAD = 8             # f32 sublane tile: chunk heights pad to it
CH_SMALL = 2048              # chunk sublane-rows (1 MB f32 chunks)
CH_LARGE = 4096              # for domains past 128 small chunks
MAX_N = 128 * CH_LARGE * LANES   # 2^26: lane stage holds NC <= 128


@dataclasses.dataclass(frozen=True)
class VpermRoute:
    """Device-ready routing for one static bijection over ``total``
    padded elements, applied as ``y[:n_out] = x_padded[perm][:n_out]``
    with ``x`` of length ``n_in``.  Index planes are stored narrow
    (int8/int16) and upcast in-kernel; shapes are static per layout.

    ``i1/i3`` and ``i4/i6``: [NC*CH, 128] int8 lane indices for the two
    chunk stages' outer lane-gathers.  ``i2``/``i5``: [NC*128, CH] int16
    wide row-gather indices on the transposed [128, CH] chunk view.
    ``c``: [total/128, 128] int8 lane-packed middle-stage indices
    (``None`` when NC == 1 and the middle stage is the identity, in
    which case R2 is skipped too).
    """

    n_in: int
    n_out: int
    nc: int
    ch: int
    i1: jnp.ndarray
    i2: jnp.ndarray
    i3: jnp.ndarray
    c: object
    i4: object
    i5: object
    i6: object

    @property
    def cs(self) -> int:
        return self.ch * LANES

    @property
    def total(self) -> int:
        return self.nc * self.cs


tree_util.register_dataclass(
    VpermRoute,
    data_fields=("i1", "i2", "i3", "c", "i4", "i5", "i6"),
    meta_fields=("n_in", "n_out", "nc", "ch"),
)


def route_threads() -> int:
    """Worker count for per-chunk route colorings (PHOTON_ROUTE_THREADS,
    default: host cores capped at 8 — the walk is memory-bound past
    that).  The native edge coloring releases the GIL (ctypes) and is
    reentrant (stack-local scratch), so chunks color concurrently."""
    import os

    from photon_tpu.utils.env import env_int

    return env_int(
        "PHOTON_ROUTE_THREADS", min(os.cpu_count() or 1, 8), minimum=1
    )


def _chunk_stage_arrays(rows: np.ndarray, ch: int):
    """Factor per-chunk CS-perms into the 5-stage micro-Clos planes.

    ``rows`` is [NC, CS] int64: row i is the permutation applied within
    chunk i (y_chunk = x_chunk[rows[i]]).  Returns (i1 [NC*CH, 128] int8,
    i2 [NC*128, CH] int16, i3 [NC*CH, 128] int8).

    The per-chunk colorings are independent and GIL-releasing, so they
    run on a thread pool (:func:`route_threads`) — the measured
    profile at E=2^23 is ~60% native edge-coloring walk, so on an
    8-core host the build drops accordingly (tools/probe_route_scaling
    carries the numbers).
    """
    nc = rows.shape[0]
    i1 = np.empty((nc * ch, LANES), np.int8)
    i2 = np.empty((nc * LANES, ch), np.int16)
    i3 = np.empty((nc * ch, LANES), np.int8)

    def one(i: int) -> None:
        r = route_permutation(rows[i], a=ch, b=LANES, device=False)
        # clos stage semantics (apply_clos_grid): lane-gather by p1 on
        # [CH,128], transpose, row-gather by p2 on [128,CH], transpose,
        # lane-gather by p3.
        i1[i * ch:(i + 1) * ch] = r.p1.astype(np.int8)
        i2[i * LANES:(i + 1) * LANES] = r.p2.astype(np.int16)
        i3[i * ch:(i + 1) * ch] = r.p3.astype(np.int8)

    from photon_tpu.utils.io_pool import in_pool_worker, map_ordered

    workers = min(route_threads(), nc)
    if in_pool_worker():
        # Already on an io_pool worker (e.g. a streamed chunk attach):
        # nesting a second pool would oversubscribe cores on a walk
        # that is cache-pressure-bound — thread at one level.
        workers = 1
    # list(): drain, surfacing the first worker exception in order.
    list(map_ordered(one, range(nc), workers=workers))
    return i1, i2, i3


def _pack_middle(cidx: np.ndarray, nc: int) -> np.ndarray:
    """Lane-pack the [CS, NC] per-row middle perms into [total/128, 128].

    NC divides 128, so each vreg row holds 128/NC whole logical rows;
    the packed lane index for flat position p*128+l is
    ``(l//NC)*NC + cidx[s, l%NC]`` with ``s = (p*128+l)//NC`` — still a
    within-128-lane gather.
    """
    cs = cidx.shape[0]
    total = cs * nc
    flat = np.arange(total, dtype=np.int64)
    s = flat // nc
    c = flat % nc
    packed = ((flat % 128) // nc * nc + cidx[s, c]).astype(np.int8)
    return packed.reshape(total // LANES, LANES)


def pick_geometry(need: int) -> tuple[int, int]:
    """(ch, nc) covering ``need`` elements: the smaller chunk height when
    it fits in 128 chunks, NC a power of two so it divides 128."""
    if need > MAX_N:
        raise ValueError(
            f"vperm supports up to {MAX_N:,} elements single-device "
            f"(got {need:,}); shard the layout across devices first"
        )
    ch = CH_SMALL if need <= 128 * CH_SMALL * LANES else CH_LARGE
    nc = max(1, -(-need // (ch * LANES)))
    if nc & (nc - 1):
        nc = 1 << nc.bit_length()
    return ch, nc


def route_vperm_full(perm: np.ndarray, n_in: int, n_out: int,
                     ch: int) -> VpermRoute:
    """Route a FULL-domain bijection (``len(perm)`` = NC×CS exactly).

    ``perm[d]`` is the padded-source index feeding padded-destination
    ``d``; callers guarantee destinations below ``n_out`` read real
    sources and pad destinations read pad (zero) sources.
    """
    perm = np.ascontiguousarray(perm, dtype=np.int64)
    total = perm.size
    cs = ch * LANES
    nc = total // cs
    if nc * cs != total or (nc & (nc - 1)) or nc > 128:
        raise ValueError(f"total {total} is not a valid NC*CS geometry")
    if perm.size and (
        perm.min() < 0 or perm.max() >= total
        or np.bincount(perm, minlength=total).max() != 1
    ):
        raise ValueError("perm is not a permutation of [0, total)")

    if nc == 1:
        i1, i2, i3 = _chunk_stage_arrays(perm[None, :], ch)
        c = i4 = i5 = i6 = None
    else:
        r = route_permutation(perm, a=nc, b=cs, device=False)
        i1, i2, i3 = _chunk_stage_arrays(r.p1.astype(np.int64), ch)
        c = jnp.asarray(_pack_middle(r.p2.astype(np.int64), nc))
        i4, i5, i6 = (
            jnp.asarray(p)
            for p in _chunk_stage_arrays(r.p3.astype(np.int64), ch)
        )

    return VpermRoute(
        n_in=n_in, n_out=n_out, nc=nc, ch=ch,
        i1=jnp.asarray(i1), i2=jnp.asarray(i2), i3=jnp.asarray(i3),
        c=c, i4=i4, i5=i5, i6=i6,
    )


def route_vperm(perm: np.ndarray) -> VpermRoute:
    """Route ``y = x[perm]`` (square n-element permutation, n ≤ MAX_N).

    The domain pads to whole chunks; pad slots map identically so padded
    inputs carry zeros through untouched.
    """
    perm = np.ascontiguousarray(perm, dtype=np.int64)
    n = perm.size
    if n and (perm.min() < 0 or perm.max() >= n
              or np.bincount(perm, minlength=n).max() != 1):
        raise ValueError("perm is not a permutation of [0, n)")
    ch, nc = pick_geometry(n)
    total = nc * ch * LANES
    full = np.arange(total, dtype=np.int64)
    full[:n] = perm
    return route_vperm_full(full, n, n, ch)


def full_bijection(dest_src: np.ndarray, n_sources: int,
                   total: int) -> np.ndarray:
    """Extend an injective dest→source map to a full-domain bijection.

    ``dest_src[d]`` is the real source for destination ``d`` (< 0 for
    pad destinations).  Real sources live in [0, n_sources); the unused
    sources (real pads plus the [n_sources, total) tail) fill the pad
    destinations and the tail in ascending order — they only ever carry
    zeros.  Shared by ops/benes (grid domains) and the xchg route.
    """
    n_dest = dest_src.size
    if n_dest > total or n_sources > total:
        raise ValueError("total smaller than the streams it must cover")
    perm = np.empty(total, dtype=np.int64)
    real = dest_src >= 0
    perm[:n_dest][real] = dest_src[real]
    used = np.zeros(total, dtype=bool)
    used[dest_src[real]] = True
    unused = np.flatnonzero(~used)
    n_pad_dest = int((~real).sum()) + (total - n_dest)
    if unused.size != n_pad_dest:
        raise ValueError("dest_src is not injective into the source stream")
    perm[:n_dest][~real] = unused[: int((~real).sum())]
    perm[n_dest:] = unused[int((~real).sum()):]
    return perm


def _micro_clos_body(y, i1_ref, i2_ref, i3_ref):
    """The 5-stage micro-Clos array math, shared by every chunk kernel
    variant (plain, dz-expanding) so the stage sequence can never
    desynchronize between them."""
    y = jnp.take_along_axis(y, i1_ref[...].astype(jnp.int32), axis=1)
    y = y.T  # [128, CH] in VMEM
    y = jnp.take_along_axis(y, i2_ref[...].astype(jnp.int32), axis=1)
    y = y.T
    return jnp.take_along_axis(y, i3_ref[...].astype(jnp.int32), axis=1)


def _chunk_kernel(x_ref, i1_ref, i2_ref, i3_ref, o_ref):
    """Fused 5-stage micro-Clos over one [CH, 128] chunk in VMEM."""
    o_ref[...] = _micro_clos_body(x_ref[...], i1_ref, i2_ref, i3_ref)


def _lane_kernel(x_ref, c_ref, o_ref):
    o_ref[...] = jnp.take_along_axis(
        x_ref[...], c_ref[...].astype(jnp.int32), axis=1
    )


def _chunk_pass(x2d: Array, i1: Array, i2: Array, i3: Array, nc: int,
                ch: int, interpret: bool) -> Array:
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _chunk_kernel,
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
            pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
            pl.BlockSpec((LANES, ch), lambda i: (i, 0)),
            pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, i1, i2, i3)


def _lane_pass(x2d: Array, c: Array, ch: int, interpret: bool) -> Array:
    from jax.experimental import pallas as pl

    n_tiles = x2d.shape[0] // ch
    return pl.pallas_call(
        _lane_kernel,
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
            pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, c)


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply_vperm(x: Array, route: VpermRoute,
                interpret: bool = False) -> Array:
    """Apply the routed bijection to a flat [n_in] array → flat [n_out].

    Pipeline: chunk pass R1 → transpose [NC,CS]→[CS,NC] → lane-packed
    middle pass → transpose back → chunk pass R2.  Three pallas passes
    plus two XLA transposes, no data-dependent XLA ops.  NC == 1 runs
    the single chunk pass only.
    """
    nc, ch, cs, total = route.nc, route.ch, route.cs, route.total
    if x.shape[0] != route.n_in:
        raise ValueError(f"length {x.shape[0]} != routed n_in {route.n_in}")
    dtype = x.dtype
    if total > route.n_in:
        x = jnp.concatenate([x, jnp.zeros(total - route.n_in, dtype)])
    g = x.reshape(nc * ch, LANES)
    g = _chunk_pass(g, route.i1, route.i2, route.i3, nc, ch, interpret)
    if nc > 1:
        # [NC, CS] -> [CS, NC]: per-column NC-perms become lane-local
        # once packed; flat row-major order of the [CS, NC] view is the
        # packed [total/128, 128] layout _pack_middle indexed.
        t = g.reshape(nc, cs).T.reshape(nc * ch, LANES)
        t = _lane_pass(t, route.c, ch, interpret)
        g = t.reshape(cs, nc).T.reshape(nc * ch, LANES)
        g = _chunk_pass(g, route.i4, route.i5, route.i6, nc, ch, interpret)
    return g.reshape(total)[:route.n_out]


def invert_vperm(route: VpermRoute) -> VpermRoute:
    """The inverse bijection's route from the same routing (no second
    edge-coloring): run the pipeline backwards with each stage's rows
    inverted row-wise.  A chunk stage applies (i1, T, i2, T, i3); its
    inverse applies (inv i3, T, inv i2, T, inv i1) — the same kernel
    shape — and the middle lane stage inverts row-wise (each packed row
    is a 128-perm, so argsort per row is its inverse).  ``n_in`` and
    ``n_out`` swap."""

    def inv_rows(p):
        return jnp.argsort(p.astype(jnp.int32), axis=1).astype(p.dtype)

    if route.nc == 1:
        return VpermRoute(
            n_in=route.n_out, n_out=route.n_in, nc=1, ch=route.ch,
            i1=inv_rows(route.i3), i2=inv_rows(route.i2),
            i3=inv_rows(route.i1),
            c=None, i4=None, i5=None, i6=None,
        )
    return VpermRoute(
        n_in=route.n_out, n_out=route.n_in, nc=route.nc, ch=route.ch,
        i1=inv_rows(route.i6), i2=inv_rows(route.i5),
        i3=inv_rows(route.i4),
        c=inv_rows(route.c),
        i4=inv_rows(route.i3), i5=inv_rows(route.i2),
        i6=inv_rows(route.i1),
    )


def apply_vperm_reference(x: np.ndarray, perm: np.ndarray) -> np.ndarray:
    """NumPy oracle for tests."""
    return np.asarray(x)[np.asarray(perm)]


# -- the xchg production routes ---------------------------------------------

@dataclasses.dataclass(frozen=True)
class XchgAux:
    """Batch-attached exchange routing for the `xchg` kernel.

    ``route`` permutes the row-major per-entry product stream into the
    reduce-side order.  Two reduce strategies (PHOTON_XCHG_REDUCE):

    - ``aligned`` — destination is the slab-aligned slot stream; the
      reduce is ops/pallas_gather.aligned_reduce (``bounds`` is None).
    - ``cumsum`` — destination is the COMPACT feature-sorted stream
      (exactly n*k entries: zero NC padding when n*k is a chunk
      multiple); the reduce is an f32 cumsum + one [d+1] boundary
      gather (``g[f] = ps[bounds[f+1]] - ps[bounds[f]]``).  Cheaper
      data movement, at f32 prefix-sum precision — the auto probe's
      correctness gate arbitrates.

    ``vals_dest`` (cumsum mode, when the attach provides vals): the
    STATIC value stream pre-permuted to the destination order, so each
    step moves only the dz expansion and the value multiply happens at
    the destination, fused into the prefix scan — one fewer E-stream
    read per evaluation.
    """

    route: VpermRoute
    bounds: object = None  # [dim+1] int32 device array for cumsum mode
    vals_dest: object = None  # [total] f32, pre-permuted static values
    # Fingerprint of the row-major value stream the bake saw — the
    # strided f32 SAMPLE itself (elementwise-comparable: a collapsed
    # scalar like an L1 norm is permutation-invariant and would miss
    # value swaps), carried as a DATA leaf so re-attaching with new
    # values never changes the treedef (a meta fp would force a full
    # jit recompile per re-attach).  Lets eager callers that pass
    # DIFFERENT values be rejected instead of silently reading the
    # stale baked stream (ADVICE r4).  None until vals are baked.
    vals_fp: object = None


# Sample cap for the fingerprint: bounds the guard's host cost to O(1)
# per eager call.  The sample is STRIDED across the whole stream (not a
# prefix) so a re-weighting confined to a later region of a large
# stream still moves the fingerprint.
_VALS_FP_SAMPLES = 65536


tree_util.register_dataclass(
    XchgAux, data_fields=("route", "bounds", "vals_dest", "vals_fp"),
    meta_fields=(),
)


def build_xchg_route(layout, n: int, k: int) -> VpermRoute:
    """Route the row-major entry stream into aligned-layout slot order.

    ``layout`` is the host ops/pallas_gather.AlignedLayout (must carry
    ``src``).  The returned route feeds ops/pallas_gather.aligned_reduce:
    ``apply_vperm(products_rowmajor, route)`` is the slot stream, with
    pad slots carrying zeros.  This replaces the per-step E-element XLA
    ``per_row[rows]`` gather (measured 493 ms at E=2^25, third window)
    with the 3-pass vperm pipeline.
    """
    n_rm = n * k
    slots_src = layout.src.reshape(-1)
    n_slots = int(slots_src.size)
    ch, nc = pick_geometry(max(n_rm, n_slots))
    total = nc * ch * LANES
    perm = full_bijection(slots_src, n_rm, total)
    return route_vperm_full(perm, n_rm, n_slots, ch)


def build_xchg_sorted_route(ids: np.ndarray, dim: int,
                            order: np.ndarray | None = None) -> XchgAux:
    """Route row-major entries into the COMPACT feature-sorted stream.

    ``ids`` is the batch's [n, k] padded id array (pads carry id 0 and
    val 0 — they land inside feature 0's segment and contribute zero,
    exactly as in the fm segment-sum).  The destination has the same
    length as the source, so the permutation is square and the only
    padding is the chunk-multiple tail.  ``order`` is the stable argsort
    of the flat id stream when the caller already computed it (the fm
    aux build does — no second O(E log E) host sort).
    """
    flat = ids.reshape(-1).astype(np.int64)
    if order is None:
        order = np.argsort(flat, kind="stable")  # dest i <- rm order[i]
    else:
        order = np.ascontiguousarray(order, dtype=np.int64)
    n_rm = flat.size
    ch, nc = pick_geometry(n_rm)
    total = nc * ch * LANES
    perm = np.arange(total, dtype=np.int64)
    perm[:n_rm] = order
    if total > n_rm:
        # Tail destinations must read tail (zero-pad) sources: order is
        # already a bijection on [0, n_rm), identity on the tail.
        perm[n_rm:] = np.arange(n_rm, total, dtype=np.int64)
    route = route_vperm_full(perm, n_rm, n_rm, ch)
    bounds = np.searchsorted(
        flat[order], np.arange(dim + 1, dtype=np.int64)
    ).astype(np.int32)
    return XchgAux(route=route, bounds=jnp.asarray(bounds))


@dataclasses.dataclass(frozen=True)
class BalancedRoute:
    """Coloring-free exchange into the feature-sorted stream.

    The sorted destination gives total placement freedom for pad slots
    (zeros are harmless anywhere under a prefix-sum reduce), so the
    macro stage needs no edge-coloring: dest window j draws its
    entries from source window i in a fixed-size [NC, NC, B] block grid
    and the exchange is one XLA block transpose between two chunk-local
    passes.  B is the max per-(i, j) count plus padding — near E/NC²
    for any data whose sorted stream mixes source positions (uniform
    AND zipf do; a pre-sorted pathological dataset would not, and the
    builder falls back to the colored route).

    ``a1/a2/a3``: stage-A micro-Clos planes ([NC*CH,128] int8,
    [NC*128,CH] int16, [NC*CH,128] int8); ``b1/b2/b3``: stage B.
    ``n_in`` real sources; ``cs_win`` raw rm entries per source window
    (each physical chunk = one window front-packed plus pad tail); the
    flat output length is NC*CS.
    """

    n_in: int
    n_out: int      # real destination-stream length (repack slice)
    nc: int
    ch: int
    blk: int
    cs_win: int
    ds_win: int     # real dest entries per chunk front
    k_expand: int   # k when the in-kernel dz expansion applies, else 0
    a1: jnp.ndarray
    a2: jnp.ndarray
    a3: jnp.ndarray
    b1: jnp.ndarray
    b2: jnp.ndarray
    b3: jnp.ndarray

    @property
    def cs(self) -> int:
        return self.ch * LANES

    @property
    def total(self) -> int:
        return self.nc * self.cs


tree_util.register_dataclass(
    BalancedRoute,
    data_fields=("a1", "a2", "a3", "b1", "b2", "b3"),
    meta_fields=(
        "n_in", "n_out", "nc", "ch", "blk", "cs_win", "ds_win", "k_expand",
    ),
)


def _complete_chunk_local(dest_src: np.ndarray, nc: int,
                          cs: int) -> np.ndarray:
    """Fill pad destinations (< 0) with each CHUNK's own unused sources
    (ascending), so every row of the resulting [nc, cs] perm is a
    within-chunk permutation.  Feasible because real slots and real
    sources tally per chunk by construction."""
    grid = dest_src.reshape(nc, cs)
    out = grid % cs  # real slots: chunk-local source offset
    for i in range(nc):
        row = grid[i]
        real = row >= 0
        used = np.zeros(cs, bool)
        used[row[real] % cs] = True
        out[i, ~real] = np.flatnonzero(~used)
    return out


def _balanced_windows(dest_src: np.ndarray, n_src_stream: int, k: int):
    """Window partition + per-(src, dest)-window block census of the
    balanced exchange: ``(nc, cs_win, ds_win, k_expand, d_real, src_of,
    src_win, dest_win, blk)`` or None when the streams exceed geometry
    limits.  Split out of :func:`_build_balanced_core` so a SHARDED
    attach can census every shard's natural ``blk`` first and rebuild
    all shards with the shared maximum (uniform route geometry is what
    lets per-shard routes stack into one shard_map pytree).  Everything
    here except ``blk`` (and the data-dependent index arrays) is a
    function of (n_src_stream, n_dest, k) alone — identical across
    equal-shaped shards by construction."""
    n_dest = dest_src.size
    d_real = np.flatnonzero(dest_src >= 0)
    src_of = dest_src[d_real]
    e = d_real.size
    if max(n_src_stream, n_dest) > MAX_N:
        return None
    if e and (src_of.min() < 0 or src_of.max() >= n_src_stream):
        return None
    nc = min(
        128,
        max(1, -(-max(n_src_stream, n_dest) // (CH_SMALL * LANES))),
    )
    ds_win = -(-n_dest // nc)  # dest window j = dests [j*ds_win, ...)
    dest_win = np.minimum(d_real // ds_win, nc - 1)

    # Source windows are cs_win RAW rm entries; each physical chunk is
    # one window front-packed plus a pad tail (apply_balanced inserts
    # the tails with one fused XLA pad), so the window partition does
    # not depend on the block-derived chunk size.  When k divides 128,
    # round the window to whole rows so chunk boundaries never split a
    # row — then the in-kernel dz expansion (apply_balanced_dz) can
    # rebuild the row-major stream from a [ch, 128/k] dz tile and the
    # per-step E-stream materialization disappears.
    k_expand = k if (k and LANES % k == 0) else 0
    cs_base = -(-n_src_stream // nc)
    if k_expand:
        cs_win = k * (-(-cs_base // k))
    else:
        cs_win = cs_base
    src_win = np.minimum(src_of // cs_win, nc - 1)
    counts = np.bincount(
        src_win * nc + dest_win, minlength=nc * nc
    ).reshape(nc, nc)
    blk = int(counts.max())
    return nc, cs_win, ds_win, k_expand, d_real, src_of, src_win, dest_win, blk


def _build_balanced_core(dest_src: np.ndarray, n_src_stream: int, k: int,
                         blk_override: int | None = None):
    """Factor an exchange into the balanced form, for ANY destination
    stream that tolerates zero pads between real entries.

    ``dest_src[d]`` = source rm index feeding destination ``d`` (< 0
    for pad destinations; each source index appears at most once).
    ``n_src_stream`` is the FULL row-major stream length (n*k) — source
    windows partition the whole stream, since rm indices of real
    entries range over all of it.  ``blk_override`` forces a (>= natural)
    block capacity so equal-shaped shards share one geometry.  Returns a
    :class:`BalancedRoute` or None when the data defeats the balance
    assumption / geometry limits (caller falls back to the colored
    route).
    """
    n_dest = dest_src.size
    win = _balanced_windows(dest_src, n_src_stream, k)
    if win is None:
        return None
    nc, cs_win, ds_win, k_expand, d_real, src_of, src_win, dest_win, blk = win
    e = d_real.size
    cs_base = -(-n_src_stream // nc)
    if blk_override is not None:
        if blk_override < blk:
            raise ValueError(
                f"blk_override {blk_override} < this shard's natural "
                f"block census {blk}"
            )
        blk = blk_override
    # Quantum LANES * lcm(nc, 8): cs_pad/nc (the block stride) must be
    # whole, and ch = cs_pad/128 must be a multiple of 8 (the f32 sublane
    # tile) or Mosaic can reject the chunk kernel's block height when nc
    # is not a power of two (ADVICE r4).  Pads carry zeros.
    quantum = LANES * math.lcm(nc, SUBLANES_PAD)
    cs_pad = -(-max(nc * blk, cs_win, ds_win) // quantum) * quantum
    if nc > 1 and cs_pad > 2 * max(cs_base, ds_win):
        return None  # pathological source/dest correlation
    ch = cs_pad // LANES
    if ch > 8192:
        # VMEM ceiling for the fused chunk kernel (and headroom under
        # the int16 i2/b2 index planes' 32767 bound).
        return None
    blk_slots = cs_pad // nc
    total = nc * cs_pad

    # Stage-A slot of each entry: source chunk src_win, block dest_win,
    # position by destination order within the (src, dest) pair.  With
    # one chunk the transpose and stage B are skipped (apply's nc > 1
    # guard), so stage A must place entries at their FINAL positions —
    # mid == final, not the compacted block order (real destinations
    # can be sparse in the aligned slot stream).
    seq = np.arange(e, dtype=np.int64)
    if nc == 1:
        mid_slot = d_real.astype(np.int64)
    else:
        pair = src_win * nc + dest_win
        pair_order = np.argsort(pair, kind="stable")
        sizes = np.bincount(pair, minlength=nc * nc)
        starts = np.concatenate(([0], np.cumsum(sizes)))[:-1]
        rank_in_block = np.zeros(e, dtype=np.int64)
        rank_in_block[pair_order] = seq - np.repeat(starts, sizes)
        mid_slot = (
            src_win * cs_pad + dest_win * blk_slots + rank_in_block
        )

    # Stage A within-chunk perms (pads complete chunk-locally against
    # each chunk's own unused — zero-valued — sources).  Source
    # coordinates are in the PADDED stream: window-local offset is the
    # raw offset (windows front-pack their chunks).
    dest_src_a = np.full(total, -1, np.int64)
    dest_src_a[mid_slot] = src_win * cs_pad + (src_of % cs_win)
    rows_a = _complete_chunk_local(dest_src_a, nc, cs_pad)
    a1, a2, a3 = _chunk_stage_arrays(rows_a, ch)

    if nc == 1:
        # Stage B is skipped at apply time; identity planes keep the
        # dataclass/serialization shape uniform.
        ident = np.arange(cs_pad, dtype=np.int64)[None, :]
        b1, b2p, b3 = _chunk_stage_arrays(ident, ch)
    else:
        # Block transpose [nc, nc, blk_slots]:
        # (src, dest, b) -> (dest, src, b).
        post_t = (
            dest_win * cs_pad + src_win * blk_slots + rank_in_block
        )
        # Stage B: destination d front-packs into dest chunk dest_win.
        final = dest_win * cs_pad + (d_real - dest_win * ds_win)
        dest_src_b = np.full(total, -1, np.int64)
        dest_src_b[final] = post_t
        rows_b = _complete_chunk_local(dest_src_b, nc, cs_pad)
        b1, b2p, b3 = _chunk_stage_arrays(rows_b, ch)

    return BalancedRoute(
        n_in=n_src_stream, n_out=n_dest, nc=nc, ch=ch, blk=blk_slots,
        cs_win=cs_win, ds_win=ds_win, k_expand=k_expand,
        a1=jnp.asarray(a1), a2=jnp.asarray(a2), a3=jnp.asarray(a3),
        b1=jnp.asarray(b1), b2=jnp.asarray(b2p), b3=jnp.asarray(b3),
    )


def build_balanced_sorted_route(
    ids: np.ndarray, dim: int, order: np.ndarray | None = None,
    blk_override: int | None = None,
):
    """(BalancedRoute, bounds) for the rm → feature-sorted exchange, or
    None when the data defeats the balance assumption."""
    flat = ids.reshape(-1).astype(np.int64)
    k = int(ids.shape[-1]) if ids.ndim == 2 else 0
    e = flat.size
    if order is None:
        order = np.argsort(flat, kind="stable")
    else:
        order = np.ascontiguousarray(order, dtype=np.int64)
    route = _build_balanced_core(order, e, k, blk_override=blk_override)
    if route is None:
        return None
    bounds_rank = np.searchsorted(
        flat[order], np.arange(dim + 1, dtype=np.int64)
    )
    bw = np.minimum(bounds_rank // route.ds_win, route.nc - 1)
    bounds = (bw * route.cs + (bounds_rank - bw * route.ds_win))
    return route, jnp.asarray(bounds.astype(np.int32))


def build_balanced_aligned_route(layout, ids: np.ndarray,
                                 blk_override: int | None = None):
    """BalancedRoute for the rm → aligned-slot exchange (same balanced
    construction; the destination is the slab slot stream, whose pads
    carry zeros automatically because chunk-local completion pairs them
    with the zero-valued unused sources).  The applied stream repacks
    chunk fronts back into the contiguous slot array
    (see xchg_segment_grad).  None → colored fallback."""
    k = int(ids.shape[-1]) if ids.ndim == 2 else 0
    slots_src = np.ascontiguousarray(
        layout.src.reshape(-1), dtype=np.int64
    )
    return _build_balanced_core(slots_src, int(ids.size), k,
                                blk_override=blk_override)


def _chunk_expand_kernel(dz_ref, i1_ref, i2_ref, i3_ref, o_ref):
    """Stage A with the dz expansion fused: the [ch, 128/k] dz tile
    broadcasts to the row-major [ch, 128] stream in VMEM (static lane
    repeat), then the shared 5-stage micro-Clos body runs.  Pad-tail
    positions carry whatever dz value the repeat lands there — they
    flow into pad destinations whose vals_dest is zero."""
    k = LANES // dz_ref.shape[1]
    y = jnp.repeat(dz_ref[...], k, axis=1)
    o_ref[...] = _micro_clos_body(y, i1_ref, i2_ref, i3_ref)


_EXPAND_SUPPORTED: dict = {}


def expand_kernel_supported(k: int = 32,
                            dtype=jnp.float32) -> bool:
    """Eager Mosaic capability probe for the fused dz-expansion kernel
    (jnp.repeat along lanes), cached per (backend, k, dtype) — the
    exact configuration that will run, since narrow-lane tiles and
    bf16 gathers can lower differently.  A lowering failure would
    otherwise surface only when the optimizer's enclosing jit
    compiles."""
    backend = jax.default_backend()
    key = (backend, int(k), jnp.dtype(dtype).name)
    if key not in _EXPAND_SUPPORTED:
        if backend != "tpu":
            _EXPAND_SUPPORTED[key] = True  # interpret mode
        else:
            from jax.experimental import pallas as pl

            try:
                f = pl.pallas_call(
                    _chunk_expand_kernel,
                    out_shape=jax.ShapeDtypeStruct((8, LANES), dtype),
                    grid=(1,),
                    in_specs=[
                        pl.BlockSpec((8, LANES // k), lambda i: (i, 0)),
                        pl.BlockSpec((8, LANES), lambda i: (i, 0)),
                        pl.BlockSpec((LANES, 8), lambda i: (i, 0)),
                        pl.BlockSpec((8, LANES), lambda i: (i, 0)),
                    ],
                    out_specs=pl.BlockSpec((8, LANES), lambda i: (i, 0)),
                )
                # ensure_compile_time_eval + jit: first call may happen
                # inside an enclosing jit trace (kernel routing at trace
                # time); staged probe inputs would raise and cache a
                # spurious "unsupported" (same rationale as
                # pallas_gather.reduce_kernel_supported).  The jit wrap
                # matters: a BARE pallas_call under the escape hatch hits
                # eval-trace rules (program_id has none).
                with jax.ensure_compile_time_eval():
                    jax.block_until_ready(jax.jit(f)(
                        jnp.ones((8, LANES // k), dtype),
                        jnp.zeros((8, LANES), jnp.int8),
                        jnp.zeros((LANES, 8), jnp.int16),
                        jnp.zeros((8, LANES), jnp.int8),
                    ))
                _EXPAND_SUPPORTED[key] = True
            except Exception as exc:  # noqa: BLE001 — fall back
                import logging

                logging.getLogger("photon_tpu.vperm").warning(
                    "fused dz-expansion kernel unavailable on %s "
                    "(k=%d, %s): %s — using the streamed exchange path",
                    backend, k, jnp.dtype(dtype).name, exc,
                )
                _EXPAND_SUPPORTED[key] = False
    return _EXPAND_SUPPORTED[key]


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply_balanced_dz(dz: Array, route: BalancedRoute,
                      interpret: bool = False) -> Array:
    """The per-step exchange with the dz expansion fused into stage A:
    moves a [n] dz vector (4 MB at the bench shape) instead of a
    materialized E-stream.  Requires ``route.k_expand`` (k | 128 and
    row-aligned windows)."""
    from jax.experimental import pallas as pl

    nc, ch = route.nc, route.ch
    cs, cs_win, k = route.cs, route.cs_win, route.k_expand
    if not k:
        raise ValueError("route was built without k_expand")
    rows_win = cs_win // k
    if dz.shape[0] * k != route.n_in:
        raise ValueError(f"dz length {dz.shape[0]} != n_in/{k}")
    if nc * rows_win > dz.shape[0]:
        dz = jnp.concatenate(
            [dz, jnp.zeros(nc * rows_win - dz.shape[0], dz.dtype)]
        )
    dz2d = jnp.pad(
        dz.reshape(nc, rows_win), ((0, 0), (0, cs // k - rows_win))
    ).reshape(nc * ch, LANES // k)
    g = pl.pallas_call(
        _chunk_expand_kernel,
        out_shape=jax.ShapeDtypeStruct((nc * ch, LANES), dz.dtype),
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((ch, LANES // k), lambda i: (i, 0)),
            pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
            pl.BlockSpec((LANES, ch), lambda i: (i, 0)),
            pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ch, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(dz2d, route.a1, route.a2, route.a3)
    return _balanced_tail(g, route, interpret)


def _balanced_tail(g: Array, route: BalancedRoute,
                   interpret: bool) -> Array:
    """Block transpose + stage B, shared by both stage-A variants."""
    nc, ch, blk, total = route.nc, route.ch, route.blk, route.total
    if nc > 1:
        g = (
            g.reshape(nc, nc, blk)
            .transpose(1, 0, 2)
            .reshape(nc * ch, LANES)
        )
        g = _chunk_pass(g, route.b1, route.b2, route.b3, nc, ch, interpret)
    return g.reshape(total)


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply_balanced(x: Array, route: BalancedRoute,
                   interpret: bool = False) -> Array:
    """rm stream [n_in] → padded sorted stream [total] (pads carry 0)."""
    nc, ch, blk, total = route.nc, route.ch, route.blk, route.total
    cs, cs_win = route.cs, route.cs_win
    if x.shape[0] != route.n_in:
        raise ValueError(f"length {x.shape[0]} != routed n_in {route.n_in}")
    # Each physical chunk = one cs_win-entry rm window front-packed plus
    # a zero tail (one fused XLA pad, no data-dependent movement).
    if nc * cs_win > route.n_in:
        x = jnp.concatenate(
            [x, jnp.zeros(nc * cs_win - route.n_in, x.dtype)]
        )
    g = jnp.pad(
        x.reshape(nc, cs_win), ((0, 0), (0, cs - cs_win))
    ).reshape(nc * ch, LANES)
    g = _chunk_pass(g, route.a1, route.a2, route.a3, nc, ch, interpret)
    # The balanced exchange is one strided XLA transpose, then stage B
    # packs each dest chunk into sorted front order.
    return _balanced_tail(g, route, interpret)


# Versioned PER MODE so bumping one builder doesn't invalidate the other
# mode's (expensive) cached routes.
_ROUTE_CACHE_VERSION = {"aligned": 2, "cumsum": 3}


def _default_route_cache_root() -> str:
    """Back-compat alias — the shared resolution lives in
    photon_tpu.utils.caches (one contract for route/layout/stream
    caches)."""
    from photon_tpu.utils.caches import default_route_cache_root

    return default_route_cache_root()


def _route_cache_path(ids: np.ndarray, dim: int, mode: str, layout,
                      has_vals: bool, blk_override: int | None = None,
                      force_colored: bool = False):
    """Disk-cache path for a routed exchange, or None when disabled.

    Routes are pure functions of their inputs and cost tens of host-
    seconds at production scale (edge colorings); caching turns every
    re-run — warm bench passes, lambda sweeps, checkpoint restarts —
    into a file load.  The key hashes the [n, k] shape and ids bytes;
    aligned mode additionally hashes ``layout.src`` (the slot→source
    map), because the aligned layout drops val==0 entries — identical
    ids with different zero patterns yield different routes.
    ``has_vals`` enters the key because aligned-mode route KIND depends
    on it (balanced needs the destination value stream) — a vals-less
    caller must not pin the colored route for later vals-carrying ones.
    """
    import hashlib
    import os

    from photon_tpu.utils.caches import resolve_cache_dir

    root = resolve_cache_dir("PHOTON_ROUTE_CACHE", "")
    if root is None:
        return None
    h = hashlib.sha256()
    h.update(repr(ids.shape).encode())
    h.update(np.ascontiguousarray(ids).tobytes())
    if mode != "cumsum" and layout is not None:
        h.update(np.ascontiguousarray(layout.src).tobytes())
    ver = _ROUTE_CACHE_VERSION.get(mode, _ROUTE_CACHE_VERSION["aligned"])
    # vals-carrying keys stay in the canonical (unsuffixed) namespace so
    # the expensive production entries survive this key extension.
    # "novals2": round 5 made vals-less aligned builds produce BALANCED
    # routes (previously colored); the namespace change orphans the old
    # colored entries instead of silently serving the wrong variant,
    # while leaving the canonical namespace untouched.
    suffix = "" if has_vals else "|novals2"
    # Sharded-attach geometry levers change the route CONTENT for the
    # same ids, so they must enter the key; single-shard builds stay in
    # the canonical namespace.
    if blk_override is not None:
        suffix += f"|blk{blk_override}"
    if force_colored:
        suffix += "|colored"
    h.update(f"|{dim}|{mode}|v{ver}{suffix}".encode())
    return os.path.join(root, h.hexdigest()[:32] + ".npz")


def _aux_to_npz(aux: XchgAux) -> dict:
    out = {}
    r = aux.route
    if isinstance(r, BalancedRoute):
        out["kind"] = np.int64(2)
        out["meta"] = np.asarray(
            [r.n_in, r.n_out, r.nc, r.ch, r.blk, r.cs_win, r.ds_win,
             r.k_expand],
            np.int64,
        )
        for name in ("a1", "a2", "a3", "b1", "b2", "b3"):
            out[name] = np.asarray(getattr(r, name))
    else:
        out["kind"] = np.int64(1)
        out["meta"] = np.asarray(
            [r.n_in, r.n_out, r.nc, r.ch], np.int64
        )
        for name in ("i1", "i2", "i3", "c", "i4", "i5", "i6"):
            v = getattr(r, name)
            if v is not None:
                out[name] = np.asarray(v)
    if aux.bounds is not None:
        out["bounds"] = np.asarray(aux.bounds)
    return out


def _aux_from_npz(z) -> XchgAux:
    bounds = jnp.asarray(z["bounds"]) if "bounds" in z else None
    if int(z["kind"]) == 2:
        (n_in, n_out, nc, ch, blk, cs_win, ds_win, k_expand) = (
            int(v) for v in z["meta"]
        )
        route = BalancedRoute(
            n_in=n_in, n_out=n_out, nc=nc, ch=ch, blk=blk, cs_win=cs_win,
            ds_win=ds_win, k_expand=k_expand,
            a1=jnp.asarray(z["a1"]), a2=jnp.asarray(z["a2"]),
            a3=jnp.asarray(z["a3"]), b1=jnp.asarray(z["b1"]),
            b2=jnp.asarray(z["b2"]), b3=jnp.asarray(z["b3"]),
        )
    else:
        n_in, n_out, nc, ch = (int(v) for v in z["meta"])
        opt = {
            name: (jnp.asarray(z[name]) if name in z else None)
            for name in ("c", "i4", "i5", "i6")
        }
        route = VpermRoute(
            n_in=n_in, n_out=n_out, nc=nc, ch=ch,
            i1=jnp.asarray(z["i1"]), i2=jnp.asarray(z["i2"]),
            i3=jnp.asarray(z["i3"]), **opt,
        )
    return XchgAux(route=route, bounds=bounds)


def balanced_blk_census(dest_src: np.ndarray, n_src_stream: int,
                        k: int) -> int | None:
    """This shard's natural per-(src, dest)-window block census, or None
    when its streams exceed the balanced geometry limits.  A sharded
    attach runs this over every shard and rebuilds all of them with the
    shared maximum (``build_xchg_aux(blk_override=...)``) so the routes
    stack into one uniform-geometry pytree."""
    win = _balanced_windows(
        np.ascontiguousarray(dest_src, dtype=np.int64), n_src_stream, k
    )
    return None if win is None else win[-1]


def build_xchg_aux(layout, ids: np.ndarray, dim: int,
                   order: np.ndarray | None = None,
                   vals: np.ndarray | None = None,
                   blk_override: int | None = None,
                   force_colored: bool = False) -> XchgAux:
    """The attach/probe entry point: build the exchange aux for the
    reduce strategy selected by PHOTON_XCHG_REDUCE (aligned | cumsum).
    One builder so the auto-selection probe measures exactly the
    variant production batches carry; routes disk-cache by content
    hash (PHOTON_ROUTE_CACHE dir, "0" disables).  With ``vals``, the
    cumsum aux also carries the statically pre-permuted value stream
    (``vals_dest`` — one device pass at attach, never cached: the
    route itself is vals-independent).

    ``blk_override`` / ``force_colored`` are the sharded-attach levers
    (see :func:`balanced_blk_census`): every shard of one batch must
    come out with the same route KIND and geometry meta, or the stacked
    aux pytree would have mismatched treedefs."""
    import logging
    import os

    n, k = ids.shape
    mode = os.environ.get("PHOTON_XCHG_REDUCE", "aligned")
    path = _route_cache_path(
        np.asarray(ids), dim, mode, layout, vals is not None,
        blk_override=blk_override, force_colored=force_colored,
    )
    aux = None
    if path is not None and os.path.exists(path):
        try:
            with np.load(path) as z:
                aux = _aux_from_npz(z)
        except Exception as exc:  # noqa: BLE001 — corrupt cache = rebuild
            logging.getLogger("photon_tpu.vperm").warning(
                "route cache read failed (%s); rebuilding", exc
            )
        if (
            aux is not None
            and isinstance(aux.route, BalancedRoute)
            and aux.route.ch % math.lcm(aux.route.nc, SUBLANES_PAD)
        ):
            # Pre-round-5 caches could hold a chunk height indivisible
            # by the f32 sublane tile (the ADVICE-r4 Mosaic-rejection
            # geometry); rebuild rather than version-bump so valid
            # cached routes (nc a multiple of 8 — all production
            # shapes) survive.
            logging.getLogger("photon_tpu.vperm").warning(
                "cached route has a stale chunk geometry (ch=%d, nc=%d);"
                " rebuilding", aux.route.ch, aux.route.nc,
            )
            aux = None
    if aux is None:
        # Announce BEFORE the build, from the one place every caller
        # (auto-probe, production attach, tests) funnels through and
        # only on a real cache miss: the edge-coloring/factoring below
        # is tens of host-seconds at production size, and an
        # unexplained first-step stall was the ADVICE-r4 complaint.
        # WARNING level — on an unconfigured root logger INFO is
        # dropped by logging's lastResort handler.
        logging.getLogger("photon_tpu.vperm").warning(
            "building the xchg exchange route for %d entries (mode=%s) "
            "— one-time host work, disk-cached for reuse%s",
            ids.size, mode,
            "" if path is not None else
            " (caching DISABLED via PHOTON_ROUTE_CACHE=0)",
        )
        if mode == "cumsum":
            # The coloring-free balanced exchange when the data permits
            # it (any stream whose sorted order mixes source positions);
            # otherwise the general colored route.
            built = None if force_colored else build_balanced_sorted_route(
                np.asarray(ids), dim, order, blk_override=blk_override
            )
            if built is not None:
                route, bounds = built
                aux = XchgAux(route=route, bounds=bounds)
            else:
                aux = build_xchg_sorted_route(
                    np.asarray(ids), dim, order=order
                )
        else:
            # Aligned destination: the balanced exchange also applies
            # (slab slot pads pair with zero-valued unused sources —
            # zero-valued in the PRODUCT stream whether or not values
            # are baked, so the unbaked variant is equally valid);
            # otherwise the general colored route.
            built = (
                build_balanced_aligned_route(
                    layout, np.asarray(ids), blk_override=blk_override
                )
                if not force_colored else None
            )
            if built is not None:
                aux = XchgAux(route=built)
            else:
                aux = XchgAux(route=build_xchg_route(layout, n, k))
        if path is not None:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                tmp = path + f".tmp{os.getpid()}"
                with open(tmp, "wb") as f:
                    np.savez(f, **_aux_to_npz(aux))
                os.replace(tmp, path)
            except Exception as exc:  # noqa: BLE001 — best-effort
                logging.getLogger("photon_tpu.vperm").warning(
                    "route cache write failed (%s)", exc
                )
    if vals is not None:
        aux = bake_vals_dest(aux, vals)
    return aux


def bake_vals_dest(aux: XchgAux, vals: np.ndarray) -> XchgAux:
    """Pre-permute the STATIC value stream to the destination order and
    attach it (plus its fingerprint) to the aux — one device pass, so
    each training step moves only the dz expansion and the value multiply
    happens at the destination.  Split out of :func:`build_xchg_aux` so
    callers that load a cached route (e.g. the streaming layout cache)
    can re-bake against freshly parsed values without rebuilding the
    route.  No-op for route kinds whose reduce reads row-major values
    directly (colored aligned)."""
    import os

    if not (aux.bounds is not None or isinstance(aux.route, BalancedRoute)):
        return aux
    interp = jax.default_backend() != "tpu"
    flat_np = np.asarray(vals, np.float32).reshape(-1)
    flat = jnp.asarray(flat_np)
    if isinstance(aux.route, BalancedRoute):
        vd = apply_balanced(flat, aux.route, interpret=interp)
    else:
        vd = apply_vperm(flat, aux.route, interpret=interp)
    if os.environ.get("PHOTON_XCHG_DTYPE", "float32") == "bfloat16":
        vd = vd.astype(jnp.bfloat16)
    fp = np.ascontiguousarray(
        flat_np[::_vals_fp_stride(flat_np.size)], np.float32
    )
    return dataclasses.replace(aux, vals_dest=vd, vals_fp=fp)


def _vals_fp_stride(size: int) -> int:
    """Stride that spreads ``_VALS_FP_SAMPLES`` samples over ``size``.
    Ceil division: with floor, sizes in (cap, ~3*cap) would stride 1-2
    and a cap-truncated sample would cover only a prefix, leaving a
    tail re-weighting invisible to the guard."""
    return max(1, -(-size // _VALS_FP_SAMPLES))


def _trace_state_clean() -> bool:
    """True when no trace is active (fully eager).  Private-API probe,
    permissive on failure in the SKIP direction (guard disabled, never
    a spurious error)."""
    try:
        from jax._src import core as _core

        return bool(_core.trace_state_clean())
    except Exception:  # noqa: BLE001
        return False


def xchg_segment_grad(per_row: Array, vals_rowmajor: Array, al,
                      aux: "XchgAux | VpermRoute", dim: int,
                      interpret: bool | None = None) -> Array:
    """``g[f] = sum_e per_row[row_e] * val_e`` — the xchg backward.

    Row-major products (a free broadcast-multiply) ride the vperm into
    the reduce-side order; the reduce is either the aligned
    position-reduce or the cumsum + boundary gather (see XchgAux).

    Contract: when ``aux.vals_dest`` is set, the values were baked into
    the aux at attach time and ``vals_rowmajor`` contributes only its
    shape — it must be the SAME value array the attach saw (true for
    every production caller: both read the batch's static vals).
    """
    from photon_tpu.ops.pallas_gather import aligned_reduce

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    import os

    if isinstance(aux, VpermRoute):  # back-compat: bare aligned route
        aux = XchgAux(route=aux)
    if (
        aux.vals_dest is not None
        and aux.vals_fp is not None
        and vals_rowmajor is not None
        and not isinstance(vals_rowmajor, jax.core.Tracer)
        and not isinstance(aux.vals_fp, jax.core.Tracer)
        and _trace_state_clean()
    ):
        # Fully-eager calls can be checked against the baked stream's
        # fingerprint; traced production calls read the batch's static
        # vals by construction.  The trace-state check matters beyond
        # the isinstance ones: under omnistaging, ops on CONCRETE
        # closed-over arrays still stage inside an enclosing trace
        # (e.g. the optimizer's while_loop body), so the slicing below
        # is only safe in a clean eval state.  The strided sample
        # bounds the host transfer to O(1) while covering the whole
        # stream, and comparing it ELEMENTWISE catches swaps and
        # off-grid-adjacent edits a collapsed norm would miss; loose
        # rtol because batch_astype may have re-stored vals in bf16
        # after the attach (bf16 requantization is ~2^-9 relative per
        # element).
        flat = jnp.ravel(vals_rowmajor)
        sample_np = np.asarray(
            flat[::_vals_fp_stride(int(flat.shape[0]))], np.float32
        )
        ref = np.asarray(aux.vals_fp, np.float32)
        if sample_np.shape != ref.shape or not np.allclose(
            sample_np, ref, rtol=1e-2, atol=1e-6
        ):
            raise ValueError(
                "xchg aux has values BAKED at attach time (vals_dest), but "
                "the vals_rowmajor passed here differs from what the attach "
                "saw; re-attach the aux (build_xchg_aux(..., vals=...)) "
                "after re-weighting values"
            )
    bf16 = os.environ.get("PHOTON_XCHG_DTYPE", "float32") == "bfloat16"
    balanced = isinstance(aux.route, BalancedRoute)
    if (balanced and aux.route.k_expand and aux.vals_dest is not None
            and expand_kernel_supported(
                aux.route.k_expand,
                jnp.bfloat16 if bf16 else jnp.float32,
            )):
        # Fully fused fast path: the [n] dz vector expands INSIDE stage
        # A (no E-stream materialization at all) and the static values
        # multiply at the destination.
        dz = per_row.astype(jnp.bfloat16 if bf16 else jnp.float32)
        moved = apply_balanced_dz(dz, aux.route, interpret=bool(interpret))
    else:
        if aux.vals_dest is not None:
            # The static value stream is pre-permuted (attach time), so
            # each step moves only the dz expansion; the value multiply
            # happens at the destination, fused into the reduce read.
            k = vals_rowmajor.shape[1]
            stream = jnp.repeat(per_row.astype(jnp.float32), k)
        else:
            stream = (per_row[:, None] * vals_rowmajor).astype(
                jnp.float32
            ).reshape(-1)
        # Optional half-width payload through the exchange: the
        # permutation passes are pure data movement, so bf16 halves
        # their HBM traffic; products quantize at ~2^-9 relative and
        # the reduce runs f32 (the compensated scan below, or the
        # aligned position-reduce's f32 accumulate), so per-feature
        # sums keep ~0.1% worst-case error.  Measured-choice knob like
        # every kernel decision here.
        if bf16:
            stream = stream.astype(jnp.bfloat16)
        if balanced:
            moved = apply_balanced(stream, aux.route,
                                   interpret=bool(interpret))
        else:
            moved = apply_vperm(stream, aux.route,
                                interpret=bool(interpret))
    if aux.vals_dest is not None:
        # Upcast BOTH operands before multiplying: the exchange is done,
        # so there is no traffic reason to multiply in bf16, and a bf16
        # product of two already-quantized operands would round a third
        # time.
        moved = moved.astype(jnp.float32) * aux.vals_dest.astype(
            jnp.float32
        )
    else:
        moved = moved.astype(jnp.float32)
    if aux.bounds is None:
        if balanced:
            # Repack chunk fronts into the contiguous slot stream (one
            # XLA copy), then the existing position-reduce finishes.
            r = aux.route
            moved = (
                moved.reshape(r.nc, r.cs)[:, :r.ds_win]
                .reshape(-1)[: r.n_out]
            )
        return aligned_reduce(
            moved.reshape(al.lo.shape), al, dim, interpret=interpret
        )
    hi, lo = _compensated_cumsum(moved)
    zero = jnp.zeros(1, jnp.float32)
    hi = jnp.concatenate([zero, hi])
    lo = jnp.concatenate([zero, lo])
    bh = jnp.take(hi, aux.bounds, axis=0)
    bl = jnp.take(lo, aux.bounds, axis=0)
    # Difference the compensated pair BEFORE collapsing: at production
    # scale (E ~ 2^25) a plain f32 prefix sum reaches magnitudes where
    # its ulp exceeds small per-feature gradients, so g[f] would be
    # rounding noise.  The (hi, lo) double-f32 carries ~48 effective
    # mantissa bits through the scan at stream cost.
    return (bh[1:] - bh[:-1]) + (bl[1:] - bl[:-1])


def _compensated_cumsum(x: Array) -> tuple[Array, Array]:
    """Inclusive prefix sum of f32 ``x`` as a (hi, lo) double-f32 pair
    via an associative two-sum combine (Dekker/Knuth), so the error of
    the running sum stays bounded by the ~48-bit pair precision instead
    of growing with the prefix magnitude."""

    def combine(a, b):
        a_hi, a_lo = a
        b_hi, b_lo = b
        s = a_hi + b_hi
        z = s - a_hi
        err = (a_hi - (s - z)) + (b_hi - z)
        return s, err + a_lo + b_lo

    hi, lo = jax.lax.associative_scan(
        combine, (x, jnp.zeros_like(x))
    )
    return hi, lo
