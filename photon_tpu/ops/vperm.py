"""vperm: fast static E-element permutations from measured-fast primitives.

The round-4 chained hardware probes (ops/KERNEL_NOTES.md, third window)
showed this chip runs data-DEPENDENT XLA ops at 23–275 Melem/s (gather
68, row-wise gather 70, sort 275, 3-stage XLA Clos 23) while pallas
lane-local gathers run at 3.4 Gelem/s and XLA strided transposes at
14 GB/s.  The sparse-GLM hot loop needs exactly one data-dependent
movement per direction — the static row-order ↔ feature-order exchange
of the entry stream — so routing that exchange through the fast
primitives is the whole performance ballgame.

Decomposition (two-level Clos, all stages static, routed on host):

    y = x[perm]  over a padded domain  N = NC × CS,  CS = CH×128 = 2^18

      chunk stage R1   — arbitrary perm within each CS-element chunk,
                         itself a fused 5-stage in-VMEM micro-Clos
                         (lane-gather / VMEM transpose / wide row-gather
                         / VMEM transpose / lane-gather), one pallas
                         pass over HBM
      transpose        — [NC, CS] → [CS, NC] (XLA, strided, fast)
      lane stage  C    — per-column NC-perms of the transposed view,
                         lane-packed into [total/128, 128] tiles
                         (NC is a power of two ≤ 128, so 128/NC logical
                         rows pack per vreg row), one pallas pass
      transpose back   — [CS, NC] → [NC, CS]
      chunk stage R2   — as R1

Host routing is three levels of bipartite edge-coloring (Slepian–Duguid
route construction, native/src/clos_route.cpp): one macro coloring on
the [NC, CS] grid and two micro colorings per chunk on [CH, 128].
Routing is one-time per dataset layout (the permutation is static data
layout, not step data) and is carried as int8/int16 index planes so the
per-step routing read is ~5 bytes/element.

The reference has no analog: its Spark shuffle IS a dynamic random
exchange (SURVEY.md §2.6).  This module is the TPU-native re-design
that makes the same data movement run at sequential-stream speeds.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import tree_util

from photon_tpu.ops.clos import route_permutation

Array = jax.Array

LANES = 128
CH = 2048                    # chunk sublane-rows
CS = CH * LANES              # chunk elements (2^18)
MAX_N = 128 * CS             # lane stage holds NC <= 128 chunks


@dataclasses.dataclass(frozen=True)
class VpermRoute:
    """Device-ready routing for one static permutation over ``total``
    padded elements (``n`` real).  Index planes are stored narrow
    (int8/int16) and upcast in-kernel; shapes are static per layout.

    ``i1/i3`` and ``i4/i6``: [NC*CH, 128] int8 lane indices for the two
    chunk stages' outer lane-gathers.  ``i2``/``i5``: [NC*128, CH] int16
    wide row-gather indices on the transposed [128, CH] chunk view.
    ``c``: [total/128, 128] int8 lane-packed middle-stage indices
    (``None`` when NC == 1 and the middle stage is the identity).
    """

    n: int
    nc: int
    i1: jnp.ndarray
    i2: jnp.ndarray
    i3: jnp.ndarray
    c: object
    i4: object
    i5: object
    i6: object

    @property
    def total(self) -> int:
        return self.nc * CS


tree_util.register_dataclass(
    VpermRoute,
    data_fields=("i1", "i2", "i3", "c", "i4", "i5", "i6"),
    meta_fields=("n", "nc"),
)


def _chunk_stage_arrays(rows: np.ndarray):
    """Factor per-chunk CS-perms into the 5-stage micro-Clos planes.

    ``rows`` is [NC, CS] int64: row i is the permutation applied within
    chunk i (y_chunk = x_chunk[rows[i]]).  Returns (i1 [NC*CH, 128] int8,
    i2 [NC*128, CH] int16, i3 [NC*CH, 128] int8).
    """
    nc = rows.shape[0]
    i1 = np.empty((nc * CH, LANES), np.int8)
    i2 = np.empty((nc * LANES, CH), np.int16)
    i3 = np.empty((nc * CH, LANES), np.int8)
    for i in range(nc):
        r = route_permutation(rows[i], a=CH, b=LANES, device=False)
        # clos stage semantics (apply_clos_grid): lane-gather by p1 on
        # [CH,128], transpose, row-gather by p2 on [128,CH], transpose,
        # lane-gather by p3.
        i1[i * CH:(i + 1) * CH] = r.p1.astype(np.int8)
        i2[i * LANES:(i + 1) * LANES] = r.p2.astype(np.int16)
        i3[i * CH:(i + 1) * CH] = r.p3.astype(np.int8)
    return i1, i2, i3


def _pack_middle(cidx: np.ndarray, nc: int) -> np.ndarray:
    """Lane-pack the [CS, NC] per-row middle perms into [total/128, 128].

    NC divides 128, so each vreg row holds 128/NC whole logical rows;
    the packed lane index for flat position p*128+l is
    ``(l//NC)*NC + cidx[s, l%NC]`` with ``s = (p*128+l)//NC`` — still a
    within-128-lane gather.
    """
    cs = cidx.shape[0]
    total = cs * nc
    flat = np.arange(total, dtype=np.int64)
    s = flat // nc
    c = flat % nc
    packed = ((flat % 128) // nc * nc + cidx[s, c]).astype(np.int8)
    return packed.reshape(total // LANES, LANES)


def route_vperm(perm: np.ndarray) -> VpermRoute:
    """Route ``y = x[perm]`` (n-element permutation, n ≤ MAX_N).

    The domain pads to NC whole chunks (NC a power of two ≤ 128); pad
    slots map identically so padded inputs carry zeros through
    untouched.
    """
    perm = np.ascontiguousarray(perm, dtype=np.int64)
    n = perm.size
    if n > MAX_N:
        raise ValueError(
            f"vperm supports up to {MAX_N:,} elements single-device "
            f"(got {n:,}); shard the layout across devices first"
        )
    if n and (perm.min() < 0 or perm.max() >= n
              or np.bincount(perm, minlength=n).max() != 1):
        raise ValueError("perm is not a permutation of [0, n)")
    nc = max(1, -(-n // CS))
    if nc & (nc - 1):
        nc = 1 << nc.bit_length()  # power of two so NC divides 128
    total = nc * CS
    full = np.arange(total, dtype=np.int64)
    full[:n] = perm

    # Macro Clos on [NC, CS]: row stages become chunk-local perms, the
    # middle stage becomes per-column NC-perms (the lane stage after the
    # transpose).  For NC == 1 the single chunk stage R1 carries the
    # whole permutation and the rest of the pipeline is skipped.
    if nc == 1:
        i1, i2, i3 = _chunk_stage_arrays(full[None, :])
        c = i4 = i5 = i6 = None
    else:
        r = route_permutation(full, a=nc, b=CS, device=False)
        i1, i2, i3 = _chunk_stage_arrays(r.p1.astype(np.int64))
        c = jnp.asarray(_pack_middle(r.p2.astype(np.int64), nc))
        i4, i5, i6 = (
            jnp.asarray(p)
            for p in _chunk_stage_arrays(r.p3.astype(np.int64))
        )

    return VpermRoute(
        n=n, nc=nc,
        i1=jnp.asarray(i1), i2=jnp.asarray(i2), i3=jnp.asarray(i3),
        c=c, i4=i4, i5=i5, i6=i6,
    )


def _chunk_kernel(x_ref, i1_ref, i2_ref, i3_ref, o_ref):
    """Fused 5-stage micro-Clos over one [CH, 128] chunk in VMEM."""
    y = jnp.take_along_axis(
        x_ref[...], i1_ref[...].astype(jnp.int32), axis=1
    )
    y = y.T  # [128, CH] in VMEM
    y = jnp.take_along_axis(y, i2_ref[...].astype(jnp.int32), axis=1)
    y = y.T
    o_ref[...] = jnp.take_along_axis(
        y, i3_ref[...].astype(jnp.int32), axis=1
    )


def _lane_kernel(x_ref, c_ref, o_ref):
    o_ref[...] = jnp.take_along_axis(
        x_ref[...], c_ref[...].astype(jnp.int32), axis=1
    )


def _chunk_pass(x2d: Array, i1: Array, i2: Array, i3: Array, nc: int,
                interpret: bool) -> Array:
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _chunk_kernel,
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
            pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
            pl.BlockSpec((LANES, CH), lambda i: (i, 0)),
            pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, i1, i2, i3)


def _lane_pass(x2d: Array, c: Array, interpret: bool) -> Array:
    from jax.experimental import pallas as pl

    n_tiles = x2d.shape[0] // CH
    return pl.pallas_call(
        _lane_kernel,
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
            pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((CH, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, c)


@functools.partial(jax.jit, static_argnames=("interpret",))
def apply_vperm(x: Array, route: VpermRoute,
                interpret: bool = False) -> Array:
    """Apply the routed permutation to a flat [n] array → flat [n].

    Pipeline: chunk pass R1 → transpose [NC,CS]→[CS,NC] → lane-packed
    middle pass → transpose back → chunk pass R2.  Three pallas passes
    plus two XLA transposes, no data-dependent XLA ops.  NC == 1 runs
    the single chunk pass only.
    """
    n, nc, total = route.n, route.nc, route.total
    if x.shape[0] != n:
        raise ValueError(f"length {x.shape[0]} != routed n {n}")
    dtype = x.dtype
    if total > n:
        x = jnp.concatenate([x, jnp.zeros(total - n, dtype)])
    g = x.reshape(nc * CH, LANES)
    g = _chunk_pass(g, route.i1, route.i2, route.i3, nc, interpret)
    if nc > 1:
        # [NC, CS] -> [CS, NC]: per-column NC-perms become lane-local
        # once packed; flat row-major order of the [CS, NC] view is the
        # packed [total/128, 128] layout _pack_middle indexed.
        t = g.reshape(nc, CS).T.reshape(nc * CH, LANES)
        t = _lane_pass(t, route.c, interpret)
        g = t.reshape(CS, nc).T.reshape(nc * CH, LANES)
        g = _chunk_pass(g, route.i4, route.i5, route.i6, nc, interpret)
    return g.reshape(total)[:n]


def invert_vperm(route: VpermRoute) -> VpermRoute:
    """The inverse permutation's route from the same routing (no second
    edge-coloring): run the pipeline backwards with each stage's rows
    inverted row-wise.  A chunk stage applies (i1, T, i2, T, i3); its
    inverse applies (inv i3, T, inv i2, T, inv i1) — the same kernel
    shape — and the middle lane stage inverts row-wise (each packed row
    is a 128-perm, so argsort per row is its inverse)."""

    def inv_rows(p):
        return jnp.argsort(p.astype(jnp.int32), axis=1).astype(p.dtype)

    if route.nc == 1:
        return VpermRoute(
            n=route.n, nc=1,
            i1=inv_rows(route.i3), i2=inv_rows(route.i2),
            i3=inv_rows(route.i1),
            c=None, i4=None, i5=None, i6=None,
        )
    return VpermRoute(
        n=route.n, nc=route.nc,
        i1=inv_rows(route.i6), i2=inv_rows(route.i5),
        i3=inv_rows(route.i4),
        c=inv_rows(route.c),
        i4=inv_rows(route.i3), i5=inv_rows(route.i2),
        i6=inv_rows(route.i1),
    )


def apply_vperm_reference(x: np.ndarray, perm: np.ndarray) -> np.ndarray:
    """NumPy oracle for tests."""
    return np.asarray(x)[np.asarray(perm)]
