"""Runtime selection of the sparse-gradient reduction kernel.

The production gradient has two available lowerings (see
ops/KERNEL_NOTES.md):

- **fm** — the pre-sorted segment-sum over the static FeatureMajorAux
  layout (no per-evaluation device sort, but pays an extra
  ``dz[rows]`` gather);
- **autodiff** — differentiate through the row-major margins, whose
  transpose is an unsorted scatter-add (XLA lowers it as sort +
  segmented reduce on TPU, but as a fast native scatter on CPU).

Which wins is a hardware property (measured: fm ~wins on TPU where the
scatter's device sort dominates; autodiff wins ~2x on CPU where scatter
is native) — so, like the reference's BLAS dispatch, the choice is made
by a one-time EAGER measurement on the live backend, cached per
(platform, size bucket).  The probe runs at trace time with concrete
inputs (the same eager-probe pattern as ops/pallas_sparse.kernel_supported)
and costs a few hundred ms once per process per shape regime.

Override with ``PHOTON_SPARSE_GRAD=fm|autodiff|auto`` (default auto).
"""

from __future__ import annotations

import os
import time

import numpy as np

_CACHE: dict = {}

# Probe arrays are capped so the one-time measurement stays cheap even for
# billion-entry datasets; relative kernel cost is stable above this size.
_PROBE_MAX_ENTRIES = 1 << 21


def _bucket(n: int) -> int:
    return max(int(n).bit_length(), 1)


def _measure(e: int, d: int, n: int) -> bool:
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    flat_ids = rng.integers(0, d, size=e, dtype=np.int32)
    order = np.argsort(flat_ids, kind="stable")
    sorted_ids = jnp.asarray(flat_ids[order])
    rows = jnp.asarray((order % max(n, 1)).astype(np.int32))
    vals = jnp.asarray(rng.standard_normal(e).astype(np.float32))
    dz = jnp.asarray(rng.standard_normal(max(n, 1)).astype(np.float32))
    ids_j = jnp.asarray(flat_ids)

    def t(fn, *args, reps=3):
        fj = jax.jit(fn)
        np.asarray(fj(*args))  # compile + sync through a host copy
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fj(*args)
        np.asarray(out)
        return (time.perf_counter() - t0) / reps

    t_fm = t(
        lambda dz, r, v, i: jnp.sum(jax.ops.segment_sum(
            jnp.take(dz, r, axis=0) * v, i,
            num_segments=d, indices_are_sorted=True,
        )),
        dz, rows, vals, sorted_ids,
    )
    t_scatter = t(
        lambda v, i: jnp.sum(jnp.zeros(d, jnp.float32).at[i].add(v)),
        vals, ids_j,
    )
    return t_fm < t_scatter


def fm_path_wins(e_total: int, dim: int, n_rows: int) -> bool:
    """True when the pre-sorted segment-sum path should carry the gradient
    for this problem size on the current backend."""
    mode = os.environ.get("PHOTON_SPARSE_GRAD", "auto")
    if mode == "fm":
        return True
    if mode == "autodiff":
        return False
    import jax

    key = (jax.default_backend(), _bucket(e_total), _bucket(dim))
    if key not in _CACHE:
        try:
            scale = max(1, -(-e_total // _PROBE_MAX_ENTRIES))  # ceil: cap probe size
            e = max(e_total // scale, 1 << 10)
            n = max(n_rows // scale, 64)
            _CACHE[key] = _measure(e, dim, n)
        except Exception:  # noqa: BLE001 — a failed probe must not kill training
            _CACHE[key] = True  # fm is the TPU-safe default
        import logging

        # Logged because auto-selection is a wall-clock measurement: on a
        # machine near the kernel crossover two runs can pick different
        # kernels, whose different reduction orders give slightly different
        # float results.  Pin PHOTON_SPARSE_GRAD=fm|autodiff for bitwise
        # same-seed reproducibility (SURVEY.md §5 determinism note).
        logging.getLogger("photon_tpu.sparse_grad").info(
            "sparse-grad kernel for backend=%s e~2^%d d~2^%d: %s",
            key[0], key[1], key[2], "fm" if _CACHE[key] else "autodiff",
        )
    return _CACHE[key]
