"""Runtime selection of the sparse-gradient reduction kernel.

The production gradient has three available lowerings (see
ops/KERNEL_NOTES.md):

- **fm** — the pre-sorted segment-sum over the static FeatureMajorAux
  layout (no per-evaluation device sort, but pays an extra
  ``dz[rows]`` gather and an E-element segment sum);
- **autodiff** — differentiate through the row-major margins, whose
  transpose is an unsorted scatter-add (XLA lowers it as sort +
  segmented reduce on TPU, but as a fast native scatter on CPU);
- **pallas** — the slab-aligned Mosaic kernel
  (ops/pallas_gather.aligned_segment_grad): same ``dz[rows]`` gather,
  then a per-tile 8-way masked position reduce in VMEM and a TINY
  sorted segment-sum over the slab dictionary (n_slabs*1024 values
  instead of E).  Requires the batch to carry an AlignedLayoutDev
  (``attach_feature_major(..., aligned_dim=d)``) and Mosaic to lower
  the kernel on the local backend.

Which wins is a hardware property — so, like the reference's BLAS
dispatch, the choice is made by a one-time EAGER measurement on the live
backend, cached per (platform, size bucket, candidate set).  The probe
runs at trace time with concrete inputs (the same eager-probe pattern as
ops/pallas_sparse.kernel_supported) and costs a few hundred ms once per
process per shape regime.

Override with ``PHOTON_SPARSE_GRAD=fm|autodiff|pallas|xchg|benes|auto``
(default auto).  The pallas and xchg candidates enter auto mode only on
a real TPU backend (interpret mode on CPU is a test vehicle, orders of
magnitude slower).  ``xchg`` (ops/vperm.py) replaces the per-step
E-element ``dz[rows]`` gather with a 3-pass static vperm pipeline — the
round-4 third-window design; it auto-probes when the batch carries a
route (``xchg_route_wanted``).  ``benes`` — the XLA-staged
static-permutation kernel (ops/benes.py) — was REFUTED on hardware
(0.168 steps/s) and stays explicit-opt-in as a research path.
"""

from __future__ import annotations

import os
import time

import numpy as np

_CACHE: dict = {}

# Probe arrays are capped so the one-time measurement stays cheap even for
# billion-entry datasets; relative kernel cost is stable above this size.
# Overridable (PHOTON_SPARSE_PROBE_MAX_ENTRIES) for callers who want the
# probe at the true problem shape — bench.py pays ~10 s once to attribute
# its headline to the kernel that actually wins at full size.
_PROBE_MAX_ENTRIES = 1 << 21


def _probe_cap() -> int:
    # Clamp at 1: 0 would divide-by-zero in the ceil, negatives would uncap
    # the probe (a billion-entry dataset would then build a multi-GB probe).
    from photon_tpu.utils.env import env_int

    return env_int(
        "PHOTON_SPARSE_PROBE_MAX_ENTRIES", _PROBE_MAX_ENTRIES, minimum=1
    )


def _probe_floor() -> int:
    # 0 (or negative == default-out) disables the floor entirely.
    from photon_tpu.utils.env import env_int

    return env_int("PHOTON_SPARSE_PROBE_FLOOR", 1 << 20, minimum=0)


def _bucket(n: int) -> int:
    return max(int(n).bit_length(), 1)


def _measure(e: int, d: int, n: int, with_pallas: bool,
             with_xchg: bool = False, xchg_baked: bool = True,
             with_fm: bool = True) -> str:
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    flat_ids = rng.integers(0, d, size=e, dtype=np.int32)
    order = np.argsort(flat_ids, kind="stable")
    sorted_ids = jnp.asarray(flat_ids[order])
    rows = jnp.asarray((order % max(n, 1)).astype(np.int32))
    vals = jnp.asarray(rng.standard_normal(e).astype(np.float32))
    dz = jnp.asarray(rng.standard_normal(max(n, 1)).astype(np.float32))
    ids_j = jnp.asarray(flat_ids)

    def t(fn, *args, reps=3):
        # Chained-salt methodology (tools/probe_common.py): repeated
        # IDENTICAL calls are not decision-grade under the tunneled
        # backend (an E-gather "ran" at 3x the HBM roofline in the
        # round-4 third window) — salt the first argument per rep so no
        # call can be served from a cache, prepare the salt OUTSIDE the
        # timed window, and fetch the scalar host-side per rep.
        fj = jax.jit(fn)
        float(np.asarray(fj(*args)).ravel()[0])  # compile + sync
        ts = []
        for i in range(reps):
            salted = args[0] + jnp.float32((i + 1) * 1e-12)
            jax.block_until_ready(salted)
            t0 = time.perf_counter()
            out = fj(salted, *args[1:])
            float(np.asarray(out).ravel()[0])
            ts.append(time.perf_counter() - t0)
        return float(np.median(ts))

    timings = {
        "autodiff": t(
            lambda v, i: jnp.sum(jnp.zeros(d, jnp.float32).at[i].add(v)),
            vals, ids_j,
        ),
    }
    if with_fm:
        # Only a candidate when the batch actually carries the fm aux
        # (streamed fast-kernel chunks attach al/xchg without fm); a
        # winning-but-unavailable fm verdict would be sanitized to
        # autodiff by select_kernel, masking a genuinely faster xchg.
        timings["fm"] = t(
            lambda dz, r, v, i: jnp.sum(jax.ops.segment_sum(
                jnp.take(dz, r, axis=0) * v, i,
                num_segments=d, indices_are_sorted=True,
            )),
            dz, rows, vals, sorted_ids,
        )
    if with_pallas or with_xchg:
        from photon_tpu.ops.pallas_gather import (
            aligned_grad_reference,
            aligned_segment_grad,
            device_layout,
            load_or_build_aligned_layout,
        )

        # Probe on the same entry population, reshaped to the batch's [n, k]
        # padded-COO convention so the layout build is representative.
        # (The xchg aligned-mode probe also needs this layout; the cumsum
        # mode only needs the id grid, but the build is cheap at probe
        # size and keeps one code path.)
        k = max(e // max(n, 1), 1)
        n_probe = e // k
        layout = load_or_build_aligned_layout(
            flat_ids[: n_probe * k].reshape(n_probe, k),
            np.asarray(vals)[: n_probe * k].reshape(n_probe, k),
            d,
        )
        al = device_layout(layout)
        dz_probe = jnp.asarray(rng.standard_normal(n_probe).astype(np.float32))
    if with_pallas:
        # Correctness gate BEFORE timing eligibility: the XLA candidates are
        # stock lowerings, but pallas is our Mosaic kernel running on
        # whatever backend is live — validate its full gradient against the
        # NumPy layout reference once, on-device, and disqualify on any
        # mismatch rather than silently corrupting production training.
        g_dev = np.asarray(aligned_segment_grad(dz_probe, al, d, interpret=False))
        g_ref = aligned_grad_reference(np.asarray(dz_probe), layout, d)
        scale = max(float(np.abs(g_ref).max()), 1.0)
        if np.allclose(g_dev, g_ref, rtol=2e-4, atol=1e-4 * scale):
            timings["pallas"] = t(
                lambda dz: jnp.sum(aligned_segment_grad(dz, al, d, interpret=False)),
                dz_probe,
            )
        else:
            import logging

            logging.getLogger("photon_tpu.sparse_grad").warning(
                "pallas kernel FAILED the on-device correctness gate "
                "(max abs err %.3g); excluded from auto selection",
                float(np.abs(g_dev - g_ref).max()),
            )
    if with_xchg:
        # Same correctness-gate-then-time discipline; the route build
        # (host edge-coloring) is the dominant probe cost, paid once
        # per shape bucket.  per_row here is dz over the probe's rows;
        # vals enter row-major, so the oracle is the same layout
        # reference the pallas gate used.
        try:
            from photon_tpu.ops.vperm import (
                build_xchg_aux,
                xchg_segment_grad,
            )

            ids2d = flat_ids[: n_probe * k].reshape(n_probe, k)
            vals2d_np = np.asarray(vals)[: n_probe * k].reshape(
                n_probe, k
            )
            # xchg_baked mirrors what the production batch carries: a
            # baked aux moves only the dz expansion per step (values
            # pre-permuted at attach); an unbaked one (streamed chunks)
            # exchanges the full product stream — materially different
            # data movement, so the probe times the matching variant.
            route = build_xchg_aux(
                layout, ids2d, d,
                vals=vals2d_np if xchg_baked else None,
            )
            vals2d = jnp.asarray(vals2d_np)
            g_dev = np.asarray(xchg_segment_grad(
                dz_probe, vals2d, al, route, d, interpret=False
            ))
            ref = np.zeros(d, np.float64)
            np.add.at(
                ref,
                flat_ids[: n_probe * k],
                (np.asarray(dz_probe)[:, None]
                 * np.asarray(vals2d)).reshape(-1).astype(np.float64),
            )
            scale = max(float(np.abs(ref).max()), 1.0)
            if np.allclose(g_dev, ref, rtol=2e-4, atol=1e-4 * scale):
                timings["xchg"] = t(
                    lambda dz: jnp.sum(xchg_segment_grad(
                        dz, vals2d, al, route, d, interpret=False
                    )),
                    dz_probe,
                )
            else:
                import logging

                logging.getLogger("photon_tpu.sparse_grad").warning(
                    "xchg kernel FAILED the on-device correctness gate "
                    "(max abs err %.3g); excluded from auto selection",
                    float(np.abs(g_dev - ref).max()),
                )
        except Exception as exc:  # noqa: BLE001 — probe must not kill
            import logging

            logging.getLogger("photon_tpu.sparse_grad").warning(
                "xchg probe unavailable (%s); excluded", exc
            )
    return min(timings, key=timings.get)


def _pallas_eligible() -> bool:
    import jax

    if jax.default_backend() != "tpu":
        return False
    from photon_tpu.ops.pallas_gather import reduce_kernel_supported

    return reduce_kernel_supported()


def select_kernel(
    e_total: int,
    dim: int,
    n_rows: int,
    has_fm: bool = True,
    has_aligned: bool = False,
    has_benes: bool = False,
    has_xchg: bool = False,
    xchg_baked: bool = True,
) -> str:
    """Pick the gradient kernel — ``"fm"``, ``"autodiff"``, ``"pallas"``,
    ``"benes"``, or ``"xchg"`` — for this problem size on the current
    backend, restricted to the layouts the batch actually carries."""
    mode = os.environ.get("PHOTON_SPARSE_GRAD", "auto")
    if mode == "autodiff":
        return "autodiff"
    if mode == "fm":
        return "fm" if has_fm else "autodiff"
    if mode == "pallas":
        # Forced pallas runs in interpret mode off-TPU (tests / parity
        # checks); it still needs the aligned layout on the batch.
        return "pallas" if has_aligned else ("fm" if has_fm else "autodiff")
    if mode == "xchg":
        # The vperm-exchange kernel: row-major products ride a static
        # 3-pass permutation into slot order, deleting the per-step
        # E-element dz[rows] gather (measured 493 ms at E=2^25).
        return "xchg" if has_xchg else (
            "pallas" if has_aligned else ("fm" if has_fm else "autodiff")
        )
    if mode == "benes":
        # Explicit opt-in only — REFUTED on hardware (0.168 steps/s,
        # KERNEL_NOTES round-4 third window); kept as a research path.
        return "benes" if has_benes else (
            "pallas" if has_aligned else ("fm" if has_fm else "autodiff")
        )
    import jax

    # Probe floor: below ~1M entries the eager measurement costs more than
    # any kernel difference could repay (GAME runs hit MANY small shape
    # buckets — one probe each), and autodiff is the measured winner on
    # both real TPU and CPU at small scale (KERNEL_NOTES round-4 table).
    if e_total < _probe_floor():
        return "autodiff"

    with_pallas = has_aligned and _pallas_eligible()
    # xchg needs Mosaic (its vperm passes are pallas kernels) but NOT the
    # aligned layout: the cumsum-reduce variant carries only a route +
    # bounds (streamed chunks attach exactly that), so coupling it to
    # has_aligned would waste every cumsum layout build in auto mode.
    with_xchg = has_xchg and _pallas_eligible()
    if not (has_fm or with_pallas or with_xchg):
        # Single-candidate set: nothing to measure (e.g. streamed xchg
        # chunks on a CPU backend, where Mosaic eligibility is off).
        return "autodiff"
    # The xchg timing depends on the reduce mode AND on whether values
    # were pre-permuted at attach (baked: only the dz expansion moves;
    # unbaked: the full product stream does) — both enter the key so a
    # streamed unbaked chunk never inherits a baked measurement and a
    # mid-process PHOTON_XCHG_REDUCE flip never serves the other mode's
    # verdict.
    xchg_cfg = (
        (os.environ.get("PHOTON_XCHG_REDUCE", "aligned"), bool(xchg_baked))
        if with_xchg else None
    )
    key = (
        jax.default_backend(), _bucket(e_total), _bucket(dim),
        with_pallas, with_xchg, xchg_cfg, bool(has_fm),
    )
    if key not in _CACHE:
        try:
            scale = max(1, -(-e_total // _probe_cap()))  # ceil: cap probe size
            e = max(e_total // scale, 1 << 10)
            n = max(n_rows // scale, 64)
            # ensure_compile_time_eval: this selection usually runs while
            # an ENCLOSING jit (the optimizer's while_loop, a streamed
            # chunk program) is being traced, and under omnistaging even
            # jit calls on concrete inputs inline into the outer trace —
            # the probe's host synchronizations would raise and the
            # except below would silently pin "autodiff" forever.  The
            # escape hatch executes the probe eagerly, so the cache holds
            # a real measurement wherever the first call happens.
            with jax.ensure_compile_time_eval():
                _CACHE[key] = _measure(
                    e, dim, n, with_pallas, with_xchg,
                    xchg_baked=bool(xchg_baked), with_fm=bool(has_fm),
                )
        except Exception:  # noqa: BLE001 — a failed probe must not kill training
            # Measured on real TPU hardware (KERNEL_NOTES.md round-4 table):
            # autodiff beats fm 1.881 vs 1.124 steps/s at the headline shape.
            _CACHE[key] = "autodiff"
        import logging

        # Logged because auto-selection is a wall-clock measurement: on a
        # machine near the kernel crossover two runs can pick different
        # kernels, whose different reduction orders give slightly different
        # float results.  Pin PHOTON_SPARSE_GRAD=fm|autodiff|pallas for
        # bitwise same-seed reproducibility (SURVEY.md §5 determinism note).
        logging.getLogger("photon_tpu.sparse_grad").info(
            "sparse-grad kernel for backend=%s e~2^%d d~2^%d: %s",
            key[0], key[1], key[2], _CACHE[key],
        )
    choice = _CACHE[key]
    if choice == "xchg" and not has_xchg:
        choice = "pallas" if has_aligned else "fm"
    if choice == "pallas" and not has_aligned:
        choice = "fm"
    if choice == "fm" and not has_fm:
        choice = "autodiff"
    return choice


def aligned_layout_wanted(e_total: int | None = None) -> bool:
    """Should batch builders pay the host-side aligned-layout construction?
    True when the pallas kernel is forced, or could win auto-selection on
    this backend (TPU + Mosaic lowers the reduce kernel).  Builders call
    this so CPU runs never pay the bin-packing cost for a kernel auto mode
    will not pick.  Pass the entry count when known: below the probe floor
    auto mode is guaranteed to run autodiff, so the build would be pure
    wasted host time."""
    mode = os.environ.get("PHOTON_SPARSE_GRAD", "auto")
    if mode in ("pallas", "benes", "xchg"):
        return True
    if mode != "auto":
        return False
    if e_total is not None and e_total < _probe_floor():
        return False
    try:
        return _pallas_eligible()
    except Exception:  # noqa: BLE001 — never block batch build on a probe
        return False


def xchg_route_wanted(e_total: int) -> bool:
    """Should batch builders pay the vperm route construction (host
    edge-coloring, the costliest layout build)?  Forced mode always;
    auto mode only on a TPU backend above a size floor where the
    per-step gather the route deletes dominates the one-time build
    (override with PHOTON_XCHG_FLOOR; PHOTON_XCHG=0 disables)."""
    from photon_tpu.utils.env import env_int

    mode = os.environ.get("PHOTON_SPARSE_GRAD", "auto")
    if mode == "xchg":
        return True
    if mode != "auto" or os.environ.get("PHOTON_XCHG", "1") == "0":
        return False
    if e_total < env_int("PHOTON_XCHG_FLOOR", 1 << 23, minimum=1):
        return False
    try:
        if not _pallas_eligible():
            return False
        from photon_tpu.native.build import get_lib

        return get_lib() is not None
    except Exception:  # noqa: BLE001 — never block batch build on a probe
        return False


def fm_path_wins(e_total: int, dim: int, n_rows: int) -> bool:
    """Back-compat boolean view of :func:`select_kernel` (fm vs autodiff)."""
    return select_kernel(e_total, dim, n_rows, has_fm=True, has_aligned=False) == "fm"
