"""Model classes: GLMs and (in :mod:`photon_tpu.game`) GAME containers.

Equivalent of the reference's ``supervised/model`` package
(GeneralizedLinearModel and subclasses, Coefficients — SURVEY.md §2.1).
"""

from photon_tpu.models.glm import (  # noqa: F401
    Coefficients,
    GeneralizedLinearModel,
    LinearRegressionModel,
    LogisticRegressionModel,
    PoissonRegressionModel,
    SmoothedHingeLossLinearSVMModel,
    model_for_task,
)
