"""Generalized linear model classes.

Rebuild of the reference's model hierarchy (photon-lib .../supervised/model:
``GeneralizedLinearModel``, ``LogisticRegressionModel``,
``LinearRegressionModel``, ``PoissonRegressionModel``,
``SmoothedHingeLossLinearSVMModel``, ``Coefficients`` — SURVEY.md §2.1).

A model is a thin, immutable wrapper over :class:`Coefficients` (means +
optional per-coefficient variances) plus the task's loss/link; scoring is a
batched margin computation on-device.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from photon_tpu.core.losses import PointwiseLoss, get_loss
from photon_tpu.data.batch import Batch, margins

Array = jax.Array


class Coefficients(NamedTuple):
    """Coefficient means + optional variances (GLMix posterior diagonal —
    the reference's Coefficients(means, variancesOption))."""

    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    @classmethod
    def zeros(cls, dim: int, dtype=jnp.float32) -> "Coefficients":
        return cls(means=jnp.zeros(dim, dtype))


@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """Base GLM: coefficients + task.

    ``compute_score`` is the raw margin (w.x + offset); ``predict`` applies
    the mean/inverse-link function, matching the reference's
    computeMean/score split.
    """

    coefficients: Coefficients
    loss: PointwiseLoss

    task_type: str = "custom"

    def compute_score(self, batch: Batch) -> Array:
        return margins(self.coefficients.means, batch)

    def predict(self, batch: Batch) -> Array:
        return self.loss.mean(self.compute_score(batch))

    def with_coefficients(self, coefficients: Coefficients) -> "GeneralizedLinearModel":
        return dataclasses.replace(self, coefficients=coefficients)


def LogisticRegressionModel(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(
        coefficients, get_loss("logistic"), task_type="logistic_regression"
    )


def LinearRegressionModel(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(
        coefficients, get_loss("squared"), task_type="linear_regression"
    )


def PoissonRegressionModel(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(
        coefficients, get_loss("poisson"), task_type="poisson_regression"
    )


def SmoothedHingeLossLinearSVMModel(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(
        coefficients,
        get_loss("smoothed_hinge"),
        task_type="smoothed_hinge_loss_linear_svm",
    )


_TASK_BUILDERS = {
    "logistic_regression": LogisticRegressionModel,
    "linear_regression": LinearRegressionModel,
    "poisson_regression": PoissonRegressionModel,
    "smoothed_hinge_loss_linear_svm": SmoothedHingeLossLinearSVMModel,
}


def model_for_task(task_type: str, coefficients: Coefficients) -> GeneralizedLinearModel:
    task = task_type.lower()
    if task not in _TASK_BUILDERS:
        raise KeyError(
            f"unknown task type {task_type!r}; available: {sorted(_TASK_BUILDERS)}"
        )
    return _TASK_BUILDERS[task](coefficients)
