"""Metrics registry: labeled counters, gauges, and histograms.

The reference publishes run statistics through Spark accumulators and the
driver logs (SURVEY.md §5 'Tracing'); this process-local registry is the
rebuild's equivalent: cheap thread-safe instruments that drivers, optimizers,
and the GAME descent loop write into, snapshotted at the end of a run into
the structured run report (:mod:`photon_tpu.telemetry.report`).

Instruments are created lazily and keyed by ``(name, labels)`` so call sites
can re-request a metric (``registry.counter("optimizer.runs", lam="0.1")``)
without holding a handle.  All values are host-side Python floats — nothing
here touches JAX or devices.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing count (rows scored, solves run, ...)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc by {amount})")
        with self._lock:
            self._value += float(amount)

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-written value (dataset size, best lambda, rows/s)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value: float | None = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float | None:
        return self._value


class Histogram:
    """Distribution of observations (per-solve seconds, chunk sizes).

    Keeps exact count/sum/min/max plus a bounded, deterministic reservoir
    for percentiles: once the reservoir fills it is decimated to every
    second sample and the keep-stride doubles, so memory stays O(cap) while
    the kept samples remain an even sweep of the observation sequence (no
    RNG — runs stay reproducible).
    """

    __slots__ = ("_lock", "count", "sum", "min", "max", "_kept", "_stride", "_cap")

    def __init__(self, lock: threading.RLock, cap: int = 256):
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._kept: List[float] = []
        self._stride = 1
        self._cap = cap

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            if self.count % self._stride == 0:
                self._kept.append(value)
                if len(self._kept) > self._cap:
                    self._kept = self._kept[::2]
                    self._stride *= 2
            self.count += 1
            self.sum += value

    @property
    def mean(self) -> float | None:
        return self.sum / self.count if self.count else None

    def percentile(self, p: float) -> float | None:
        """Approximate percentile from the kept reservoir (p in [0, 100])."""
        with self._lock:
            kept = sorted(self._kept)
        if not kept:
            return None
        idx = min(len(kept) - 1, max(0, round(p / 100.0 * (len(kept) - 1))))
        return kept[idx]

    def summary(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.mean,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    """Thread-safe registry of labeled instruments.

    One registry per run (owned by the
    :class:`~photon_tpu.telemetry.TelemetrySession`); ``snapshot()`` is the
    JSON-ready export embedded in the run report, ``to_prometheus()`` the
    text exposition for scraping a long-lived process.
    """

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[Tuple[str, LabelKey], Tuple[str, object]] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, object]):
        key = (name, _label_key(labels))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                existing_kind, metric = existing
                if existing_kind != kind:
                    raise TypeError(
                        f"metric {name!r}{dict(key[1])} already registered "
                        f"as {existing_kind}, requested as {kind}"
                    )
                return metric
            metric = self._KINDS[kind](self._lock)
            self._metrics[key] = (kind, metric)
            return metric

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels)

    def snapshot(self) -> dict:
        """JSON-ready ``{"counters": [...], "gauges": [...], "histograms":
        [...]}``, each entry ``{"name", "labels", ...value(s)}``, sorted by
        (name, labels) so identical runs export identical structures.
        Formats under the registry lock (the instruments share it, so a
        mid-``observe`` count/sum pair can never tear)."""
        out: dict = {"counters": [], "gauges": [], "histograms": []}
        with self._lock:
            for (name, labels), (kind, metric) in sorted(self._metrics.items()):
                entry = {"name": name, "labels": dict(labels)}
                if kind == "histogram":
                    entry.update(metric.summary())
                else:
                    entry["value"] = metric.value
                out[kind + "s"].append(entry)
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition: one ``# TYPE`` line per metric name,
        label values escaped per the text format, histograms exported as
        summaries with quantile labels.  Formats under the registry lock
        (see :meth:`snapshot`)."""

        def sanitize(name: str) -> str:
            return "".join(c if c.isalnum() or c == "_" else "_" for c in name)

        def escape(value: str) -> str:
            return (
                value.replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n")
            )

        def fmt_labels(labels: dict, extra: dict | None = None) -> str:
            merged = {**labels, **(extra or {})}
            if not merged:
                return ""
            body = ",".join(
                f'{sanitize(k)}="{escape(str(v))}"'
                for k, v in sorted(merged.items())
            )
            return "{" + body + "}"

        lines: List[str] = []
        typed: set = set()
        with self._lock:
            for (name, labels), (kind, metric) in sorted(self._metrics.items()):
                pname = sanitize(name)
                labels = dict(labels)
                if kind == "gauge" and metric.value is None:
                    continue
                prom_type = "summary" if kind == "histogram" else kind
                if pname not in typed:  # one TYPE line per name, ever
                    typed.add(pname)
                    lines.append(f"# TYPE {pname} {prom_type}")
                if kind in ("counter", "gauge"):
                    lines.append(f"{pname}{fmt_labels(labels)} {metric.value:g}")
                else:
                    for q in (0.5, 0.9, 0.99):
                        v = metric.percentile(q * 100)
                        if v is not None:
                            lines.append(
                                f"{pname}"
                                f"{fmt_labels(labels, {'quantile': f'{q:g}'})}"
                                f" {v:g}"
                            )
                    lines.append(f"{pname}_sum{fmt_labels(labels)} {metric.sum:g}")
                    lines.append(
                        f"{pname}_count{fmt_labels(labels)} {metric.count:g}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")
