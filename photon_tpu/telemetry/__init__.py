"""Structured telemetry: metrics registry, tracing spans, run reports.

The observability layer of the rebuild (SURVEY.md §5 'Tracing /
profiling').  One :class:`TelemetrySession` spans one driver run and owns:

- a :class:`~photon_tpu.telemetry.registry.MetricsRegistry` — labeled
  counters/gauges/histograms written by drivers, optimizers
  (:meth:`~photon_tpu.core.optimizers.base.OptimizationStatesTracker.record_to`),
  and the GAME descent loop;
- a :class:`~photon_tpu.telemetry.tracing.Tracer` — nested wall-clock spans
  (``PhotonLogger.timed`` phases feed it automatically once the session is
  attached to the logger);
- finalization into ``<output-dir>/telemetry/`` run-report artifacts
  (:mod:`photon_tpu.telemetry.report`).

Telemetry is on by default and gated twice: per-run by the drivers'
``--no-telemetry`` flag, globally by ``PHOTON_TELEMETRY=off`` (or 0/false).
A disabled session is a full no-op object — spans yield a null span,
instruments swallow writes, finalize writes nothing — so library code takes
a session unconditionally (``telemetry or NULL_SESSION``) and never
branches.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional

from photon_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from photon_tpu.telemetry.tracing import Span, Tracer  # noqa: F401
from photon_tpu.telemetry.distributed import (  # noqa: F401
    FlightRecorder,
    MergeableHistogram,
    SpanRecord,
    TraceCollector,
    TraceContext,
    TraceSampler,
    activate_trace,
    attach_trace,
    current_trace,
    new_trace_id,
    span_of,
    trace_of,
)

# photon_tpu.telemetry.report is imported lazily (build_report below): it is
# also the `python -m photon_tpu.telemetry.report` CLI, and importing it here
# would make runpy warn about the double import.

_ENV_VAR = "PHOTON_TELEMETRY"
_OFF_VALUES = ("off", "0", "false", "no")


def telemetry_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve the two gates: the env var kills telemetry process-wide
    (operator override, e.g. benchmark runs); otherwise the driver flag
    (default True) decides."""
    if os.environ.get(_ENV_VAR, "").strip().lower() in _OFF_VALUES:
        return False
    return True if flag is None else bool(flag)


class _NullMetric:
    """Write-only sink standing in for every instrument when disabled."""

    def inc(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    @property
    def value(self):
        return None


_NULL_METRIC = _NullMetric()


class _NullRegistry:
    def counter(self, name: str, **labels) -> _NullMetric:
        return _NULL_METRIC

    gauge = counter
    histogram = counter

    def snapshot(self) -> dict:
        return {"counters": [], "gauges": [], "histograms": []}

    def to_prometheus(self) -> str:
        return ""


class _NullSpan:
    def set_attribute(self, key: str, value) -> None:
        pass


_NULL_SPAN = _NullSpan()


class TelemetrySession:
    """Run-scoped telemetry: registry + tracer + report finalization.

    ``write`` (default True) lets multi-process drivers restrict artifact
    output to the primary rank after they learn their process index —
    instruments still record everywhere (cheap, and keeps rank behavior
    identical up to the filesystem).
    """

    def __init__(self, driver: str, enabled: bool = True):
        self.driver = driver
        self.enabled = enabled
        self.write = True
        self.registry = MetricsRegistry() if enabled else _NullRegistry()
        self.tracer = Tracer() if enabled else None
        self.started_at = time.time()
        self._t0 = time.monotonic()
        self.run_id = (
            f"{driver}-{time.strftime('%Y%m%d-%H%M%S', time.localtime(self.started_at))}"
            f"-{os.getpid()}"
        )
        self._finalized: Optional[dict] = None

    # -- instruments --------------------------------------------------------
    def counter(self, name: str, **labels):
        return self.registry.counter(name, **labels)

    def gauge(self, name: str, **labels):
        return self.registry.gauge(name, **labels)

    def histogram(self, name: str, **labels):
        return self.registry.histogram(name, **labels)

    @contextlib.contextmanager
    def span(self, name: str, **attributes) -> Iterator[object]:
        if self.tracer is None:
            yield _NULL_SPAN
            return
        with self.tracer.span(name, **attributes) as sp:
            yield sp

    def attach(self, logger) -> None:
        """Route the logger's ``timed()`` phases through this session's
        tracer (phase logs and spans stay one instrumentation point)."""
        if self.enabled:
            logger.tracer = self.tracer

    # -- finalization -------------------------------------------------------
    def build_report(self, status: str = "success",
                     error: Optional[str] = None,
                     extra: Optional[dict] = None) -> dict:
        from photon_tpu.telemetry.report import capture_environment

        report = {
            "driver": self.driver,
            "run_id": self.run_id,
            "status": status,
            "error": error,
            "started_at": self.started_at,
            "duration_s": time.monotonic() - self._t0,
            "environment": capture_environment(),
            "phase_totals": self.tracer.phase_totals() if self.tracer else {},
            "metrics": self.registry.snapshot(),
            "spans": self.tracer.export() if self.tracer else [],
        }
        if extra:
            report["extra"] = extra
        return report

    def finalize(self, output_dir: str, status: str = "success",
                 error: Optional[str] = None,
                 extra: Optional[dict] = None) -> Optional[dict]:
        """Build the run report and write ``telemetry/{run_report.json,
        spans.jsonl}`` under ``output_dir``.  Idempotent: a second call
        (e.g. the error path after a failed success-path write) returns the
        first report unchanged.  Returns None when disabled.  Never raises:
        a telemetry failure (unwritable output dir, disk quota) must not
        crash an otherwise-successful run, nor — on the error path —
        replace the driver's real exception with a telemetry traceback."""
        if not self.enabled:
            return None
        if self._finalized is not None:
            return self._finalized
        import json
        import logging

        try:
            report = self.build_report(status=status, error=error, extra=extra)
        except Exception as e:
            logging.getLogger("photon_tpu.telemetry").warning(
                "telemetry report build failed (%s: %s); run continues",
                type(e).__name__, e,
            )
            return None
        self._finalized = report
        if self.write and output_dir:
            try:
                tdir = os.path.join(output_dir, "telemetry")
                os.makedirs(tdir, exist_ok=True)
                with open(os.path.join(tdir, "run_report.json"), "w") as f:
                    # default=str: a non-JSON attribute (numpy scalar, Path)
                    # degrades to its repr.
                    json.dump(report, f, indent=1, default=str)
                self.tracer.write_jsonl(os.path.join(tdir, "spans.jsonl"))
            except Exception as e:
                logging.getLogger("photon_tpu.telemetry").warning(
                    "telemetry write to %s failed (%s: %s); run continues",
                    output_dir, type(e).__name__, e,
                )
        return report


NULL_SESSION = TelemetrySession("null", enabled=False)
