"""Tracing spans: nested wall-clock timing with attributes and status.

The structured upgrade of the reference's ``Timed { }`` phase logs
(SURVEY.md §5 'Tracing / profiling'): each instrumented region becomes a
span with a parent (nesting reconstructs the phase tree: driver run →
fit-config → descent iteration → coordinate solve), wall-clock duration,
free-form attributes, and an ok/error status recorded even when the region
raises.  Spans are process-local and host-side — device-level profiling
stays with ``jax.profiler`` (:func:`photon_tpu.utils.logging.maybe_profile`);
these spans answer "where did the run's wall-clock go" without a trace
viewer.

The active-span stack is thread-local, so spans opened on IO-pool worker
threads become roots of their own trees instead of corrupting the main
thread's nesting.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Iterator, List, Optional


class Span:
    """One timed region.  ``duration_s`` is None while the span is open."""

    __slots__ = (
        "name", "span_id", "parent_id", "start_time", "duration_s",
        "attributes", "status", "error", "thread",
    )

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 start_time: float, thread: str):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_time = start_time  # epoch seconds (for cross-run ordering)
        self.duration_s: Optional[float] = None
        self.attributes: dict = {}
        self.status = "ok"
        self.error: Optional[str] = None
        self.thread = thread

    def set_attribute(self, key: str, value) -> None:
        self.attributes[key] = value

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_time": self.start_time,
            "duration_s": self.duration_s,
            "status": self.status,
        }
        if self.attributes:
            out["attributes"] = self.attributes
        if self.error is not None:
            out["error"] = self.error
        if self.thread != "MainThread":
            out["thread"] = self.thread
        return out


class Tracer:
    """Creates spans, tracks the per-thread active stack, keeps finished
    spans for export (append order == completion order, children before
    parents)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._local = threading.local()
        self._next_id = 0
        self.finished: List[Span] = []

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def span(self, name: str, **attributes) -> Iterator[Span]:
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        sp = Span(name, span_id, parent, time.time(),
                  threading.current_thread().name)
        sp.attributes.update(attributes)
        t0 = time.monotonic()
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.status = "error"
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            sp.duration_s = time.monotonic() - t0
            stack.pop()
            with self._lock:
                self.finished.append(sp)

    def export(self) -> List[dict]:
        with self._lock:
            return [sp.to_dict() for sp in self.finished]

    def write_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for entry in self.export():
                # default=str: never crash a run over an attribute type.
                f.write(json.dumps(entry, default=str) + "\n")

    def phase_totals(self) -> dict:
        """Total seconds per span name over finished spans — the run
        report's wall-clock breakdown table (same shape as PhotonLogger's
        ``phase_times``, derived from spans instead of a parallel dict)."""
        totals: dict = {}
        with self._lock:
            spans = list(self.finished)
        for sp in spans:
            if sp.duration_s is not None:
                totals[sp.name] = totals.get(sp.name, 0.0) + sp.duration_s
        return totals
