"""Live fleet console: poll a FleetObserver's HTTP plane, render a table.

``python -m photon_tpu.telemetry.live --url http://127.0.0.1:PORT`` polls
the :class:`~photon_tpu.serving.observe.MetricsPlane` JSON endpoint (the
same server whose ``/metrics`` path speaks Prometheus text) and renders
the fleet snapshot as a terminal table: per-model-version QPS / p50 / p99
/ shed rate, merged child compute-latency quantiles, SLO burn-rate state,
and flight-dump count.  ``--once`` prints a single frame and exits (the
mode tests drive); without it the view refreshes every ``--interval``
seconds until interrupted.

Stdlib only (urllib) — the console must work wherever the fleet does,
including containers with nothing installed beyond the package itself.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def fetch_snapshot(url: str, timeout_s: float = 5.0) -> dict:
    """GET the observer's JSON snapshot (any path except /metrics)."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _fmt_ms(seconds) -> str:
    if seconds is None:
        return "-"
    return f"{float(seconds) * 1e3:.2f}ms"


def render_snapshot(snap: dict) -> str:
    """One console frame from a ``FleetObserver.fleet_snapshot()`` dict."""
    lines = []
    lines.append(
        f"fleet @ {time.strftime('%H:%M:%S')} — "
        f"window {snap.get('window_s', '?')}s, "
        f"{snap.get('traces', 0)} trace(s) kept, "
        f"{snap.get('flight_dumps', 0)} flight dump(s)"
    )
    versions = snap.get("versions") or {}
    header = (f"{'version':>8} {'qps':>8} {'rows/s':>10} {'p50':>10} "
              f"{'p99':>10} {'shed%':>7} {'err%':>6} {'reqs':>7}")
    lines.append(header)
    lines.append("-" * len(header))
    if not versions:
        lines.append("  (no traffic in window)")
    for version in sorted(versions, key=str):
        row = versions[version]
        lines.append(
            f"{str(version):>8} {row.get('qps', 0.0):>8.1f} "
            f"{row.get('rows_per_s', 0.0):>10.1f} "
            f"{_fmt_ms(row.get('p50_s')):>10} "
            f"{_fmt_ms(row.get('p99_s')):>10} "
            f"{100.0 * row.get('shed_rate', 0.0):>6.1f}% "
            f"{100.0 * row.get('error_rate', 0.0):>5.1f}% "
            f"{row.get('requests', 0):>7d}"
        )
    compute = snap.get("child_compute") or {}
    if compute.get("count"):
        lines.append(
            f"child compute: p50 {_fmt_ms(compute.get('p50_s'))} "
            f"p99 {_fmt_ms(compute.get('p99_s'))} "
            f"({compute['count']} batch(es))"
        )
    slo = snap.get("slo") or {}
    for row in slo.get("slos", []):
        state = "ALERT" if row.get("alerting") else "ok"
        lines.append(
            f"slo {row.get('name', '?'):<16} {state:<5} "
            f"fast-burn {row.get('fast_burn', 0.0):.2f} "
            f"slow-burn {row.get('slow_burn', 0.0):.2f}"
        )
    alerts = slo.get("alerts", [])
    if alerts:
        lines.append(f"alerts fired: {len(alerts)} "
                     f"(latest: {alerts[-1].get('slo', '?')})")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m photon_tpu.telemetry.live",
        description="Live console view of a serving fleet's metrics plane.",
    )
    parser.add_argument("--url", required=True,
                        help="observer HTTP address, e.g. http://127.0.0.1:9900")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds (default 2)")
    parser.add_argument("--once", action="store_true",
                        help="print one frame and exit")
    args = parser.parse_args(argv)

    url = args.url
    if not url.startswith("http"):
        url = f"http://{url}"
    while True:
        try:
            snap = fetch_snapshot(url)
        except Exception as e:  # noqa: BLE001 — operator-facing CLI
            print(f"live: fetch from {url} failed: {e}", file=sys.stderr)
            return 1
        print(render_snapshot(snap))
        if args.once:
            return 0
        print()
        time.sleep(max(0.05, args.interval))


if __name__ == "__main__":
    sys.exit(main())
