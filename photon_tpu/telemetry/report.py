"""Run reports: environment capture, JSON artifact, markdown rendering.

Every driver run finalizes its :class:`~photon_tpu.telemetry.TelemetrySession`
into ``<output-dir>/telemetry/``:

- ``run_report.json`` — status, duration, captured environment, the metrics
  registry snapshot, and the full span tree (the machine-readable record of
  the run; the reference's scattered driver logs, made structural).
- ``spans.jsonl`` — one span per line for trace tooling.

``python -m photon_tpu.telemetry.report <run_report.json>`` renders the
report as markdown (status header, environment, phase breakdown, metrics
tables) — the human-readable view, kept out of the hot path.

Telemetry artifacts live beside — never inside — ``training_summary.json``:
summaries stay byte-identical across identical runs (the determinism
contract tests/test_legacy_avro_determinism.py pins), while telemetry holds
all the wall-clock data.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import sys
from typing import Optional


def capture_environment() -> dict:
    """Host/process facts worth pinning to a run.

    JAX facts are captured only when jax is ALREADY imported — telemetry
    must never be the thing that initializes a backend (the indexing driver
    runs jax-free; multi-process ranks init on their own schedule).
    """
    env = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "photon_env": {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith("PHOTON_")
        },
    }
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        jax_info: dict = {"version": getattr(jax_mod, "__version__", None)}
        # Device facts ONLY from an already-initialized backend:
        # default_backend()/device_count() would otherwise trigger backend
        # init from inside telemetry — slow at best, a hang on a TPU-tunnel
        # platform at worst, and wrong for drivers that never touch devices.
        try:
            from jax._src import xla_bridge

            initialized = bool(getattr(xla_bridge, "_backends", None))
        except Exception:
            initialized = False
        if initialized:
            try:
                jax_info["backend"] = jax_mod.default_backend()
                jax_info["device_count"] = jax_mod.device_count()
                jax_info["process_index"] = jax_mod.process_index()
                jax_info["process_count"] = jax_mod.process_count()
            except Exception as e:  # never let capture kill a report
                jax_info["error"] = f"{type(e).__name__}: {e}"
        else:
            jax_info["backend"] = "uninitialized"
        env["jax"] = jax_info
    return env


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _fmt_labels(labels: dict) -> str:
    return ", ".join(f"{k}={v}" for k, v in sorted(labels.items())) or "—"


def _render_pipeline_section(report: dict) -> list:
    """The checkpoint-publisher / io-pool pipeline at a glance: how long
    the training loop actually blocked on checkpoint IO vs how long the
    background publishes took, plus the host-IO pool's live shape.  Empty
    when the run neither checkpointed nor pooled reads."""
    metrics = report.get("metrics") or {}
    hists = {
        (h["name"], tuple(sorted(h.get("labels", {}).items()))): h
        for h in metrics.get("histograms") or []
    }
    scalars = {
        (m["name"], tuple(sorted(m.get("labels", {}).items()))): m["value"]
        for m in (metrics.get("counters") or []) + (metrics.get("gauges") or [])
    }

    def hist(name):
        return hists.get((name, ()))

    def scalar(name):
        return scalars.get((name, ()))

    lines = []
    ckpt_rows = []
    for name, label in (
        ("checkpoint.write_seconds", "loop-side save (stage + submit)"),
        ("checkpoint.blocked_s", "loop blocked on previous publish"),
        ("checkpoint.publish_lag_s", "background publish (enqueue→landed)"),
    ):
        h = hist(name)
        if h and h.get("count"):
            ckpt_rows.append(
                f"| {name} | {label} | {h['count']} | {_fmt(h['mean'])} "
                f"| {_fmt(h['max'])} |"
            )
    if ckpt_rows or scalar("checkpoint.saves"):
        lines += ["", "## Checkpoint pipeline", ""]
        if scalar("checkpoint.saves") is not None:
            lines.append(f"- **saves**: {_fmt(scalar('checkpoint.saves'))}")
        if ckpt_rows:
            lines += ["", "| metric | meaning | count | mean (s) | max (s) |",
                      "|---|---|---|---|---|", *ckpt_rows]
    pool = {
        name: scalar(name)
        for name in ("io_pool.workers", "io_pool.in_flight_peak")
        if scalar(name) is not None
    }
    if pool:
        lines += ["", "## Host-IO pool", ""]
        for name, value in pool.items():
            lines.append(f"- **{name}**: {_fmt(value)}")
    # Elastic-resume / stall events: preemptions honored, watchdog stalls,
    # guarded-IO timeout escalations, and staged-RSS blocking fallbacks —
    # labeled counters, so sum over label variants.
    resilience = {}
    for name in ("descent.preempted", "watchdog.stalled",
                 "io.stall_timeouts", "checkpoint.staged_fallback_sync"):
        total = sum(
            m["value"] for m in metrics.get("counters") or []
            if m["name"] == name
        )
        if total:
            resilience[name] = total
    if resilience:
        lines += ["", "## Resilience events", ""]
        for name, value in resilience.items():
            lines.append(f"- **{name}**: {_fmt(value)}")
    return lines


def _render_streaming_section(report: dict) -> list:
    """The out-of-core stream's measured tier economics (``stream.*`` /
    ``tiles.*``): per-tier stall vs hidden-overlap seconds for the
    disk→host and host→device stages, plus the host-cache and disk-store
    shape of a spilled run.  Empty when the run never streamed."""
    metrics = report.get("metrics") or {}
    counters = metrics.get("counters") or []
    gauges = metrics.get("gauges") or []

    def plain(name, coll):
        for m in coll:
            if m["name"] == name and not m.get("labels"):
                return m["value"]
        return None

    def by_tier(name):
        out = {}
        for m in counters:
            if m["name"] == name:
                out[(m.get("labels") or {}).get("tier", "")] = m["value"]
        return out

    if plain("stream.chunks", counters) is None:
        return []
    lines = ["", "## Streaming tiers", "",
             f"- **chunks delivered**: {_fmt(plain('stream.chunks', counters))}"]
    stalls = by_tier("stream.stall_s")
    overlaps = by_tier("stream.prefetch_overlap_s")
    tiers = [t for t in ("disk", "h2d") if t in stalls or t in overlaps]
    if tiers:
        lines += ["", "| tier | stall (s) | overlap hidden (s) |",
                  "|---|---|---|"]
        for tier in tiers:
            lines.append(
                f"| {tier} | {_fmt(stalls.get(tier, 0.0))} "
                f"| {_fmt(overlaps.get(tier, 0.0))} |"
            )
    cache = {
        name: plain(name, counters)
        for name in ("tiles.cache_hits", "tiles.cache_misses",
                     "tiles.cache_evictions")
        if plain(name, counters) is not None
    }
    for name in ("tiles.host_cache_bytes", "tiles.disk_bytes"):
        value = plain(name, gauges)
        if value is not None:
            cache[name] = value
    if cache:
        lines.append("")
        for name, value in cache.items():
            lines.append(f"- **{name}**: {_fmt(value)}")
    return lines


def _render_entity_solves_section(report: dict) -> list:
    """The random-effect size-bin layout at a glance (``solves.*`` gauges):
    per (coordinate, bin) — routed solver, row capacity, live vs padded
    entities, and the padded fraction of the bin's entity×row cells — so
    the bin policy's padding waste is observable instead of guessed.
    Empty when the run trained no random-effect coordinate."""
    metrics = report.get("metrics") or {}
    by_bin: dict = {}
    for m in metrics.get("gauges") or []:
        if not m["name"].startswith("solves."):
            continue
        labels = m.get("labels", {})
        key = (labels.get("coordinate", "?"), labels.get("bin", "?"))
        entry = by_bin.setdefault(key, dict(labels))
        entry[m["name"]] = m["value"]
    if not by_bin:
        return []
    lines = [
        "", "## Entity solves", "",
        "| coordinate | bin | capacity | route | live entities "
        "| padded entities | padded fraction |",
        "|---|---|---|---|---|---|---|",
    ]
    for (coord, b) in sorted(by_bin):
        e = by_bin[(coord, b)]
        lines.append(
            f"| {coord} | {b} | {e.get('capacity', '—')} "
            f"| {e.get('route', '—')} "
            f"| {_fmt(e.get('solves.bin_occupancy'))} "
            f"| {_fmt(e.get('solves.bin_entities_padded'))} "
            f"| {_fmt(e.get('solves.padded_fraction'))} |"
        )
    return lines


def _render_serving_section(report: dict) -> list:
    """The online scoring service at a glance (``serving.*``): request/batch
    counters and the coalescing ratio they imply, padded fraction, cold
    entities, host syncs per batch (the ≤ 1 residency contract, made
    visible), and the latency/QPS numbers.  Empty when the run served
    nothing."""
    metrics = report.get("metrics") or {}
    counters = metrics.get("counters") or []
    gauges = metrics.get("gauges") or []

    def total(name):
        return sum(m["value"] for m in counters if m["name"] == name)

    def gauge(name):
        for m in gauges:
            if m["name"] == name and not m.get("labels"):
                return m["value"]
        return None

    batches = total("serving.batches")
    requests = total("serving.requests")
    if not batches and not requests:
        return []
    lines = ["", "## Online serving", ""]
    rows = [("serving.requests", requests),
            ("serving.batches", batches),
            ("serving.rows", total("serving.rows"))]
    if requests and batches:
        rows.append(("requests per batch (coalescing)",
                     round(requests / batches, 3)))
    if batches:
        rows.append(("serving.host_syncs per batch",
                     round(total("serving.host_syncs") / batches, 3)))
    cold = total("serving.cold_entities")
    if cold:
        rows.append(("serving.cold_entities", cold))
    compilations = total("serving.compilations")
    rows.append(("serving.compilations", compilations))
    for name in ("serving.qps", "serving.rows_per_second",
                 "serving.model_bytes"):
        v = gauge(name)
        if v is not None:
            rows.append((name, v))
    lines += ["| metric | value |", "|---|---|"]
    lines += [f"| {name} | {_fmt(value)} |" for name, value in rows]
    hists = [
        h for h in metrics.get("histograms") or []
        if h["name"] in ("serving.request_latency_s", "serving.score_seconds",
                         "serving.batch_rows", "serving.padded_fraction",
                         "serving.coalesced", "serving.admission_error_s")
    ]
    if hists:
        lines += ["", "| distribution | count | mean | p50 | p99 | max |",
                  "|---|---|---|---|---|---|"]
        for h in hists:
            lines.append(
                f"| {h['name']} | {h['count']} | {_fmt(h['mean'])} "
                f"| {_fmt(h['p50'])} | {_fmt(h['p99'])} | {_fmt(h['max'])} |"
            )
    return lines


def _render_fleet_section(report: dict) -> list:
    """The serving fleet at a glance (``serving.replica_*`` / shed /
    rollout metrics): per-replica traffic and health, the admission-control
    shed breakdown, the deadline hit rate over admitted requests, and the
    canary-rollout timeline.  Empty when the run never routed requests
    through a fleet (single-scorer serving keeps the plain "Online
    serving" section only)."""
    metrics = report.get("metrics") or {}
    counters = metrics.get("counters") or []
    gauges = metrics.get("gauges") or []

    def by_label(coll, name, label):
        out = {}
        for m in coll:
            if m["name"] == name:
                key = (m.get("labels") or {}).get(label, "?")
                out[key] = out.get(key, 0) + m["value"]
        return out

    def total(name):
        return sum(m["value"] for m in counters if m["name"] == name)

    replica_requests = by_label(counters, "serving.replica_requests",
                                "replica")
    if not replica_requests:
        return []
    replica_rows = by_label(counters, "serving.replica_rows", "replica")
    replica_deaths = by_label(counters, "serving.replica_deaths", "replica")
    rerouted = by_label(counters, "serving.rerouted", "replica")
    replica_qps = by_label(gauges, "serving.replica_qps", "replica")
    replica_depth = by_label(gauges, "serving.replica_depth", "replica")
    lines = ["", "## Serving fleet", "",
             "| replica | requests | rows | qps | depth peak (rows) "
             "| deaths | rerouted off |",
             "|---|---|---|---|---|---|---|"]
    for rid in sorted(replica_requests):
        lines.append(
            f"| {rid} | {_fmt(replica_requests[rid])} "
            f"| {_fmt(replica_rows.get(rid, 0))} "
            f"| {_fmt(replica_qps.get(rid))} "
            f"| {_fmt(replica_depth.get(rid))} "
            f"| {_fmt(replica_deaths.get(rid, 0))} "
            f"| {_fmt(rerouted.get(rid, 0))} |"
        )
    admitted = total("serving.admitted")
    shed = by_label(counters, "serving.shed", "reason")
    shed_total = sum(shed.values())
    offered = admitted + shed_total
    lines.append("")
    lines.append(f"- **admitted**: {_fmt(admitted)} of {_fmt(offered)} "
                 "offered")
    if shed_total:
        breakdown = ", ".join(
            f"{reason}={_fmt(count)}" for reason, count in sorted(shed.items())
        )
        lines.append(
            f"- **shed**: {_fmt(shed_total)} "
            f"({shed_total / offered:.1%} of offered) — {breakdown}"
        )
    missed = total("serving.deadline_missed")
    if admitted:
        lines.append(
            f"- **deadline hit rate**: {(admitted - missed) / admitted:.1%}"
            f" of admitted ({_fmt(missed)} missed)"
        )
    rollout_steps = []
    for m in gauges:
        if m["name"] == "serving.rollout_step":
            labels = m.get("labels") or {}
            rollout_steps.append(
                (m["value"], labels.get("replica", "?"),
                 labels.get("phase", "?"))
            )
    if rollout_steps:
        timeline = " → ".join(
            f"{rid}:{phase}" for _, rid, phase in sorted(rollout_steps)
        )
        lines.append(f"- **rollout timeline**: {timeline}")
    # Self-healing supervisor (ISSUE 13): deaths/restarts summary + the
    # event timeline (died-<cause> / respawn / rejoin-probe / rejoined /
    # respawn-failed / quarantined), same monotonic-gauge shape as the
    # rollout timeline.
    resurrections = by_label(counters, "serving.replica_resurrections",
                             "replica")
    quarantined = by_label(counters, "serving.replica_quarantined",
                           "replica")
    respawn_failures = total("serving.respawn_failures")
    supervisor_steps = []
    for m in gauges:
        if m["name"] == "serving.supervisor_step":
            labels = m.get("labels") or {}
            supervisor_steps.append(
                (m["value"], labels.get("replica", "?"),
                 labels.get("phase", "?"))
            )
    if resurrections or quarantined or respawn_failures or supervisor_steps:
        deaths_total = sum(replica_deaths.values())
        lines.append(
            f"- **supervisor**: deaths={_fmt(deaths_total)}, "
            f"resurrections={_fmt(sum(resurrections.values()))}, "
            f"respawn failures={_fmt(respawn_failures)}, "
            f"quarantined={_fmt(sum(quarantined.values()))}"
            + (f" ({', '.join(sorted(quarantined))})" if quarantined else "")
        )
    if supervisor_steps:
        timeline = " → ".join(
            f"{rid}:{phase}" for _, rid, phase in sorted(supervisor_steps)
        )
        lines.append(f"- **supervisor timeline**: {timeline}")
    # Child telemetry aggregation (ISSUE 14 satellite): subprocess
    # replicas' scorer counters arrive via the stats control frame merged
    # under the same names + a replica label — thread replicas' own
    # counters carry no replica label and are excluded here (key "?").
    child_syncs = by_label(counters, "serving.host_syncs", "replica")
    child_syncs.pop("?", None)
    if child_syncs:
        child_batches = by_label(counters, "serving.batches", "replica")
        child_cold = by_label(counters, "serving.cold_entities", "replica")
        parts = [
            f"{rid}: host_syncs={_fmt(child_syncs[rid])}, "
            f"batches={_fmt(child_batches.get(rid, 0))}, "
            f"cold_entities={_fmt(child_cold.get(rid, 0))}"
            for rid in sorted(child_syncs)
        ]
        lines.append("- **child scorers**: " + "; ".join(parts))
    return lines


def _render_online_section(report: dict) -> list:
    """The online-learning loop at a glance (``online.*`` + ``onboard.*``):
    rows/batches ingested, coordinates refreshed vs locked per refresh,
    the in-place device-data growth split (rows into headroom vs migrated
    vs new entities — the zero-full-rebuild contract made visible),
    append->serving refresh latency, and the staleness gauge.  Empty when
    the run performed no online refresh."""
    metrics = report.get("metrics") or {}
    counters = metrics.get("counters") or []
    gauges = metrics.get("gauges") or []

    def total(name):
        return sum(m["value"] for m in counters if m["name"] == name)

    def gauge(name):
        for m in gauges:
            if m["name"] == name and not m.get("labels"):
                return m["value"]
        return None

    refreshes = total("online.refreshes")
    ingested = total("online.rows_ingested")
    if not refreshes and not ingested:
        return []
    lines = ["", "## Online learning", "", "| metric | value |", "|---|---|"]
    rows = [
        ("online.refreshes", refreshes),
        ("online.batches_ingested", total("online.batches_ingested")),
        ("online.rows_ingested", ingested),
        ("online.coordinates_refreshed", total("online.coordinates_refreshed")),
        ("online.coordinates_locked", total("online.coordinates_locked")),
        ("online.publishes", total("online.publishes")),
    ]
    failures = total("online.refresh_failures")
    if failures:
        rows.append(("online.refresh_failures", failures))
    rollbacks = total("serving.rollout_rollbacks")
    if rollbacks:
        rows.append(("serving.rollout_rollbacks", rollbacks))
    for name in ("onboard.rows_in_place", "onboard.rows_migrated",
                 "onboard.entities_migrated", "onboard.entities_new",
                 "onboard.rows_absent"):
        v = total(name)
        if v:
            rows.append((name, v))
    stale = gauge("online.staleness_s")
    if stale is not None:
        rows.append(("online.staleness_s", stale))
    lines += [f"| {name} | {_fmt(value)} |" for name, value in rows]
    hists = [
        h for h in metrics.get("histograms") or []
        if h["name"] == "online.refresh_latency_s"
    ]
    if hists:
        lines += ["", "| distribution | count | mean | p50 | p99 | max |",
                  "|---|---|---|---|---|---|"]
        for h in hists:
            lines.append(
                f"| {h['name']} | {h['count']} | {_fmt(h['mean'])} "
                f"| {_fmt(h['p50'])} | {_fmt(h['p99'])} | {_fmt(h['max'])} |"
            )
    # Per-bin capacity headroom (the in-place growth budget): grouped like
    # the entity-solves section.
    by_bin: dict = {}
    for m in gauges:
        if not m["name"].startswith("onboard.bin_"):
            continue
        labels = m.get("labels", {})
        key = (labels.get("column", "?"), labels.get("bin", "?"))
        by_bin.setdefault(key, {})[m["name"]] = m["value"]
    if by_bin:
        lines += ["", "| column | bin | row cells | live rows | headroom |",
                  "|---|---|---|---|---|"]
        for (column, b) in sorted(by_bin):
            e = by_bin[(column, b)]
            lines.append(
                f"| {column} | {b} "
                f"| {_fmt(e.get('onboard.bin_row_capacity'))} "
                f"| {_fmt(e.get('onboard.bin_rows_live'))} "
                f"| {_fmt(e.get('onboard.bin_row_headroom'))} |"
            )
    return lines


def _render_observe_section(report: dict) -> list:
    """The fleet observability plane (ISSUE 16): cross-process trace
    critical paths (queue vs batch-wait vs transport vs compute per
    request, stage sum reconciling with end-to-end latency by
    construction), SLO burn-rate state + fired alerts, and the flight
    dumps collected from dead replicas.  Reads the driver-provided
    ``extra["observe"]`` payload (``FleetObserver.export()``); empty when
    the run was not observed."""
    observe = (report.get("extra") or {}).get("observe") or {}
    if not observe:
        return []
    lines = ["", "## Fleet traces / SLOs", ""]
    lines.append(
        f"- **tracing**: sample rate {_fmt(observe.get('sample_rate'))}, "
        f"{_fmt(observe.get('traces_kept'))} trace(s) kept, "
        f"{_fmt(observe.get('spans_merged'))} child span(s) merged"
    )
    paths = observe.get("critical_paths") or []
    if paths:
        stage_names = [s["stage"] for s in paths[0].get("stages", [])]
        lines += ["",
                  "| trace | procs | spans | total (s) | "
                  + " | ".join(f"{n} (s)" for n in stage_names) + " |",
                  "|---|---|---|---|" + "---|" * len(stage_names)]
        for cp in paths:
            stages = {s["stage"]: s["duration_s"]
                      for s in cp.get("stages", [])}
            lines.append(
                f"| {cp.get('trace_id', '?')} "
                f"| {len(cp.get('processes', []))} "
                f"| {_fmt(cp.get('spans'))} | {_fmt(cp.get('total_s'))} | "
                + " | ".join(_fmt(stages.get(n)) for n in stage_names)
                + " |"
            )
    slo = observe.get("slo") or {}
    slos = slo.get("slos") or []
    if slos:
        lines += ["", "| SLO | kind | objective | budget | fast burn "
                  "| slow burn | state |",
                  "|---|---|---|---|---|---|---|"]
        for row in slos:
            state = "**ALERT**" if row.get("alerting") else "ok"
            lines.append(
                f"| {row.get('name', '?')} | {row.get('kind', '?')} "
                f"| {_fmt(row.get('objective'))} | {_fmt(row.get('budget'))} "
                f"| {_fmt(row.get('fast_burn'))} "
                f"| {_fmt(row.get('slow_burn'))} | {state} |"
            )
    alerts = slo.get("alerts") or []
    if alerts:
        parts = ", ".join(
            f"{a.get('slo', '?')} (fast {_fmt(a.get('fast_burn'))}×)"
            for a in alerts
        )
        lines.append(f"- **alerts fired**: {len(alerts)} — {parts}")
    dumps = observe.get("flight_dumps") or []
    if dumps:
        lines += ["", "### Flight dumps", ""]
        for d in dumps:
            where = d.get("path") or "(in memory)"
            lines.append(
                f"- **{d.get('replica', '?')}** g{d.get('generation', 0)} "
                f"({d.get('cause', '?')}): "
                f"{_fmt(d.get('child_records'))} child record(s), "
                f"{_fmt(d.get('lost_spans_recovered'))} lost span(s) "
                f"recovered — {where}"
            )
    return lines


def render_markdown(report: dict) -> str:
    """Human-readable view of a run report dict."""
    lines = [
        f"# Run report: {report.get('driver', '?')}",
        "",
        f"- **run id**: {report.get('run_id', '?')}",
        f"- **status**: {report.get('status', '?')}"
        + (f" — {report['error']}" if report.get("error") else ""),
        f"- **duration**: {_fmt(report.get('duration_s'))} s",
    ]
    env = report.get("environment", {})
    if env:
        lines += ["", "## Environment", ""]
        for key in ("python", "platform", "pid"):
            if key in env:
                lines.append(f"- **{key}**: {env[key]}")
        jax_info = env.get("jax")
        if jax_info:
            lines.append(
                "- **jax**: "
                + ", ".join(f"{k}={v}" for k, v in jax_info.items())
            )
        if env.get("photon_env"):
            lines.append(
                "- **PHOTON_ env**: "
                + ", ".join(f"{k}={v}" for k, v in env["photon_env"].items())
            )

    totals = report.get("phase_totals") or {}
    if totals:
        lines += ["", "## Wall-clock by phase", "",
                  "| phase | total (s) |", "|---|---|"]
        for name, secs in sorted(totals.items(), key=lambda kv: -kv[1]):
            lines.append(f"| {name} | {secs:.3f} |")

    lines += _render_pipeline_section(report)
    lines += _render_streaming_section(report)
    lines += _render_entity_solves_section(report)
    lines += _render_serving_section(report)
    lines += _render_fleet_section(report)
    lines += _render_observe_section(report)
    lines += _render_online_section(report)

    metrics = report.get("metrics") or {}
    counters = metrics.get("counters") or []
    gauges = metrics.get("gauges") or []
    if counters or gauges:
        lines += ["", "## Metrics", "",
                  "| metric | labels | value |", "|---|---|---|"]
        for entry in counters + gauges:
            lines.append(
                f"| {entry['name']} | {_fmt_labels(entry['labels'])} "
                f"| {_fmt(entry['value'])} |"
            )
    histograms = metrics.get("histograms") or []
    if histograms:
        lines += ["", "## Distributions", "",
                  "| metric | labels | count | mean | p50 | p99 | max |",
                  "|---|---|---|---|---|---|---|"]
        for entry in histograms:
            lines.append(
                f"| {entry['name']} | {_fmt_labels(entry['labels'])} "
                f"| {entry['count']} | {_fmt(entry['mean'])} "
                f"| {_fmt(entry['p50'])} | {_fmt(entry['p99'])} "
                f"| {_fmt(entry['max'])} |"
            )

    spans = report.get("spans") or []
    if spans:
        lines += ["", f"## Spans ({len(spans)})", ""]
        # Children finish before parents, so rebuild the tree for display.
        by_parent: dict = {}
        for sp in spans:
            by_parent.setdefault(sp.get("parent_id"), []).append(sp)

        def walk(parent_id, depth):
            for sp in sorted(
                by_parent.get(parent_id, []), key=lambda s: s["start_time"]
            ):
                flag = "" if sp.get("status") == "ok" else " **[error]**"
                lines.append(
                    f"{'  ' * depth}- {sp['name']}: "
                    f"{_fmt(sp.get('duration_s'))} s{flag}"
                )
                walk(sp["span_id"], depth + 1)

        walk(None, 0)
    return "\n".join(lines) + "\n"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "photon_tpu.telemetry.report",
        description="Render a telemetry run_report.json as markdown.",
    )
    p.add_argument("report", help="path to run_report.json (or a driver "
                   "output dir containing telemetry/run_report.json)")
    p.add_argument("-o", "--output", default=None,
                   help="write markdown here instead of stdout")
    return p


def resolve_report_path(path: str) -> str:
    if os.path.isdir(path):
        nested = os.path.join(path, "telemetry", "run_report.json")
        return nested if os.path.exists(nested) else os.path.join(
            path, "run_report.json"
        )
    return path


def main(argv: Optional[list] = None) -> None:
    args = build_parser().parse_args(argv)
    with open(resolve_report_path(args.report)) as f:
        report = json.load(f)
    text = render_markdown(report)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


if __name__ == "__main__":
    main()
