"""Cross-process request tracing, mergeable snapshots, flight recorder.

The fleet (router → replica subprocess → scorer batch) is a distributed
system whose existing telemetry is per-process and end-of-run: a request
that is shed, rerouted around a dead replica, or slowed by batch-wait
leaves no record that crosses a process boundary.  This module is the
wire-level half of the observability plane (the fleet-facing half lives in
:mod:`photon_tpu.serving.observe`):

- :class:`TraceContext` — a trace id + parent span id small enough to ride
  the length-prefixed frame protocol's JSON header on every hop;
- :class:`SpanRecord` — a mutable per-hop span that accumulates timestamped
  events (enqueue, admit/shed, coalesce, dispatch, compute, egress) and
  serializes to a plain dict;
- :class:`TraceSampler` — deterministic rate-based sampling so the hot
  path stays cheap (no RNG: runs stay reproducible);
- :class:`TraceCollector` — the parent-side merge point: spans from every
  process land here, keyed by trace id, bounded to the most recent traces;
  :meth:`TraceCollector.critical_path` decomposes one request into
  queue / batch-wait / compute / transport stages whose sum reconciles
  with the measured end-to-end latency by construction;
- :class:`MergeableHistogram` — fixed-bucket counts that merge across
  processes by addition (the registry's reservoir histograms cannot merge:
  two reservoirs with different strides have no sound union);
- :class:`FlightRecorder` — a bounded ring of recent spans/events/frame
  summaries each replica keeps; persisted next to the run report when the
  supervisor declares the replica dead, so postmortems start with the
  victim's final seconds.

Everything here is host-side Python over plain dicts — nothing touches JAX
or devices, and every record is JSON-ready so it can ride frame headers
and land in run reports unchanged.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Iterator, List, Optional

__all__ = [
    "TraceContext",
    "SpanRecord",
    "TraceSampler",
    "TraceCollector",
    "MergeableHistogram",
    "FlightRecorder",
    "new_trace_id",
    "attach_trace",
    "trace_of",
    "attach_span",
    "span_of",
    "activate_trace",
    "current_trace",
    "shift_span_times",
]


def shift_span_times(spans: List[dict], offset_s: float) -> List[dict]:
    """Map child-process span timestamps onto the parent's clock before
    merging trace trees.  ``offset_s`` is the estimated child-minus-parent
    wall-clock offset (from control-connection ping RTT: the child's
    ``pong`` echoes its ``time.time()``, and the parent estimates
    ``offset = child_time - (t_send + t_recv) / 2``); subtracting it
    de-skews ``start`` and every event ``t`` so a skewed host can no
    longer misorder cross-process hops on the merged timeline.  Durations
    are untouched — they were measured monotonically on the child and are
    already skew-free.  Mutates and returns ``spans`` (the caller owns the
    freshly-deserialized wire dicts)."""
    if not offset_s:
        return spans
    for d in spans or []:
        if not isinstance(d, dict):
            continue
        if isinstance(d.get("start"), (int, float)):
            d["start"] = d["start"] - offset_s
        for e in d.get("events") or []:
            if isinstance(e, dict) and isinstance(e.get("t"), (int, float)):
                e["t"] = e["t"] - offset_s
    return spans

_id_lock = threading.Lock()
_id_counter = 0


def new_trace_id() -> str:
    """Process-unique id: pid-scoped counter + startup entropy.  Hex, short
    enough to ride every frame header without bloating small requests."""
    global _id_counter
    with _id_lock:
        _id_counter += 1
        n = _id_counter
    return f"{os.getpid():x}-{_ENTROPY}-{n:x}"


_ENTROPY = os.urandom(4).hex()


class TraceContext:
    """What crosses a process boundary: the trace id, the parent span id,
    and the sampling verdict (a child must not re-roll the sampling dice —
    a trace is whole or absent)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def to_wire(self) -> dict:
        return {"tid": self.trace_id, "sid": self.span_id}

    @classmethod
    def from_wire(cls, wire: Optional[dict]) -> Optional["TraceContext"]:
        if not wire or "tid" not in wire:
            return None
        return cls(str(wire["tid"]), str(wire.get("sid", "")), True)

    def child_of(self, span: "SpanRecord") -> "TraceContext":
        return TraceContext(self.trace_id, span.span_id, self.sampled)


class SpanRecord:
    """One hop of one trace: a named region in one process with timestamped
    events.  Mutable while open; :meth:`to_dict` is the wire/report form.

    Timestamps are epoch seconds (``time.time``) so events from different
    processes land on one axis; durations are measured monotonically so a
    clock step mid-span cannot produce a negative stage."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "process",
        "start", "duration_s", "events", "attrs", "status", "_t0",
    )

    def __init__(self, trace_id: str, name: str, process: str,
                 parent_id: Optional[str] = None,
                 span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id or new_trace_id()
        self.parent_id = parent_id
        self.name = name
        self.process = process
        self.start = time.time()
        self.duration_s: Optional[float] = None
        self.events: List[dict] = []
        self.attrs: dict = {}
        self.status = "ok"
        self._t0 = time.monotonic()

    def event(self, name: str, **attrs) -> None:
        e = {"name": name, "t": time.time()}
        if attrs:
            e.update(attrs)
        self.events.append(e)

    def finish(self, status: str = "ok") -> "SpanRecord":
        if self.duration_s is None:
            self.duration_s = time.monotonic() - self._t0
            self.status = status
        return self

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, True)

    def to_dict(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "process": self.process,
            "start": self.start,
            "duration_s": self.duration_s,
            "status": self.status,
            "events": list(self.events),
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out


class TraceSampler:
    """Deterministic rate sampling: an accumulator crosses 1.0 every
    ``1/rate`` requests, so a 0.1 rate samples exactly every 10th request
    — no RNG, so benchmark runs reproduce and the overhead bound is a
    property of the rate, not of luck."""

    __slots__ = ("rate", "_acc", "_lock")

    def __init__(self, rate: float = 1.0):
        self.rate = max(0.0, min(1.0, float(rate)))
        self._acc = 1.0 if self.rate > 0 else 0.0  # sample the first request
        self._lock = threading.Lock()

    def should_sample(self) -> bool:
        if self.rate <= 0.0:
            return False
        if self.rate >= 1.0:
            return True
        with self._lock:
            self._acc += self.rate
            if self._acc >= 1.0:
                self._acc -= 1.0
                return True
            return False


# -- critical-path stage names, in request order -----------------------------
STAGES = ("queue", "batch_wait", "transport", "compute", "child_other",
          "resolve")


class TraceCollector:
    """Parent-side merge point for spans from every process.

    Bounded: keeps the most recent ``capacity`` traces (eviction is by
    trace arrival order — a long run cannot grow memory without bound).
    ``merge_remote`` accepts span dicts shipped back from child replicas
    over the control connection or recovered from a flight-recorder dump.
    """

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, List[dict]]" = OrderedDict()
        self.spans_merged = 0
        self.spans_dropped = 0

    # -- ingest --------------------------------------------------------------
    def add(self, span) -> None:
        d = span.to_dict() if isinstance(span, SpanRecord) else dict(span)
        tid = d.get("trace_id")
        if not tid:
            return
        with self._lock:
            bucket = self._traces.get(tid)
            if bucket is None:
                while len(self._traces) >= self.capacity:
                    self._traces.popitem(last=False)
                    self.spans_dropped += 1
                bucket = self._traces[tid] = []
            else:
                self._traces.move_to_end(tid)
            bucket.append(d)
            self.spans_merged += 1

    def merge_remote(self, spans: List[dict]) -> int:
        """Merge spans shipped from another process; returns count merged.
        A span for an already-evicted trace re-opens it (the dump of a dead
        replica may arrive long after the trace finished)."""
        n = 0
        for d in spans or []:
            if isinstance(d, dict) and d.get("trace_id"):
                self.add(d)
                n += 1
        return n

    def recover_lost(self, trace_id: str, span: dict, reason: str) -> None:
        """Adopt an unfinished span recovered from a dead replica's flight
        dump as a terminal stub — the trace stays whole (no orphan hop)
        and the stub says why the hop never reported back."""
        stub = dict(span)
        stub["status"] = "lost"
        stub.setdefault("duration_s", 0.0)
        stub.setdefault("attrs", {})
        stub["attrs"] = dict(stub["attrs"], lost_reason=reason)
        self.add(stub)

    # -- queries -------------------------------------------------------------
    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces.keys())

    def trace(self, trace_id: str) -> List[dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def tree(self, trace_id: str) -> Optional[dict]:
        """The merged cross-process trace tree: each node is the span dict
        plus a ``children`` list; returns the root (parentless) node.
        Spans whose parent never arrived attach to the root rather than
        dangling — a merged trace has no orphans by construction."""
        spans = self.trace(trace_id)
        if not spans:
            return None
        nodes = {d["span_id"]: dict(d, children=[]) for d in spans}
        root = None
        for node in nodes.values():
            if node.get("parent_id") in nodes:
                nodes[node["parent_id"]]["children"].append(node)
            elif node.get("parent_id") is None and root is None:
                root = node
        if root is None:  # no parentless span shipped: oldest is the root
            root = min(nodes.values(), key=lambda n: n["start"])
        for node in nodes.values():
            if node is root:
                continue
            if node.get("parent_id") not in nodes:
                root["children"].append(node)
        return root

    def processes(self, trace_id: str) -> List[str]:
        return sorted({d.get("process", "?") for d in self.trace(trace_id)})

    def critical_path(self, trace_id: str) -> Optional[dict]:
        """Per-request stage decomposition for one trace.

        Anchored entirely on the ROOT span's clock: the root's events
        partition ``[enqueue, done]`` into queue → batch-wait → remote →
        resolve, and the remote segment is subdivided by the child span's
        *measured durations* (compute, other) with transport as the
        remainder, clamped at zero and rescaled if clock skew makes the
        child claim more time than the parent observed.  The stage sum
        therefore equals the measured end-to-end latency by construction.
        """
        spans = self.trace(trace_id)
        if not spans:
            return None
        # Anchor on the ROUTER hop — the span carrying the "enqueue" event
        # is where queue/batch-wait decomposition is defined.  A trace
        # rooted above it (a client span, an online-publish span) still
        # decomposes; a trace without one falls back to the tree root.
        root = next(
            (d for d in spans
             if d.get("duration_s") is not None
             and any(e.get("name") == "enqueue"
                     for e in d.get("events", ()))),
            None,
        )
        if root is None:
            root = next(
                (d for d in spans if d.get("parent_id") is None), None
            )
        if root is None or root.get("duration_s") is None:
            return None
        total = float(root["duration_s"])
        ev = {e["name"]: float(e["t"]) for e in root.get("events", ())}
        t0 = float(root["start"])
        t_end = t0 + total

        def at(name: str, default: float) -> float:
            return min(max(ev.get(name, default), t0), t_end)

        t_dispatch = at("dispatch", t0)
        t_score0 = at("score_begin", t_dispatch)
        t_score1 = at("score_end", t_end)
        stages = {
            "queue": max(0.0, t_dispatch - t0),
            "batch_wait": max(0.0, t_score0 - t_dispatch),
            "resolve": max(0.0, t_end - t_score1),
        }
        remote = max(0.0, t_score1 - t_score0)
        # Subdivide the remote segment with the child hop's own clock.
        child = next(
            (d for d in spans
             if d.get("parent_id") == root["span_id"]
             and d.get("process") != root.get("process")
             and d.get("duration_s") is not None),
            None,
        )
        if child is not None and remote > 0:
            child_total = min(float(child["duration_s"]), remote)
            cev = {e["name"]: float(e["t"]) for e in child.get("events", ())}
            compute = max(0.0, cev.get("compute_end", 0.0)
                          - cev.get("compute_begin", 0.0))
            compute = min(compute, child_total)
            stages["transport"] = remote - child_total
            stages["compute"] = compute
            stages["child_other"] = child_total - compute
        else:
            stages["transport"] = 0.0
            stages["compute"] = remote
            stages["child_other"] = 0.0
        ordered = [
            {"stage": name, "duration_s": stages.get(name, 0.0)}
            for name in STAGES
        ]
        return {
            "trace_id": trace_id,
            "total_s": total,
            "stages": ordered,
            "stage_sum_s": sum(s["duration_s"] for s in ordered),
            "processes": self.processes(trace_id),
            "spans": len(spans),
        }

    def export(self, limit: int = 32) -> List[dict]:
        """Most recent ``limit`` traces as flat span lists (report form)."""
        with self._lock:
            ids = list(self._traces.keys())[-limit:]
        return [{"trace_id": tid, "spans": self.trace(tid)} for tid in ids]


class MergeableHistogram:
    """Fixed-bucket latency histogram whose snapshots merge by addition.

    The registry's reservoir histograms are ideal in-process but two
    reservoirs with different strides have no sound union; fleet-level
    p50/p99 therefore aggregates these instead: log-spaced bucket counts
    (100 µs … ~100 s) that any process can snapshot, ship as a plain list,
    and the supervisor merges with elementwise adds.  Quantiles interpolate
    within the winning bucket — bounded error, zero coordination.
    """

    # 40 log-spaced bounds, 1e-4 s to ~100 s (ratio ~1.43 per step).
    BOUNDS = tuple(1e-4 * (10 ** (i / 6.45)) for i in range(40))

    __slots__ = ("counts", "count", "sum", "_lock")

    def __init__(self, counts: Optional[List[int]] = None,
                 count: int = 0, total: float = 0.0):
        self.counts = list(counts) if counts else [0] * (len(self.BOUNDS) + 1)
        self.count = int(count)
        self.sum = float(total)
        self._lock = threading.Lock()

    def _bucket(self, value: float) -> int:
        lo, hi = 0, len(self.BOUNDS)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.BOUNDS[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def observe(self, value: float) -> None:
        value = float(value)
        idx = self._bucket(value)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += value

    def snapshot(self) -> dict:
        with self._lock:
            return {"counts": list(self.counts), "count": self.count,
                    "sum": self.sum}

    def merge(self, snap: dict) -> None:
        counts = snap.get("counts") or []
        with self._lock:
            for i, c in enumerate(counts[: len(self.counts)]):
                self.counts[i] += int(c)
            self.count += int(snap.get("count", 0))
            self.sum += float(snap.get("sum", 0.0))

    @classmethod
    def merged(cls, snaps: List[dict]) -> "MergeableHistogram":
        h = cls()
        for s in snaps:
            h.merge(s)
        return h

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1], interpolated within the
        winning bucket (upper bound for the overflow bucket)."""
        with self._lock:
            counts, total = list(self.counts), self.count
        if total == 0:
            return None
        target = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= target and c > 0:
                lo = self.BOUNDS[i - 1] if i > 0 else 0.0
                hi = self.BOUNDS[i] if i < len(self.BOUNDS) else self.BOUNDS[-1]
                frac = (target - seen) / c if c else 0.0
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.BOUNDS[-1]

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None


class FlightRecorder:
    """Bounded ring of a replica's recent spans, events, and frame
    summaries — the crash postmortem's raw material.

    ``dump()`` persists the ring atomically (tmp + replace) so a reader
    never sees a torn file even if the writer dies mid-dump; the child
    flushes at traced-frame ingress *before* scoring, so a SIGKILL mid-
    batch still leaves the victim's last accepted work on disk.
    """

    def __init__(self, owner: str, capacity: int = 128):
        self.owner = owner
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(capacity))
        self.records_total = 0

    def record(self, kind: str, **fields) -> None:
        entry = {"t": time.time(), "kind": kind}
        entry.update(fields)
        with self._lock:
            self._ring.append(entry)
            self.records_total += 1

    def note_frame(self, direction: str, kind: str, nbytes: int,
                   seq: Optional[int] = None) -> None:
        self.record("frame", direction=direction, frame_kind=kind,
                    nbytes=int(nbytes), seq=seq)

    def note_span(self, span: SpanRecord, phase: str) -> None:
        self.record("span", phase=phase, span=span.to_dict())

    def snapshot(self) -> dict:
        with self._lock:
            records = list(self._ring)
        return {
            "owner": self.owner,
            "written_at": time.time(),
            "records_total": self.records_total,
            "records": records,
        }

    def dump(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, default=str)
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


# -- request attachment ------------------------------------------------------
# ScoringRequest is a frozen dataclass: the trace context rides as an extra
# attribute set via object.__setattr__ — invisible to equality/repr, absent
# unless tracing sampled this request, and dropped naturally when the
# request is re-sliced (concat_requests builds new objects).
_TRACE_ATTR = "_photon_trace"


def attach_trace(request, ctx: TraceContext) -> None:
    object.__setattr__(request, _TRACE_ATTR, ctx)


def trace_of(request) -> Optional[TraceContext]:
    return getattr(request, _TRACE_ATTR, None)


# The live SpanRecord rides the same way (parent-process only — the span
# object itself never crosses the wire, only its TraceContext does): the
# batcher reads it to stamp batch-close/score events onto the root span
# without the router having to thread span handles through the queue.
_SPAN_ATTR = "_photon_span"


def attach_span(request, span: SpanRecord) -> None:
    object.__setattr__(request, _SPAN_ATTR, span)


def span_of(request) -> Optional[SpanRecord]:
    return getattr(request, _SPAN_ATTR, None)


# -- thread-local active trace (the refresh→canary→swap linkage) -------------
_active = threading.local()


def current_trace() -> Optional[TraceContext]:
    return getattr(_active, "ctx", None)


@contextlib.contextmanager
def activate_trace(ctx: Optional[TraceContext]) -> Iterator[None]:
    """Make ``ctx`` the thread's ambient trace context: spans originated
    on this thread without an explicit parent (e.g. the rollout pipeline
    under an online refresh) join this trace instead of starting new
    ones."""
    prev = getattr(_active, "ctx", None)
    _active.ctx = ctx
    try:
        yield
    finally:
        _active.ctx = prev
