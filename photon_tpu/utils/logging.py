"""Logging + phase timing.

Rebuild of the reference's ``PhotonLogger`` (driver log file + console) and
``Timed`` blocks that record wall-clock per driver phase (SURVEY.md §5
'Tracing / profiling').  Adds an optional hook into ``jax.profiler`` traces
for device-level profiling, the TPU-era upgrade of the reference's
phase-timer logs.
"""

from __future__ import annotations

import contextlib
import logging
import os
import sys
import time
from typing import Iterator, Optional


class PhotonLogger:
    """Console + optional file logger with phase-timing helpers."""

    def __init__(self, name: str = "photon_tpu", log_file: Optional[str] = None,
                 level: int = logging.INFO):
        self._logger = logging.getLogger(name)
        self._logger.setLevel(level)
        self._logger.propagate = False
        if not self._logger.handlers:
            console = logging.StreamHandler(sys.stderr)
            console.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(message)s")
            )
            self._logger.addHandler(console)
        # logging.getLogger returns a process-wide singleton: each construction
        # is a new run, so file handlers are reset to exactly the requested
        # log file (keeping stale ones would append later runs to earlier
        # runs' logs; re-adding the same file would duplicate every line).
        target = os.path.abspath(log_file) if log_file else None
        for h in list(self._logger.handlers):
            if isinstance(h, logging.FileHandler) and h.baseFilename != target:
                self._logger.removeHandler(h)
                h.close()
        if target and not any(
            isinstance(h, logging.FileHandler) and h.baseFilename == target
            for h in self._logger.handlers
        ):
            os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
            fh = logging.FileHandler(log_file)
            fh.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(message)s")
            )
            self._logger.addHandler(fh)
        self.phase_times: dict[str, float] = {}
        # Set by TelemetrySession.attach(): when present, every timed()
        # phase also opens a tracing span, so phase logs and the run
        # report's span tree come from the one instrumentation point.
        self.tracer = None

    def info(self, msg: str, *args) -> None:
        self._logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self._logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self._logger.error(msg, *args)

    @contextlib.contextmanager
    def timed(self, phase: str, span: bool = True) -> Iterator[None]:
        """Log + record wall-clock of a driver phase (the reference's
        ``Timed { }``).  ``span=False`` keeps the log + phase_times entry
        but skips the tracing span — for unbounded-cardinality phases
        (one per part file in a beyond-host-memory stream) where retaining
        a Span each would grow the run report without bound."""
        t0 = time.monotonic()
        self.info("phase %s: start", phase)
        span_ctx = (
            self.tracer.span(phase) if span and self.tracer is not None
            else contextlib.nullcontext()
        )
        try:
            with span_ctx:
                yield
        finally:
            dt = time.monotonic() - t0
            self.phase_times[phase] = self.phase_times.get(phase, 0.0) + dt
            self.info("phase %s: done in %.3fs", phase, dt)


@contextlib.contextmanager
def Timed(phase: str, logger: Optional[PhotonLogger] = None) -> Iterator[None]:
    logger = logger or PhotonLogger()
    with logger.timed(phase):
        yield


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]) -> Iterator[None]:
    """Wrap a phase in a jax.profiler trace when a directory is given."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
