"""Utilities: logging, phase timing, profiling hooks.

Equivalent of the reference's ``util`` package (PhotonLogger, Timed —
SURVEY.md §2.1/§5).
"""

from photon_tpu.utils.logging import PhotonLogger, Timed  # noqa: F401


def pow2_at_least(n: int) -> int:
    """Smallest power of two >= n (>= 1) — the shape-bucketing rule shared by
    projection capacities, sharded-metric padding, and streamed-scoring
    chunks, so jitted programs compile O(log n) times across sizes."""
    return 1 << max(int(n) - 1, 0).bit_length()
