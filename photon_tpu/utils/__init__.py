"""Utilities: logging, phase timing, profiling hooks.

Equivalent of the reference's ``util`` package (PhotonLogger, Timed —
SURVEY.md §2.1/§5).
"""

from photon_tpu.utils.logging import PhotonLogger, Timed  # noqa: F401
