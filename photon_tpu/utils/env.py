"""Tiny env-var parsing helpers shared by the tuning knobs."""

from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: int, minimum: Optional[int] = None) -> int:
    """``int(os.environ[name])`` with explicit fallback rules: unset or
    unparsable returns ``default``; a parsed value below ``minimum`` (when
    given) also returns ``default`` — every knob states its clamp here
    instead of hand-rolling a subtly different one."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        return default
    if minimum is not None and val < minimum:
        return default
    return val
