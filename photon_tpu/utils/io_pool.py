"""Bounded, ordered host-IO thread pool.

The reference gets per-host decode parallelism from Spark executor threads
(each partition parsed on its own core — SURVEY.md §2.6); the analog here
is a small thread pool over FILES/CHUNKS whose native decode calls (ctypes
releases the GIL) run concurrently while results are consumed strictly in
submission order — so vocabularies built by first-seen interning stay
byte-identical to the sequential read.

``PHOTON_IO_THREADS`` sets the pool width (default: the host CPU count,
capped at 8; 1 disables pooling entirely).  The in-flight window is
bounded, so memory never scales with the number of files.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")

_worker_ctx = threading.local()


def in_pool_worker() -> bool:
    """True when the current thread is executing a :func:`map_ordered`
    call — lets nested pooled work (e.g. route colorings inside a
    streamed chunk attach) cap itself to one level instead of
    oversubscribing cores.  Precise by construction (an explicit
    thread-local set around each pooled call), unlike thread-name
    sniffing, which both over-matches foreign executors and misses
    renamed ones."""
    return bool(getattr(_worker_ctx, "active", False))


def io_threads() -> int:
    """Configured host-IO parallelism (>= 1); unset/invalid falls back to
    the host CPU count, capped at 8."""
    from photon_tpu.utils.env import env_int

    default = max(1, min(os.cpu_count() or 1, 8))
    return env_int("PHOTON_IO_THREADS", default, minimum=1)


_submit_pools: dict = {}
_submit_lock = threading.Lock()

# Named background pools: each distinct overlap workload gets its own
# small bounded executor, so e.g. the disk→host tile prefetch of a spilled
# streamed fit cannot starve the warm-start key-join prefetch (both are
# "one short job beside device compute" patterns, but with very different
# blocking profiles — key joins are CPU, tile prefetches are disk IO).
_POOL_WORKERS = {"default": 2, "tile-prefetch": 2}


def submit(fn: Callable[[], R], pool: str = "default"):
    """Fire one background call on a small shared io-pool executor and
    return its Future — the overlap primitive for host work that should run
    beside device compute (e.g. the foreign-vocabulary warm-start key join
    prefetched while the fixed-effect coordinate trains, or a spilled
    chunk's disk→host read warmed one stage ahead of its h2d upload).
    Pools are lazily created, bounded (2 threads each — these are
    occasional short jobs, not the bulk pipelines ``map_ordered`` serves),
    and process-lifetime; submitted work must be short and must not block
    indefinitely."""
    with _submit_lock:
        ex = _submit_pools.get(pool)
        if ex is None:
            ex = _submit_pools[pool] = ThreadPoolExecutor(
                max_workers=_POOL_WORKERS.get(pool, 2),
                thread_name_prefix=f"photon-io-submit-{pool}",
            )
        return ex.submit(fn)


def map_ordered(
    fn: Callable[[T], R],
    items: Sequence[T] | Iterable[T],
    workers: Optional[int] = None,
    window: Optional[int] = None,
    retry_site: Optional[str] = None,
    telemetry=None,
) -> Iterator[R]:
    """``map(fn, items)`` with up to ``workers`` concurrent calls, results
    yielded strictly in input order, and at most ``window`` calls in flight
    (default ``2 * workers``) so memory stays bounded.

    With ``retry_site`` set, each per-item call retries transient IO
    failures (OSError) with jittered exponential backoff on its worker
    thread (``photon_tpu.fault.retry``), counted as
    ``io.retries{site=retry_site}`` on ``telemetry`` — a flaky part file
    costs backoff, not the whole pooled read.

    With ``telemetry`` given, the pool's live shape lands in the run
    report: ``io_pool.workers`` (configured width), ``io_pool.in_flight``
    (submitted-but-unharvested calls, updated as the window slides) and
    ``io_pool.in_flight_peak`` (the high-water mark — how much of the
    window a read actually used).

    With ``workers <= 1`` (or a single item) this degrades to a plain lazy
    map — no threads, no queueing.  An exception from any call is re-raised
    at its in-order position.  Abandoning the iterator cancels calls that
    have not started; calls already RUNNING keep running on pool threads
    (their results are discarded) and, like any executor thread, are joined
    at interpreter exit — so ``fn`` should not block indefinitely.

    Concurrency/memory tradeoff is the caller's: up to ``window`` call
    RESULTS are resident at once (plus ``workers`` in-progress calls'
    transient memory) — map memory-heavy work through a reducer so the
    window holds summaries, not payloads.
    """
    items = list(items)
    if retry_site is not None:
        from photon_tpu.fault.retry import retry_call

        inner = fn

        def fn(item):
            return retry_call(
                lambda: inner(item), site=retry_site, telemetry=telemetry
            )

    if workers is None:
        workers = io_threads()
    if workers <= 1 or len(items) <= 1:
        for it in items:
            yield fn(it)
        return
    if window is None:
        window = 2 * workers
    window = max(window, 1)
    def run_marked(item: T) -> R:
        _worker_ctx.active = True
        try:
            return fn(item)
        finally:
            _worker_ctx.active = False

    if telemetry is not None:
        telemetry.gauge("io_pool.workers").set(workers)
    in_flight_peak = 0

    def _note_in_flight(n: int) -> None:
        nonlocal in_flight_peak
        if telemetry is None:
            return
        telemetry.gauge("io_pool.in_flight").set(n)
        if n > in_flight_peak:
            in_flight_peak = n
            telemetry.gauge("io_pool.in_flight_peak").set(n)

    ex = ThreadPoolExecutor(max_workers=workers)
    try:
        futs: deque = deque()
        idx = 0
        while futs or idx < len(items):
            while idx < len(items) and len(futs) < window:
                futs.append(ex.submit(run_marked, items[idx]))
                idx += 1
            _note_in_flight(len(futs))
            result = futs.popleft().result()
            _note_in_flight(len(futs))
            yield result
    finally:
        ex.shutdown(wait=False, cancel_futures=True)
