"""Shared disk-cache root resolution.

Three host-side caches share one precedence contract — the exchange
routes (``PHOTON_ROUTE_CACHE``), the streamed-chunk layouts
(``PHOTON_STREAM_LAYOUT_CACHE``), and the aligned layouts
(``PHOTON_LAYOUT_CACHE``): a specific env var overrides (value ``"0"``
disables), otherwise they live in subdirectories of the route-cache
root so one knob relocates or disables everything together.  One helper
so the contract cannot drift between hand-rolled copies.
"""

from __future__ import annotations

import functools
import os
from typing import Optional


@functools.lru_cache(maxsize=1)
def default_route_cache_root() -> str:
    """Resolve the default cache root ONCE per process: back-compat
    honors an existing CWD cache (pre-round-5 default, and how this
    host's pre-built production routes are stored); otherwise cache
    files stay out of the working directory (ADVICE r4) under the
    conventional user cache root.  Memoized so a mid-process chdir
    cannot flip the location and split a cache across two roots
    (the env overrides are still read per call by callers)."""
    legacy = os.path.abspath(".photon_route_cache")
    if os.path.isdir(legacy):
        return legacy
    return os.path.join(
        os.path.expanduser("~"), ".cache", "photon_tpu", "routes"
    )


def resolve_cache_dir(env_name: str, subdir: str) -> Optional[str]:
    """The directory a named cache should use, or None when disabled.

    ``env_name`` (when set in the environment) overrides; its value
    ``"0"`` disables.  Otherwise the cache follows ``PHOTON_ROUTE_CACHE``
    (same ``"0"`` semantics) into ``<route root>/<subdir>`` — with
    ``subdir == ""`` meaning the route root itself (how the route cache
    resolves its own root: an explicit override and the followed root
    coincide there).
    """
    root = os.environ.get(env_name)
    if root == "0":
        return None
    if root is not None:
        return root  # explicit override: use as-is
    base = os.environ.get("PHOTON_ROUTE_CACHE")
    if base == "0":
        return None
    if base is None:
        base = default_route_cache_root()
    return os.path.join(base, subdir) if subdir else base
