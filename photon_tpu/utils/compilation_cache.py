"""Shared persistent-XLA-compilation-cache setup.

One implementation behind both the drivers (every CLI run) and bench.py —
driver programs are identical run-to-run, so caching them cuts a repeat
GAME fit from ~14 s to ~3 s on a 1-core host (the analog of the reference
benefitting from a warmed JVM).
"""

from __future__ import annotations

import os


def enable(
    env_var: str,
    default_dir: str,
    min_compile_secs: float = 0.2,
    respect_existing: bool = True,
) -> None:
    """Point JAX's persistent compilation cache at ``$env_var`` (or
    ``default_dir``).  ``$env_var`` set to ``0``/``off``/``none``/
    ``disabled`` disables; with ``respect_existing`` a cache dir already
    configured (tests, an enclosing tool, the operator) wins.  Best-effort:
    never raises.
    """
    import jax

    spec = os.environ.get(env_var, "")
    if spec.lower() in ("0", "off", "none", "disabled"):
        return
    try:
        if respect_existing and jax.config.jax_compilation_cache_dir:
            return
        jax.config.update("jax_compilation_cache_dir", spec or default_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as ex:  # noqa: BLE001 — caching is best-effort, never fatal
        import logging

        logging.getLogger("photon_tpu.compilation_cache").warning(
            "persistent compilation cache disabled: %s", ex
        )
