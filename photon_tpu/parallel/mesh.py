"""Device mesh construction and batch sharding.

Replaces the reference's Spark partitioning layer (RDD partitions spread over
executors — SURVEY.md §2.6).  Axes used by the framework:

- ``"data"``  — batch/data parallelism for the fixed effect (≙ RDD partitions
  + treeAggregate).
- ``"entity"`` — per-entity sharding of random-effect solves (≙
  RandomEffectDatasetPartitioner's hash partitioning).  In practice both map
  onto the same physical chips; a 1-D mesh reused under two names keeps the
  code paths explicit.

Multi-host: mesh creation uses all addressable JAX devices; under
``jax.distributed`` the same code spans slices, with `pjit` emitting DCN
collectives across slice boundaries automatically.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_tpu.data.batch import Batch, SparseBatch, attach_feature_major, pad_batch

DATA_AXIS = "data"
ENTITY_AXIS = "entity"


def create_mesh(
    n_devices: Optional[int] = None, axis_name: str = DATA_AXIS, devices=None
) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (all by default)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, batch: Batch, axis_name: str = DATA_AXIS):
    """Shardings for a batch pytree: every leaf sharded on its leading
    (example) axis."""
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, P(axis_name, *([None] * (leaf.ndim - 1)))),
        batch,
    )


def shard_batch(
    batch: Batch,
    mesh: Mesh,
    axis_name: str = DATA_AXIS,
    build_fm: bool = True,
    aligned_dim: Optional[int] = None,
) -> Batch:
    """Pad the batch to a multiple of the mesh axis size (zero-weight rows)
    and place it sharded across the axis.

    The padding convention means padded rows are invisible to objectives and
    evaluators — the analog of the reference's uneven final RDD partition.

    For 2-D sparse batches this also attaches the per-shard feature-major
    layout (``build_fm``), so sharded objectives take the pre-sorted
    segment-sum gradient path; the aux's leading block axis is sharded like
    the rows, giving each device its block-local sorted view.  With
    ``aligned_dim`` (the coefficient dimension) the per-shard slab-aligned
    layouts — and, when the selector wants them, the per-shard xchg
    exchange routes — are built and stacked too, so the fast kernels run
    inside the sharded objective (VERDICT r5 item 2).  The extra host
    build is gated HERE on ops/sparse_grad_select.aligned_layout_wanted
    (mirroring the single-device attach sites), so callers can pass the
    dimension unconditionally and CPU-only runs never pay for layouts
    the selector cannot route to.
    """
    n_shards = mesh.shape[axis_name]
    n = batch.num_examples
    target = ((n + n_shards - 1) // n_shards) * n_shards
    padded = pad_batch(batch, target)
    if isinstance(padded, SparseBatch) and (
        padded.al is not None or padded.al_t is not None
    ):
        # Any pre-attached single-block aligned layouts cannot be
        # row-sharded; strip and (when aligned_dim says to) rebuild them
        # per shard below.
        padded = padded._replace(al=None, al_t=None, xchg=None, benes=None)
    if build_fm and isinstance(padded, SparseBatch) and padded.ids.ndim == 2:
        if aligned_dim is not None:
            from photon_tpu.ops.sparse_grad_select import aligned_layout_wanted

            if not aligned_layout_wanted(int(padded.ids.size)):
                aligned_dim = None
        padded = attach_feature_major(
            padded._replace(fm=None), shards=n_shards,
            aligned_dim=aligned_dim,
        )
    return jax.device_put(padded, batch_sharding(mesh, padded, axis_name))


def put_replicated(x, mesh: Optional[Mesh]):
    """Place a pytree of arrays fully replicated over ``mesh``.

    ``mesh=None`` (single device) just materializes the leaves as device
    arrays.  Used for state every shard reads whole (model coefficient
    vectors, small index buffers); bulk per-row state (score rows, scoring
    feature caches) is sharded with :func:`put_sharded` instead.
    """
    if mesh is None:
        return jax.tree.map(jnp.asarray, x)
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding), x)


def mesh_shards(mesh: Optional[Mesh]) -> int:
    """Number of shards along a mesh's axes (1 without a mesh)."""
    if mesh is None:
        return 1
    return int(np.prod(list(mesh.shape.values())))


def first_axis_name(mesh: Mesh) -> str:
    """The mesh's leading (in practice: only) axis name — the one physical
    axis the data-sharded score tables AND the entity-sharded random-effect
    solve bins both split over.  One accessor so the two layouts cannot
    silently pick different axes on a future multi-axis mesh."""
    return next(iter(mesh.shape))


def axis_sharding(
    mesh: Mesh, ndim: int, axis: int = 0, axis_name: str = DATA_AXIS
) -> NamedSharding:
    """Sharding that splits dimension ``axis`` of an ``ndim``-array over
    ``axis_name`` and replicates every other dimension."""
    spec = [None] * ndim
    spec[axis] = axis_name
    return NamedSharding(mesh, P(*spec))


def put_sharded(x, mesh: Optional[Mesh], axis: int = 0,
                axis_name: str = DATA_AXIS):
    """Place a pytree of arrays with dimension ``axis`` sharded over the
    mesh (``mesh=None`` just materializes device arrays).

    The residual/validation engines and the coordinate scoring caches use
    this for per-row state (score rows, feature shards, entity indices):
    each device holds only its row slice — one copy of the data across the
    mesh instead of one copy per device — and the per-coordinate offset /
    compensated-total kernels stay element-wise per shard, with GSPMD
    inserting the collectives (psum for metric reductions, gathers for
    cross-shard row selection) where an op genuinely crosses shards.
    The sharded dimension must already be padded to a multiple of the mesh
    size (:func:`pad_to_multiple`; padded rows carry weight 0).
    """
    if mesh is None:
        return jax.tree.map(jnp.asarray, x)
    return jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, axis_sharding(mesh, leaf.ndim, axis, axis_name)
        ),
        x,
    )


_RESHARD_CACHE: dict = {}


def reshard(x: jax.Array, sharding: NamedSharding) -> jax.Array:
    """Re-place a DEVICE array onto ``sharding`` through a jitted identity.

    ``jax.device_put`` on committed multi-process arrays cannot always move
    data across processes; a jitted identity with ``out_shardings`` lets
    XLA insert the collective instead, and is a no-op when the sharding
    already matches.  Jitted identities are cached per sharding so repeated
    calls (one per descent iteration) never retrace.
    """
    fn = _RESHARD_CACHE.get(sharding)
    if fn is None:
        fn = jax.jit(lambda y: y, out_shardings=sharding)
        _RESHARD_CACHE[sharding] = fn
    return fn(x)


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def reshard_to_mesh(x, mesh: Optional[Mesh], axis: int = 0,
                    axis_name: str = DATA_AXIS, pad_value=0):
    """Re-pad and re-shard one array onto the CURRENT mesh — the elastic-
    resume placement path.

    ``x`` holds a LOGICAL (unpadded) dimension along ``axis`` — score rows,
    labels, per-row entity indices — possibly written by a run on a
    different device/process count.  The dimension is padded with
    ``pad_value`` up to a multiple of THIS mesh's size (the padding
    convention everywhere: padded rows carry weight 0 / entity index -1,
    invisible to kernels and metrics) and the result is placed sharded over
    ``axis_name``.  Host numpy uploads directly; device arrays re-place
    through the jitted-identity :func:`reshard` (safe for committed
    multi-process arrays).  ``mesh=None`` just materializes a device array
    — so one code path serves every mesh shape, including none.

    This is deliberately the ONLY coupling between a checkpoint and the
    mesh that restores it: checkpoints record logical layouts, and every
    padded/sharded buffer is rebuilt HERE against whatever mesh the
    resuming run constructed (see photon_tpu.fault.checkpoint).
    """
    if mesh is None:
        return jnp.asarray(x)
    # Pad to the multiple of the WHOLE mesh (product of axes), not just the
    # sharded axis: the engines' preallocated tables and caches size n_pad
    # with mesh_shards(mesh), and the two must never disagree on a
    # multi-axis mesh (a product-multiple is always divisible by the
    # sharded axis's extent, so the placement below stays valid).
    n_shards = mesh_shards(mesh)
    length = x.shape[axis]
    short = pad_to_multiple(length, n_shards) - length
    sharding = axis_sharding(mesh, x.ndim, axis, axis_name)
    if isinstance(x, jax.Array):
        if short:
            widths = [(0, 0)] * x.ndim
            widths[axis] = (0, short)
            x = jnp.pad(x, widths, constant_values=pad_value)
        return reshard(x, sharding)
    host = np.asarray(x)
    if short:
        widths = [(0, 0)] * host.ndim
        widths[axis] = (0, short)
        host = np.pad(host, widths, constant_values=pad_value)
    return jax.device_put(host, sharding)


def put_request(x, mesh: Optional[Mesh]):
    """Place one serving request-batch buffer (a pytree of small host
    arrays) for the online scoring hot path.

    Request micro-batches are tiny next to the model gather tables, so they
    are REPLICATED over the mesh: every shard reads the whole batch and the
    per-row gather against the row-sharded tables resolves with one
    collective on the table side instead of re-sharding a few-hundred-row
    buffer every request.  Today that makes this exactly
    :func:`put_replicated`; the alias exists so the serving request layout
    is decided in ONE place — the pre-compiled bucket programs
    (photon_tpu.serving.scorer) are lowered against buffers placed here,
    and every later request must hit the exact compiled layout or it would
    force a recompile.
    """
    return put_replicated(x, mesh)


def abstract_like(x):
    """``jax.ShapeDtypeStruct`` pytree mirroring ``x``'s shapes, dtypes,
    and shardings — AOT-lowering inputs (``jax.jit(f).lower(...)``) without
    keeping sample buffers alive.  The serving scorer lowers each bucket
    program against abstract request buffers shaped by this, then compiles
    once; committed-array leaves carry their sharding into the lowering so
    the compiled program pins the exact runtime placement."""
    return jax.tree.map(
        lambda leaf: jax.ShapeDtypeStruct(
            leaf.shape,
            leaf.dtype,
            sharding=leaf.sharding if isinstance(leaf, jax.Array) else None,
        ),
        x,
    )


def to_host(x) -> np.ndarray:
    """``np.asarray`` that also works for multi-process sharded arrays.

    In a multi-process job a globally-sharded ``jax.Array`` spans devices
    this process cannot address; fetching it raises.  Gather the shards
    across processes first (every host gets the full array — host fetches
    in this framework are small: solver stats, model tables, score
    vectors).  Single-process arrays pass straight through.
    """
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        x = multihost_utils.process_allgather(x, tiled=True)
    return np.asarray(x)
