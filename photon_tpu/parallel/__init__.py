"""Parallelism layer: device mesh, sharded batches, distributed objectives.

The rebuild of the reference's distribution runtime (Spark treeAggregate /
broadcast / shuffle — SURVEY.md §2.6): per-shard gradients combined with
``lax.psum`` over ICI under ``shard_map``, parameters replicated in device
memory (no per-iteration broadcast), and entity-grouping done once host-side
into static shardings instead of a shuffle.
"""

from photon_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    create_mesh,
    replicated_sharding,
    shard_batch,
)
from photon_tpu.parallel.distributed import DistributedGlmObjective  # noqa: F401
