"""Distributed GLM objective: per-shard evaluation + explicit ICI collectives.

The TPU-native rebuild of the reference's ``DistributedGLMLossFunction``
(photon-api .../function/glm — SURVEY.md §3.4): where the reference broadcasts
coefficients, folds each RDD partition through a ``ValueAndGradientAggregator``
and tree-reduces (gradient, value) pairs to the driver once per optimizer
iteration, this evaluates the local shard's value/gradient under ``shard_map``
and combines with ``lax.psum`` over the mesh's data axis — one fused XLA
program per optimizer *run* (not per iteration), no host round-trips, with the
coefficient vector resident and replicated in device memory.

The optimizer is oblivious: it receives a ``fun(w) -> (value, grad)`` whose
collectives are internal, so the same L-BFGS/OWL-QN/TRON code drives
single-chip and pod-scale training (the reference's Optimizer/ObjectiveFunction
split, kept).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from photon_tpu.core.objective import GlmObjective
from photon_tpu.data.batch import Batch
from photon_tpu.parallel.mesh import DATA_AXIS

Array = jax.Array


class DistributedGlmObjective:
    """Binds a :class:`GlmObjective` to a mesh data axis.

    Methods mirror the single-node objective so optimization problems can be
    built against either (SURVEY.md §2.2 Distributed/SingleNode split).
    """

    def __init__(self, obj: GlmObjective, mesh: Mesh, axis_name: str = DATA_AXIS):
        self.obj = obj
        self.mesh = mesh
        self.axis_name = axis_name

    # -- spec helpers ---------------------------------------------------------
    def _batch_specs(self, batch: Batch):
        return jax.tree.map(
            lambda leaf: P(self.axis_name, *([None] * (leaf.ndim - 1))), batch
        )

    # -- distributed evaluations ---------------------------------------------
    def value_and_grad(self, w: Array, batch: Batch) -> tuple[Array, Array]:
        ax = self.axis_name

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=(P(), P()),
            check_rep=False,
        )
        def _vg(w, local):
            # L2 must be added once globally, not once per shard.
            v, g = jax.value_and_grad(self.obj.data_value)(w, local)
            v = lax.psum(v, ax)
            g = lax.psum(g, ax)
            if self.obj.l2_weight:
                v = v + 0.5 * self.obj.l2_weight * jnp.dot(w, w)
                g = g + self.obj.l2_weight * w
            return v, g

        return _vg(w, batch)

    def value(self, w: Array, batch: Batch) -> Array:
        return self.value_and_grad(w, batch)[0]

    def hessian_vector(self, w: Array, v: Array, batch: Batch) -> Array:
        ax = self.axis_name

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), P(), self._batch_specs(batch)),
            out_specs=P(),
            check_rep=False,
        )
        def _hv(w, v, local):
            hv = jax.jvp(
                lambda u: jax.grad(self.obj.data_value)(u, local), (w,), (v,)
            )[1]
            hv = lax.psum(hv, ax)
            return hv + self.obj.l2_weight * v

        return _hv(w, v, batch)

    def hessian_diagonal(self, w: Array, batch: Batch) -> Array:
        ax = self.axis_name
        l2 = self.obj.l2_weight

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
            check_rep=False,
        )
        def _hd(w, local):
            # Strip the l2 added per shard by the local method; re-add once.
            local_diag = self.obj.hessian_diagonal(w, local) - l2
            return lax.psum(local_diag, ax) + l2

        return _hd(w, batch)

    # -- optimizer binding ----------------------------------------------------
    def bind(self, batch: Batch) -> Callable[[Array], tuple[Array, Array]]:
        return lambda w: self.value_and_grad(w, batch)

    def bind_hvp(self, batch: Batch) -> Callable[[Array, Array], Array]:
        return lambda w, v: self.hessian_vector(w, v, batch)
