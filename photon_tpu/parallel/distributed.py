"""Distributed GLM objective: per-shard evaluation + explicit ICI collectives.

The TPU-native rebuild of the reference's ``DistributedGLMLossFunction``
(photon-api .../function/glm — SURVEY.md §3.4): where the reference broadcasts
coefficients, folds each RDD partition through a ``ValueAndGradientAggregator``
and tree-reduces (gradient, value) pairs to the driver once per optimizer
iteration, here the *loss value* is a ``shard_map`` program — local weighted
loss per shard, ``lax.psum`` over the mesh's data axis — and derivatives come
from differentiating straight through it (``jax.value_and_grad`` /
``jax.jvp``), which transposes the psum correctly under JAX's varying-axes
semantics.  One fused XLA program per optimizer *run*, no host round-trips,
coefficients resident and replicated in device memory.

The optimizer is oblivious: it receives a ``fun(w) -> (value, grad)`` whose
collectives are internal, so the same L-BFGS/OWL-QN/TRON code drives
single-chip and pod-scale training (the reference's Optimizer/ObjectiveFunction
split, kept).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6: top-level export, `check_vma` kwarg
    from jax import shard_map
except ImportError:  # jax 0.4.x: experimental home, kwarg named `check_rep`
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_experimental(f, **kw)

from photon_tpu.core.objective import GlmObjective, _static_zero
from photon_tpu.data.batch import Batch
from photon_tpu.parallel.mesh import DATA_AXIS

Array = jax.Array

# One-shot flag for the multi-process auto-pin notice (_sparse_kernel is
# on the per-step hot path).
_MP_AUTO_PIN_LOGGED = False


def _aux_is_stacked(v) -> bool:
    """True when a batch aux carries a leading shard axis: the 2-D index
    planes (aligned ``lo``, route stage planes) read rank 3."""
    from photon_tpu.ops.pallas_gather import AlignedLayoutDev

    if isinstance(v, AlignedLayoutDev):
        return v.lo.ndim == 3
    route = getattr(v, "route", None)
    if route is not None:
        plane = getattr(route, "a1", None)
        if plane is None:
            plane = route.i1
        return plane.ndim == 3
    return False


class DistributedGlmObjective:
    """Binds a :class:`GlmObjective` to a mesh data axis.

    Methods mirror the single-node objective so optimization problems can be
    built against either (SURVEY.md §2.2 Distributed/SingleNode split).
    """

    def __init__(self, obj: GlmObjective, mesh: Mesh, axis_name: str = DATA_AXIS):
        self.obj = obj
        self.mesh = mesh
        self.axis_name = axis_name

    @property
    def l1_weight(self):
        """Mirrors GlmObjective so optimization problems treat both alike."""
        return self.obj.l1_weight

    # -- spec helpers ---------------------------------------------------------
    def _batch_specs(self, batch: Batch):
        return jax.tree.map(
            lambda leaf: P(self.axis_name, *([None] * (leaf.ndim - 1))), batch
        )

    def _squeeze_local_aux(self, local: Batch) -> Batch:
        """Inside shard_map: drop the leading shard axis from STACKED
        aligned/xchg aux so each device hands its block's layout to the
        kernels in their single-block form.  Stacked-ness is a SHAPE
        property (index-plane rank 3 instead of 2) — not a mesh-size
        inference: a 1-device-per-process multi-host assembly is stacked
        at axis length 1, while a 1-device local mesh with a
        single-block attach is not.  The fm aux keeps its
        (always-present) block axis — _fm_segment_grad consumes it
        directly."""
        for aux in ("al", "al_t", "xchg"):
            v = getattr(local, aux, None)
            if v is not None and _aux_is_stacked(v):
                local = local._replace(
                    **{aux: jax.tree.map(lambda x: x[0], v)}
                )
        return local

    def _sparse_kernel(self, w: Array, batch: Batch):
        """The measured kernel choice for this batch/backend — any of the
        static-layout kernels now runs per shard (VERDICT r5 item 2).

        MULTI-PROCESS auto mode pins to the generic autodiff path: the
        selection is a per-host wall-clock measurement, and hosts
        measuring different winners would build different shard_map
        programs — mismatched collective sequences hang the job rather
        than falling back.  This mirrors the drivers' determinism pin
        (README determinism note); pin ``PHOTON_SPARSE_GRAD`` explicitly
        to run a fast kernel on a multi-process mesh — a forced choice
        is identical on every host by construction."""
        import os

        if (
            os.environ.get("PHOTON_SPARSE_GRAD", "auto") == "auto"
            and jax.process_count() > 1
        ):
            global _MP_AUTO_PIN_LOGGED
            if not _MP_AUTO_PIN_LOGGED:
                _MP_AUTO_PIN_LOGGED = True
                import logging

                logging.getLogger("photon_tpu.distributed").info(
                    "multi-process auto mode pins the sharded objective "
                    "to autodiff (per-host probes could disagree); set "
                    "PHOTON_SPARSE_GRAD=fm|pallas|xchg to run a fast "
                    "kernel"
                )
            return None
        return self.obj._sparse_kernel(batch, int(w.shape[0]))

    # -- distributed value (the one shard_map program) ------------------------
    def value(self, w: Array, batch: Batch) -> Array:
        """Global objective: psum of per-shard weighted losses + L2 once."""
        ax = self.axis_name

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
        )
        def _v(w, local):
            return lax.psum(self.obj.data_value(w, local), ax)

        v = _v(w, batch)
        if not _static_zero(self.obj.l2_weight):
            v = v + 0.5 * self.obj.l2_weight * jnp.dot(w, w)
        return v

    # -- derivatives: differentiate through the psum --------------------------
    def value_and_grad(self, w: Array, batch: Batch) -> tuple[Array, Array]:
        kernel = self._sparse_kernel(w, batch)
        if kernel is not None:
            # Static-sparsity fast path: per-shard explicit value+gradient
            # over the shard's block-local static layout (fm segment-sum,
            # pallas aligned reduce, or the xchg exchange — whichever the
            # measured selection picked), psum-ed — the direct analog of
            # treeAggregate(ValueAndGradientAggregator) with the
            # per-evaluation sort deleted (see FeatureMajorAux).
            ax = self.axis_name

            @partial(
                shard_map,
                mesh=self.mesh,
                in_specs=(P(), self._batch_specs(batch)),
                out_specs=(P(), P()),
                check_vma=False,  # outputs are psum-replicated by
                # construction; pallas_call cannot annotate vma
            )
            def _vg(w, local):
                local2 = self._squeeze_local_aux(local)
                v, g = self.obj._fast_data_value_and_grad(w, local2, kernel)
                return lax.psum(v, ax), lax.psum(g, ax)

            v, g = _vg(w, batch)
            l2 = self.obj.l2_weight
            if not _static_zero(l2):
                v = v + 0.5 * l2 * jnp.dot(w, w)
                g = g + l2 * w
            return v, g
        return jax.value_and_grad(self.value)(w, batch)

    def grad(self, w: Array, batch: Batch) -> Array:
        if self._sparse_kernel(w, batch) is not None:
            return self.value_and_grad(w, batch)[1]
        return jax.grad(self.value)(w, batch)

    def hessian_vector(self, w: Array, v: Array, batch: Batch) -> Array:
        kernel = (
            self._sparse_kernel(w, batch)
            if self.obj.normalization is None else None
        )
        if kernel is not None:
            ax = self.axis_name

            @partial(
                shard_map,
                mesh=self.mesh,
                in_specs=(P(), P(), self._batch_specs(batch)),
                out_specs=P(),
                check_vma=False,  # as in _vg: psum-replicated outputs
            )
            def _hv(w, v, local):
                local2 = self._squeeze_local_aux(local)
                return lax.psum(
                    self.obj._fast_data_hessian_vector(w, v, local2, kernel),
                    ax,
                )

            hv = _hv(w, v, batch)
            l2 = self.obj.l2_weight
            if not _static_zero(l2):
                hv = hv + l2 * v
            return hv
        return jax.jvp(
            lambda u: self._differentiable_grad(u, batch), (w,), (v,)
        )[1]

    def _differentiable_grad(self, w: Array, batch: Batch) -> Array:
        """Gradient via a kernel jvp can differentiate THROUGH (the
        normalized-Hv path re-differentiates the gradient, and
        ``pallas_call`` has no JVP rule): pallas/xchg route to the fm
        layout — always built alongside the aligned one — mirroring
        GlmObjective._differentiable_grad."""
        kernel = self._sparse_kernel(w, batch)
        if kernel in ("pallas", "xchg", "benes"):
            kernel = "fm" if batch.fm is not None else None
        if kernel is None:
            return jax.grad(self.value)(w, batch)
        ax = self.axis_name

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
            check_vma=False,  # as in _vg: psum-replicated outputs
        )
        def _g(w, local):
            local2 = self._squeeze_local_aux(local)
            _, g = self.obj._fast_data_value_and_grad(w, local2, kernel)
            return lax.psum(g, ax)

        g = _g(w, batch)
        l2 = self.obj.l2_weight
        if not _static_zero(l2):
            g = g + l2 * w
        return g

    def hessian_diagonal(self, w: Array, batch: Batch) -> Array:
        ax = self.axis_name
        l2 = self.obj.l2_weight

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
        )
        def _hd(w, local):
            # Strip the l2 added per shard by the local method; re-add once.
            return lax.psum(self.obj.hessian_diagonal(w, local) - l2, ax)

        return _hd(w, batch) + l2

    def hessian_matrix(self, w: Array, batch: Batch) -> Array:
        """Full Hessian: psum of per-shard ``Xᵀ D X`` blocks + l2·I once
        (the treeAggregate of HessianMatrixAggregator — SURVEY.md §2.2)."""
        ax = self.axis_name
        l2 = self.obj.l2_weight
        d = w.shape[0]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
        )
        def _hm(w, local):
            local_h = self.obj.hessian_matrix(w, local) - l2 * jnp.eye(
                d, dtype=w.dtype
            )
            return lax.psum(local_h, ax)

        return _hm(w, batch) + l2 * jnp.eye(d, dtype=w.dtype)

    # -- optimizer binding ----------------------------------------------------
    def bind(self, batch: Batch) -> Callable[[Array], tuple[Array, Array]]:
        return lambda w: self.value_and_grad(w, batch)

    def bind_hvp(self, batch: Batch) -> Callable[[Array, Array], Array]:
        return lambda w, v: self.hessian_vector(w, v, batch)


# A pytree like GlmObjective (the wrapped objective's reg weights stay
# dynamic); the mesh and axis name are static structure, so solvers cached by
# core/problem.py retrace only when the mesh itself changes.
jax.tree_util.register_pytree_node(
    DistributedGlmObjective,
    lambda o: ((o.obj,), (o.mesh, o.axis_name)),
    lambda aux, children: DistributedGlmObjective(children[0], aux[0], aux[1]),
)


class RowSplitGlmObjective:
    """Per-entity objective whose ROWS are split across a mesh axis.

    The missing leg of the reference's entity-grouping shuffle: when one
    entity's rows span hosts, the reference physically moves rows so each
    entity is co-located.  Here nothing moves — every shard evaluates the
    data terms on its LOCAL rows of every entity and ``lax.psum``s, so the
    (vmapped, replicated) optimizer sees exact global per-entity values.
    The shuffle becomes a collective (README §scale-out data strategy).

    Use INSIDE ``shard_map`` over ``axis_name`` (see
    :func:`solve_entities_row_split`).  Regularization is added once
    globally — data terms psum, l2/l1 do not.
    """

    def __init__(self, obj: GlmObjective, axis_name: str = DATA_AXIS):
        self.obj = obj
        self.axis_name = axis_name

    @property
    def l1_weight(self):
        return self.obj.l1_weight

    def value_and_grad(self, w: Array, batch: Batch) -> tuple[Array, Array]:
        v, g = jax.value_and_grad(self.obj.data_value)(w, batch)
        v = lax.psum(v, self.axis_name)
        g = lax.psum(g, self.axis_name)
        l2 = self.obj.l2_weight
        if not _static_zero(l2):
            v = v + 0.5 * l2 * jnp.dot(w, w)
            g = g + l2 * w
        return v, g

    def value(self, w: Array, batch: Batch) -> Array:
        v = lax.psum(self.obj.data_value(w, batch), self.axis_name)
        if not _static_zero(self.obj.l2_weight):
            v = v + 0.5 * self.obj.l2_weight * jnp.dot(w, w)
        return v

    def grad(self, w: Array, batch: Batch) -> Array:
        return self.value_and_grad(w, batch)[1]

    def hessian_vector(self, w: Array, v: Array, batch: Batch) -> Array:
        hv = jax.jvp(
            lambda u: jax.grad(self.obj.data_value)(u, batch), (w,), (v,)
        )[1]
        hv = lax.psum(hv, self.axis_name)
        if not _static_zero(self.obj.l2_weight):
            hv = hv + self.obj.l2_weight * v
        return hv

    def hessian_diagonal(self, w: Array, batch: Batch) -> Array:
        l2 = self.obj.l2_weight
        local = self.obj.hessian_diagonal(w, batch) - l2
        return lax.psum(local, self.axis_name) + l2

    def hessian_matrix(self, w: Array, batch: Batch) -> Array:
        d = w.shape[0]
        l2 = self.obj.l2_weight
        local = self.obj.hessian_matrix(w, batch) - l2 * jnp.eye(d, dtype=w.dtype)
        return lax.psum(local, self.axis_name) + l2 * jnp.eye(d, dtype=w.dtype)


jax.tree_util.register_pytree_node(
    RowSplitGlmObjective,
    lambda o: ((o.obj,), (o.axis_name,)),
    lambda aux, children: RowSplitGlmObjective(children[0], aux[0]),
)


def solve_entities_row_split(
    objective: GlmObjective,
    config,
    batches: Batch,
    w0s: Array,
    mesh: Mesh,
    axis_name: str = DATA_AXIS,
):
    """Solve every entity's GLM with its rows SHARDED across ``axis_name``.

    ``batches`` leaves are ``[E, R, ...]`` (entity-major, per-entity padded
    rows — zero-weight padding as usual) with ``R`` divisible by the axis
    size; ``w0s`` is ``[E, dim]`` replicated.  Each shard holds the
    ``R/num_shards`` row slice of EVERY entity; the vmapped optimizer runs
    replicated on all shards, driven by psum-exact global gradients
    (:class:`RowSplitGlmObjective`).  Returns (Coefficients, OptimizerResult)
    pytrees with leading entity axes, replicated across the mesh.

    This is the rows-exceed-host-memory leg of the random-effect story: on a
    multi-process mesh each process contributes only the rows IT read, and
    no row ever crosses a host — the reference's shuffle traffic becomes one
    psum per objective evaluation over ICI/DCN.
    """
    n_shards = mesh.shape[axis_name]
    r = jax.tree.leaves(batches)[0].shape[1]
    if r % n_shards:
        raise ValueError(
            f"per-entity row capacity ({r}) must be divisible by the mesh "
            f"axis size ({n_shards}); pad entity rows first"
        )
    if getattr(batches, "fm", None) is not None:
        batches = batches._replace(fm=None)  # row-major path under vmap

    program = _row_split_program(
        mesh, axis_name, config.optimizer.lower(), config.optimizer_config,
        config.variance_computation,
        jax.tree.structure(batches),
        tuple(leaf.ndim for leaf in jax.tree.leaves(batches)),
    )
    return program(RowSplitGlmObjective(objective, axis_name), batches, w0s)


@functools.lru_cache(maxsize=32)
def _row_split_program(mesh, axis_name, optimizer, opt_cfg, variance,
                       batch_treedef, batch_ranks):
    """One shard_map'd solve program per (mesh, static config, batch
    structure): the per-bucket/per-descent-iteration calls in
    RandomEffectCoordinate.train hit jax's trace cache instead of retracing
    the whole vmapped optimizer every call (same discipline as
    core/problem.cached_solver; the objective rides along as a replicated
    pytree argument)."""
    from photon_tpu.core.problem import cached_solver

    solver = cached_solver(optimizer, opt_cfg, variance, vmapped=True)
    batch_specs = jax.tree.unflatten(
        batch_treedef,
        [P(None, axis_name, *([None] * (r - 2))) for r in batch_ranks],
    )
    return shard_map(
        lambda split_obj, local, w0s: solver(split_obj, local, w0s),
        mesh=mesh,
        in_specs=(P(), batch_specs, P()),
        out_specs=P(),
        check_vma=False,  # optimizer state is replicated by construction:
        # every shard runs the identical update from psum-ed gradients
    )
