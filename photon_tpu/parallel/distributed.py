"""Distributed GLM objective: per-shard evaluation + explicit ICI collectives.

The TPU-native rebuild of the reference's ``DistributedGLMLossFunction``
(photon-api .../function/glm — SURVEY.md §3.4): where the reference broadcasts
coefficients, folds each RDD partition through a ``ValueAndGradientAggregator``
and tree-reduces (gradient, value) pairs to the driver once per optimizer
iteration, here the *loss value* is a ``shard_map`` program — local weighted
loss per shard, ``lax.psum`` over the mesh's data axis — and derivatives come
from differentiating straight through it (``jax.value_and_grad`` /
``jax.jvp``), which transposes the psum correctly under JAX's varying-axes
semantics.  One fused XLA program per optimizer *run*, no host round-trips,
coefficients resident and replicated in device memory.

The optimizer is oblivious: it receives a ``fun(w) -> (value, grad)`` whose
collectives are internal, so the same L-BFGS/OWL-QN/TRON code drives
single-chip and pod-scale training (the reference's Optimizer/ObjectiveFunction
split, kept).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from photon_tpu.core.objective import GlmObjective, _static_zero
from photon_tpu.data.batch import Batch
from photon_tpu.parallel.mesh import DATA_AXIS

Array = jax.Array


class DistributedGlmObjective:
    """Binds a :class:`GlmObjective` to a mesh data axis.

    Methods mirror the single-node objective so optimization problems can be
    built against either (SURVEY.md §2.2 Distributed/SingleNode split).
    """

    def __init__(self, obj: GlmObjective, mesh: Mesh, axis_name: str = DATA_AXIS):
        self.obj = obj
        self.mesh = mesh
        self.axis_name = axis_name

    @property
    def l1_weight(self):
        """Mirrors GlmObjective so optimization problems treat both alike."""
        return self.obj.l1_weight

    # -- spec helpers ---------------------------------------------------------
    def _batch_specs(self, batch: Batch):
        return jax.tree.map(
            lambda leaf: P(self.axis_name, *([None] * (leaf.ndim - 1))), batch
        )

    # -- distributed value (the one shard_map program) ------------------------
    def value(self, w: Array, batch: Batch) -> Array:
        """Global objective: psum of per-shard weighted losses + L2 once."""
        ax = self.axis_name

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
        )
        def _v(w, local):
            return lax.psum(self.obj.data_value(w, local), ax)

        v = _v(w, batch)
        if not _static_zero(self.obj.l2_weight):
            v = v + 0.5 * self.obj.l2_weight * jnp.dot(w, w)
        return v

    # -- derivatives: differentiate through the psum --------------------------
    def value_and_grad(self, w: Array, batch: Batch) -> tuple[Array, Array]:
        if self.obj._fm_ready(batch, int(w.shape[0])):
            # Static-sparsity fast path: per-shard explicit value+gradient
            # over the shard's block-local feature-major layout, psum-ed —
            # the direct analog of treeAggregate(ValueAndGradientAggregator)
            # with the per-evaluation sort deleted (see FeatureMajorAux).
            ax = self.axis_name

            @partial(
                shard_map,
                mesh=self.mesh,
                in_specs=(P(), self._batch_specs(batch)),
                out_specs=(P(), P()),
            )
            def _vg(w, local):
                v, g = self.obj._fast_data_value_and_grad(w, local)
                return lax.psum(v, ax), lax.psum(g, ax)

            v, g = _vg(w, batch)
            l2 = self.obj.l2_weight
            if not _static_zero(l2):
                v = v + 0.5 * l2 * jnp.dot(w, w)
                g = g + l2 * w
            return v, g
        return jax.value_and_grad(self.value)(w, batch)

    def grad(self, w: Array, batch: Batch) -> Array:
        if self.obj._fm_ready(batch, int(w.shape[0])):
            return self.value_and_grad(w, batch)[1]
        return jax.grad(self.value)(w, batch)

    def hessian_vector(self, w: Array, v: Array, batch: Batch) -> Array:
        if self.obj.normalization is None and self.obj._fm_ready(batch, int(w.shape[0])):
            ax = self.axis_name

            @partial(
                shard_map,
                mesh=self.mesh,
                in_specs=(P(), P(), self._batch_specs(batch)),
                out_specs=P(),
            )
            def _hv(w, v, local):
                return lax.psum(self.obj._fast_data_hessian_vector(w, v, local), ax)

            hv = _hv(w, v, batch)
            l2 = self.obj.l2_weight
            if not _static_zero(l2):
                hv = hv + l2 * v
            return hv
        return jax.jvp(lambda u: self.grad(u, batch), (w,), (v,))[1]

    def hessian_diagonal(self, w: Array, batch: Batch) -> Array:
        ax = self.axis_name
        l2 = self.obj.l2_weight

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
        )
        def _hd(w, local):
            # Strip the l2 added per shard by the local method; re-add once.
            return lax.psum(self.obj.hessian_diagonal(w, local) - l2, ax)

        return _hd(w, batch) + l2

    def hessian_matrix(self, w: Array, batch: Batch) -> Array:
        """Full Hessian: psum of per-shard ``Xᵀ D X`` blocks + l2·I once
        (the treeAggregate of HessianMatrixAggregator — SURVEY.md §2.2)."""
        ax = self.axis_name
        l2 = self.obj.l2_weight
        d = w.shape[0]

        @partial(
            shard_map,
            mesh=self.mesh,
            in_specs=(P(), self._batch_specs(batch)),
            out_specs=P(),
        )
        def _hm(w, local):
            local_h = self.obj.hessian_matrix(w, local) - l2 * jnp.eye(
                d, dtype=w.dtype
            )
            return lax.psum(local_h, ax)

        return _hm(w, batch) + l2 * jnp.eye(d, dtype=w.dtype)

    # -- optimizer binding ----------------------------------------------------
    def bind(self, batch: Batch) -> Callable[[Array], tuple[Array, Array]]:
        return lambda w: self.value_and_grad(w, batch)

    def bind_hvp(self, batch: Batch) -> Callable[[Array, Array], Array]:
        return lambda w, v: self.hessian_vector(w, v, batch)


# A pytree like GlmObjective (the wrapped objective's reg weights stay
# dynamic); the mesh and axis name are static structure, so solvers cached by
# core/problem.py retrace only when the mesh itself changes.
jax.tree_util.register_pytree_node(
    DistributedGlmObjective,
    lambda o: ((o.obj,), (o.mesh, o.axis_name)),
    lambda aux, children: DistributedGlmObjective(children[0], aux[0], aux[1]),
)
