"""Socket transport for the serving fleet: length-prefixed binary frames.

Stdlib-only (``socket`` + ``socketserver`` — no new deps): the real ingest
the ROADMAP's fleet tier calls for, in front of the same
``RequestBatcher``/router stack the in-process loop uses.  One TCP
connection carries a sequence of request/response exchanges:

    frame    := u32_be payload_len | payload
    payload  := u32_be header_len | header_json_utf8 | array_bytes...

The JSON header describes the frame kind and its array manifest — each
entry ``{"slot", "name", "dtype", "shape"}`` names one contiguous
little-endian buffer concatenated (in manifest order) after the header.
Request slots: ``feat`` (dense features per shard), ``ids``/``vals``
(padded-COO sparse pair per shard), ``col`` (raw entity keys per id
column — numpy fixed-width strings ride as their ``<U*`` buffers),
``offset``.  ``deadline_ms`` in the header is a RELATIVE budget: the
server stamps the absolute deadline at ingest, so client/server clocks
never need to agree.  Response kinds: ``scores`` (one float32 array),
``shed`` (admission fast-fail, with the reason), ``error``.

Fault surface: every frame read declares the ``transport:read`` fault
site (an injected transient read error behaves like a flaky network).
Scoring requests are idempotent, so :class:`ScoringClient` retries the
whole exchange through ``retry_call`` — reconnect + resend — and a
recovered fault is counted as ``io.retries{site=transport:read}``.

Residency contract (``tools/check_host_sync.py`` guards this module): the
transport is pure host IO — it must never touch device data; the
coercions below operate on wire bytes and caller-owned numpy only.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_tpu.fault.injection import fault_point
from photon_tpu.serving.router import RequestShedError
from photon_tpu.serving.scorer import ScoringRequest
from photon_tpu.telemetry.distributed import (
    TraceContext,
    attach_trace,
    trace_of,
)

MAX_FRAME_BYTES = 1 << 28  # 256 MB: far past any sane micro-batch


class TransportError(RuntimeError):
    """A malformed frame or a remote-side serving failure."""


# -- frame IO ----------------------------------------------------------------

def _read_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes (the fault-injectable transport read edge).
    A peer close mid-frame is a ConnectionError — an OSError, so the
    client's retry layer treats it like any transient network fault."""
    fault_point("transport:read")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf += chunk
    return bytes(buf)


def read_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("!I", _read_exact(sock, 4))
    if n > MAX_FRAME_BYTES:
        raise TransportError(f"frame of {n} bytes exceeds the "
                             f"{MAX_FRAME_BYTES}-byte cap")
    return _read_exact(sock, n)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("!I", len(payload)) + payload)


# -- payload encode/decode ---------------------------------------------------

def _pack(header: dict) -> bytes:
    manifest = []
    bufs = []
    for slot, name, arr in header.pop("_arrays"):
        a = np.ascontiguousarray(arr)
        manifest.append({
            "slot": slot, "name": name,
            "dtype": a.dtype.str, "shape": list(a.shape),
        })
        bufs.append(a.tobytes())
    header["arrays"] = manifest
    head = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return b"".join([struct.pack("!I", len(head)), head, *bufs])


def _unpack(payload: bytes) -> Tuple[dict, List[np.ndarray]]:
    (hlen,) = struct.unpack("!I", payload[:4])
    header = json.loads(payload[4: 4 + hlen].decode("utf-8"))
    pos = 4 + hlen
    arrays = []
    for entry in header.get("arrays", []):
        dtype = np.dtype(entry["dtype"])
        count = int(np.prod(entry["shape"], dtype=np.int64))
        nbytes = count * dtype.itemsize
        if pos + nbytes > len(payload):
            raise TransportError("truncated frame: array bytes short")
        arrays.append(
            np.frombuffer(payload[pos: pos + nbytes], dtype=dtype)
            .reshape(entry["shape"])
        )
        pos += nbytes
    if pos != len(payload):
        raise TransportError("trailing bytes after the array manifest")
    return header, arrays


def payload_kind(payload: bytes) -> str:
    """The frame kind without decoding the array bytes (header-only
    parse) — the subprocess replica's per-connection dispatch peek."""
    (hlen,) = struct.unpack("!I", payload[:4])
    header = json.loads(payload[4: 4 + hlen].decode("utf-8"))
    return header.get("kind", "")


def pack_control(kind: str, **fields) -> bytes:
    """A small array-less control frame (ping/pong/swap/ok/shutdown — the
    replica-supervision vocabulary rides the same length-prefixed wire as
    scoring)."""
    header = {"v": 1, "kind": kind, "_arrays": []}
    header.update(fields)
    return _pack(header)


def unpack_control(payload: bytes) -> dict:
    """Decode a control frame to its header dict; a remote ``error`` frame
    raises like any other response."""
    header, _ = _unpack(payload)
    if header.get("kind") == "error":
        raise TransportError(f"remote control failed: {header.get('message')}")
    return header


def pack_request(request: ScoringRequest,
                 deadline_s: Optional[float] = None,
                 seq: Optional[int] = None,
                 gen: Optional[int] = None) -> bytes:
    """One scoring request as a wire payload.  Array order is pinned
    (sorted shard names, then sorted id columns, then offset) so the same
    request always produces the same bytes.  ``seq`` tags the frame for
    the PIPELINED client mode: the server scores tagged requests
    concurrently and echoes the tag on each response, so one connection
    can carry open-loop offered load instead of a serial exchange.
    ``gen`` stamps the sender's membership generation (ISSUE 19): the
    replica child adopts the max it has seen and echoes it on responses,
    so a parent can fence answers produced by a stale generation."""
    entries = []
    for shard in sorted(request.features):
        leaf = request.features[shard]
        if isinstance(leaf, tuple):
            entries.append(("ids", shard, leaf[0]))
            entries.append(("vals", shard, leaf[1]))
        else:
            entries.append(("feat", shard, leaf))
    for col in sorted(request.entity_ids):
        entries.append(("col", col, request.entity_ids[col]))
    if request.offset is not None:
        entries.append(("offset", "", request.offset))
    model = getattr(request, "model", None)
    if model is not None and not isinstance(model, str):
        # A coalesced mixed-tenant batch: per-row ids ride as a fixed-
        # width string array (object arrays have no wire form).
        # host-sync: model-id vectors live on host; this is a dtype cast.
        entries.append(("model", "", np.asarray(model).astype(str)))
        model = None
    header = {
        "v": 1, "kind": "score",
        "deadline_ms": None if deadline_s is None else deadline_s * 1e3,
        "_arrays": entries,
    }
    if model is not None:
        # Single-tenant request: the model id rides the header — the
        # frame-level routing field (ISSUE 18).
        header["model"] = model
    if seq is not None:
        header["seq"] = int(seq)
    if gen is not None:
        header["gen"] = int(gen)
    ctx = trace_of(request)
    if ctx is not None:
        # Distributed-trace propagation: the context rides the frame header
        # so the receiving hop parents its span under the sender's.
        header["trace"] = ctx.to_wire()
    return _pack(header)


def unpack_request_hx(
    payload: bytes,
) -> Tuple[ScoringRequest, Optional[float], Optional[int], dict]:
    """Decode a request frame to ``(request, deadline_s, seq, header)``
    — the header-retaining variant for receivers that need the frame's
    membership stamp (``header["gen"]``, ISSUE 19) besides the request
    itself.  ``seq`` is None for plain serial-exchange clients."""
    header, arrays = _unpack(payload)
    if header.get("kind") != "score":
        raise TransportError(f"unexpected request kind {header.get('kind')!r}")
    features: Dict[str, object] = {}
    sparse: Dict[str, dict] = {}
    entity_ids: Dict[str, np.ndarray] = {}
    offset = None
    model = header.get("model")
    for entry, arr in zip(header.get("arrays", []), arrays):
        slot, name = entry["slot"], entry["name"]
        if slot == "feat":
            features[name] = arr
        elif slot in ("ids", "vals"):
            sparse.setdefault(name, {})[slot] = arr
        elif slot == "col":
            entity_ids[name] = arr
        elif slot == "offset":
            offset = arr
        elif slot == "model":
            model = arr.astype(object)
        else:
            raise TransportError(f"unknown array slot {slot!r}")
    for name, pair in sparse.items():
        if "ids" not in pair or "vals" not in pair:
            raise TransportError(f"sparse shard {name!r} missing ids/vals")
        features[name] = (pair["ids"], pair["vals"])
    deadline_ms = header.get("deadline_ms")
    request = ScoringRequest(features=features, entity_ids=entity_ids,
                             offset=offset, model=model)
    ctx = TraceContext.from_wire(header.get("trace"))
    if ctx is not None:
        attach_trace(request, ctx)
    return (
        request,
        None if deadline_ms is None else deadline_ms / 1e3,
        header.get("seq"),
        header,
    )


def unpack_request_ex(
    payload: bytes,
) -> Tuple[ScoringRequest, Optional[float], Optional[int]]:
    """Decode a request frame to ``(request, deadline_s, seq)`` —
    ``seq`` is None for plain serial-exchange clients."""
    request, deadline_s, seq, _ = unpack_request_hx(payload)
    return request, deadline_s, seq


def unpack_request(payload: bytes) -> Tuple[ScoringRequest, Optional[float]]:
    request, deadline_s, _ = unpack_request_ex(payload)
    return request, deadline_s


def _seqed(header: dict, seq: Optional[int]) -> dict:
    if seq is not None:
        header["seq"] = int(seq)
    return header


def pack_scores(scores: np.ndarray, seq: Optional[int] = None,
                meta: Optional[dict] = None) -> bytes:
    """``meta`` piggybacks observability on the response header — the
    child replica ships its completed span dicts (``spans``) and served
    model version (``version``) inline, so trace merge needs no extra
    round-trip on the hot path."""
    header = {"v": 1, "kind": "scores",
              # host-sync: response egress — wire serialization of the host
              # scores array the scorer already fetched (its ONE d2h).
              "_arrays": [("scores", "", np.asarray(scores, np.float32))]}
    if meta:
        header.update(meta)
    return _pack(_seqed(header, seq))


def pack_shed(reason: str, detail: str = "",
              seq: Optional[int] = None) -> bytes:
    return _pack(_seqed({"v": 1, "kind": "shed", "reason": reason,
                         "detail": detail, "_arrays": []}, seq))


def pack_error(message: str, seq: Optional[int] = None) -> bytes:
    return _pack(_seqed({"v": 1, "kind": "error", "message": message[:2000],
                         "_arrays": []}, seq))


def _decode_response(payload: bytes):
    """``(seq, scores, exception, header)`` from a response frame — exactly
    one of scores/exception is set; the header carries any piggybacked
    observability metadata (child spans, served version)."""
    header, arrays = _unpack(payload)
    kind = header.get("kind")
    seq = header.get("seq")
    if kind == "scores":
        return seq, arrays[0], None, header
    if kind == "shed":
        return seq, None, RequestShedError(header.get("reason", "unknown"),
                                           header.get("detail", "")), header
    if kind == "error":
        return seq, None, TransportError(
            f"remote scoring failed: {header.get('message')}"
        ), header
    return (seq, None,
            TransportError(f"unexpected response kind {kind!r}"), header)


def unpack_response(payload: bytes) -> np.ndarray:
    _, scores, exc, _ = _decode_response(payload)
    if exc is not None:
        raise exc
    return scores


def unpack_response_ex(payload: bytes):
    """``(scores, header)`` — the header-aware decode for callers that
    consume the piggybacked span/version metadata (raises like
    :func:`unpack_response` on shed/error frames)."""
    _, scores, exc, header = _decode_response(payload)
    if exc is not None:
        raise exc
    return scores, header


# -- server ------------------------------------------------------------------

class ScoringServer:
    """Threaded TCP ingest in front of a fleet router (or anything with a
    ``submit(request, deadline_s=None) -> Future`` — a single
    ``RequestBatcher`` works too, minus shedding).  One handler thread per
    connection; each connection is a serial request/response stream, so
    client-side concurrency = connection count.  Admission sheds and
    scoring errors travel back as typed frames, never as dropped
    connections."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 telemetry=None):
        from photon_tpu.telemetry import NULL_SESSION

        self.service = service
        self.telemetry = telemetry or NULL_SESSION
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):  # noqa: D102 — per-connection loop
                outer._serve_connection(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.address = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="serving-transport", daemon=True,
        )
        self._thread.start()

    def _serve_connection(self, sock: socket.socket) -> None:
        t = self.telemetry
        t.counter("serving.transport_connections").inc()
        # Request/response frames are latency-critical small writes: Nagle
        # + delayed-ACK on a chatty exchange stream adds tens of ms per
        # roundtrip (observed ~30 ms on loopback) — disable batching.
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Bound response SENDS (not reads — idle persistent connections
        # must keep blocking in read_frame): pipelined responses run on
        # batcher/router callback threads, and a client that stops
        # reading (full TCP receive window) would otherwise wedge that
        # thread — the replica's whole scoring path — inside sendall.
        # With the send timeout the stalled connection errors and drops,
        # hurting only its own client.
        import struct as _struct

        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                        _struct.pack("ll", 30, 0))
        # Pipelined (seq-tagged) responses resolve on batcher/router
        # callback threads while this thread keeps reading: one write lock
        # per connection keeps frames whole on the wire.
        write_lock = threading.Lock()

        def send(out: bytes) -> bool:
            try:
                with write_lock:
                    write_frame(sock, out)
                t.counter("serving.transport_bytes", direction="out").inc(
                    len(out) + 4
                )
                return True
            except OSError:
                t.counter("serving.transport_drops").inc()
                return False

        while True:
            try:
                payload = read_frame(sock)
            except (OSError, TransportError):
                # Peer gone or a (possibly injected) transport fault: drop
                # the connection; the client reconnects and resends.
                t.counter("serving.transport_drops").inc()
                return
            t.counter("serving.transport_bytes", direction="in").inc(
                len(payload) + 4
            )
            seq = None
            try:
                request, deadline_s, seq = unpack_request_ex(payload)
                fut = self.service.submit(request, deadline_s=deadline_s)
                if seq is None:
                    # Serial exchange: one request in flight per connection.
                    out = pack_scores(fut.result())
                else:
                    # Pipelined: admission already ran (a synchronous shed
                    # raised above); the response rides a done-callback so
                    # the read loop keeps ingesting the offered stream —
                    # socket backpressure and framing are now INSIDE the
                    # overload measurement instead of serializing it.
                    def respond(f, seq=seq):
                        exc = f.exception()
                        if exc is None:
                            send(pack_scores(f.result(), seq=seq))
                        elif isinstance(exc, RequestShedError):
                            send(pack_shed(exc.reason, str(exc), seq=seq))
                        else:
                            send(pack_error(
                                f"{type(exc).__name__}: {exc}", seq=seq
                            ))

                    fut.add_done_callback(respond)
                    continue
            except RequestShedError as e:
                out = pack_shed(e.reason, str(e), seq=seq)
            except BaseException as e:  # surfaced to the caller, not fatal
                out = pack_error(f"{type(e).__name__}: {e}", seq=seq)
            if not send(out):
                return

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10)


# -- client ------------------------------------------------------------------

class ScoringClient:
    """One persistent connection to a :class:`ScoringServer`.

    ``score()`` is a synchronous request/response exchange; it retries
    transient transport failures (reconnect + resend — scoring is
    idempotent) through the standard ``retry_call`` backoff, and raises
    :class:`~photon_tpu.serving.router.RequestShedError` when admission
    fast-failed the request remotely.  NOT thread-safe: use one client per
    concurrent caller (a connection is a serial exchange stream)."""

    def __init__(self, address, telemetry=None, timeout_s: float = 30.0):
        from photon_tpu.telemetry import NULL_SESSION

        self.address = tuple(address)
        self.telemetry = telemetry or NULL_SESSION
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def score(self, request: ScoringRequest,
              deadline_s: Optional[float] = None) -> np.ndarray:
        from photon_tpu.fault.retry import retry_call

        payload = pack_request(request, deadline_s)

        def attempt() -> np.ndarray:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self.address, timeout=self.timeout_s
                )
                # See the server side: Nagle stalls a chatty exchange.
                self._sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            try:
                write_frame(self._sock, payload)
                return unpack_response(read_frame(self._sock))
            except OSError:
                # Drop the wedged connection so the NEXT attempt starts
                # from a fresh connect instead of a half-written stream.
                self._drop()
                raise

        return retry_call(
            attempt, site="transport:read", telemetry=self.telemetry
        )

    def close(self) -> None:
        self._drop()

    def __enter__(self) -> "ScoringClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class AsyncScoringClient:
    """Pipelined multi-connection client: ``submit()`` returns a Future and
    NEVER blocks on a response — request frames carry a sequence id, ride
    one of ``connections`` persistent sockets, and the server scores them
    concurrently, echoing the id on each response frame (scores, shed, or
    error) so a reader thread can resolve futures out of order.

    This is the open-loop load generator's transport
    (``traffic.replay_open_loop(client.submit, ...)``): the arrival
    schedule drives the SOCKET itself, so framing cost and socket
    backpressure sit inside the overload measurement instead of being
    bypassed by in-process submission.  Admission sheds come back as typed
    frames and surface as ``RequestShedError`` through the future.

    No retry/resend: a transport failure fails the connection's in-flight
    futures with :class:`TransportError` (an open-loop replay records
    them; resending mid-pipeline would reorder the offered schedule)."""

    @staticmethod
    def _settle(fut, value=None, exc: Optional[BaseException] = None):
        """Resolve a future exactly once — three paths can race to fail
        the same future on a dying connection (the submit-side send
        failure, the reader's decode, and _fail_pending's sweep); the
        shared ``resolve_once`` guard makes the loser's write a no-op."""
        from photon_tpu.serving.batcher import resolve_once

        resolve_once(fut, value, exc)

    def __init__(self, address, connections: int = 2, telemetry=None,
                 timeout_s: float = 60.0, observer=None):
        from photon_tpu.telemetry import NULL_SESSION

        self.address = tuple(address)
        self.telemetry = telemetry or NULL_SESSION
        # Optional FleetObserver: when set, sampled requests originate a
        # client-side span whose context rides the request frame, so the
        # server-side trace links under the caller's clock.
        self.observer = observer
        self._seq = 0
        self._lock = threading.Lock()
        self._closed = False
        self._conns = []
        for i in range(max(1, int(connections))):
            sock = socket.create_connection(self.address, timeout=timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = {
                "sock": sock,
                "wlock": threading.Lock(),
                "pending": {},  # seq -> Future (this connection's)
                "spans": {},  # seq -> client-side SpanRecord (traced only)
            }
            conn["reader"] = threading.Thread(
                target=self._read_loop, args=(conn,),
                name=f"async-scoring-client-{i}", daemon=True,
            )
            self._conns.append(conn)
        for conn in self._conns:
            conn["reader"].start()

    def submit(self, request: ScoringRequest,
               deadline_s: Optional[float] = None):
        """Send one request frame; the returned future resolves to the
        scores, or raises the remote shed/error."""
        from concurrent.futures import Future

        with self._lock:
            if self._closed:
                raise RuntimeError("client is closed")
            self._seq += 1
            seq = self._seq
        conn = self._conns[seq % len(self._conns)]
        fut = Future()
        span = (self.observer.client_span(request)
                if self.observer is not None else None)
        payload = pack_request(request, deadline_s, seq=seq)
        if span is not None:
            span.event("send", seq=seq, nbytes=len(payload))
            conn["spans"][seq] = span
        conn["pending"][seq] = fut
        try:
            with conn["wlock"]:
                write_frame(conn["sock"], payload)
        except OSError as e:
            conn["pending"].pop(seq, None)
            self._finish_span(conn, seq, status="error")
            self._settle(fut, exc=TransportError(f"send failed: {e}"))
            return fut
        dead = conn.get("dead")
        if dead is not None:
            # The reader died around this submit (the first send after a
            # peer FIN can still succeed into the socket buffer): nothing
            # will ever match this seq — fail it now, not at timeout.
            conn["pending"].pop(seq, None)
            self._finish_span(conn, seq, status="error")
            self._settle(fut, exc=TransportError(
                f"connection lost with request in flight: {dead}"
            ))
        return fut

    def _finish_span(self, conn, seq, status: str = "ok",
                     header: Optional[dict] = None) -> None:
        span = conn["spans"].pop(seq, None)
        if span is None:
            return
        version = None if header is None else header.get("version")
        span.event("response", seq=seq, version=version)
        if version is not None:
            span.attrs["version"] = version
        span.finish(status=status)
        if self.observer is not None:
            self.observer.collector.add(span)

    def _read_loop(self, conn) -> None:
        while True:
            try:
                payload = read_frame(conn["sock"])
            except (OSError, TransportError) as e:
                # Mark the connection dead BEFORE sweeping: a submit that
                # registers its future after the sweep sees the flag and
                # self-fails instead of waiting forever on a reader that
                # already exited.
                conn["dead"] = e
                self._fail_pending(conn, e)
                return
            seq, scores, exc, header = _decode_response(payload)
            fut = conn["pending"].pop(seq, None)
            if isinstance(exc, RequestShedError):
                status = "shed"
            elif exc is not None:
                status = "error"
            else:
                status = "ok"
            self._finish_span(conn, seq, status=status, header=header)
            if fut is None:
                continue  # unknown tag: a late frame after a local failure
            self._settle(fut, scores, exc)

    def _fail_pending(self, conn, error: BaseException) -> None:
        pending, conn["pending"] = conn["pending"], {}
        if not self._closed and pending:
            self.telemetry.counter("serving.transport_drops").inc()
        for seq in list(conn["spans"]):
            self._finish_span(conn, seq, status="error")
        for fut in pending.values():
            self._settle(fut, exc=TransportError(
                f"connection lost with request in flight: {error}"
            ))

    def close(self) -> None:
        with self._lock:
            self._closed = True
        for conn in self._conns:
            try:
                conn["sock"].close()
            except OSError:
                pass
        for conn in self._conns:
            conn["reader"].join(timeout=5)
            self._fail_pending(conn, ConnectionError("client closed"))

    def __enter__(self) -> "AsyncScoringClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
