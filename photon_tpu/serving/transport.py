"""Socket transport for the serving fleet: length-prefixed binary frames.

Stdlib-only (``socket`` + ``socketserver`` — no new deps): the real ingest
the ROADMAP's fleet tier calls for, in front of the same
``RequestBatcher``/router stack the in-process loop uses.  One TCP
connection carries a sequence of request/response exchanges:

    frame    := u32_be payload_len | payload
    payload  := u32_be header_len | header_json_utf8 | array_bytes...

The JSON header describes the frame kind and its array manifest — each
entry ``{"slot", "name", "dtype", "shape"}`` names one contiguous
little-endian buffer concatenated (in manifest order) after the header.
Request slots: ``feat`` (dense features per shard), ``ids``/``vals``
(padded-COO sparse pair per shard), ``col`` (raw entity keys per id
column — numpy fixed-width strings ride as their ``<U*`` buffers),
``offset``.  ``deadline_ms`` in the header is a RELATIVE budget: the
server stamps the absolute deadline at ingest, so client/server clocks
never need to agree.  Response kinds: ``scores`` (one float32 array),
``shed`` (admission fast-fail, with the reason), ``error``.

Fault surface: every frame read declares the ``transport:read`` fault
site (an injected transient read error behaves like a flaky network).
Scoring requests are idempotent, so :class:`ScoringClient` retries the
whole exchange through ``retry_call`` — reconnect + resend — and a
recovered fault is counted as ``io.retries{site=transport:read}``.

Residency contract (``tools/check_host_sync.py`` guards this module): the
transport is pure host IO — it must never touch device data; the
coercions below operate on wire bytes and caller-owned numpy only.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_tpu.fault.injection import fault_point
from photon_tpu.serving.router import RequestShedError
from photon_tpu.serving.scorer import ScoringRequest

MAX_FRAME_BYTES = 1 << 28  # 256 MB: far past any sane micro-batch


class TransportError(RuntimeError):
    """A malformed frame or a remote-side serving failure."""


# -- frame IO ----------------------------------------------------------------

def _read_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes (the fault-injectable transport read edge).
    A peer close mid-frame is a ConnectionError — an OSError, so the
    client's retry layer treats it like any transient network fault."""
    fault_point("transport:read")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf += chunk
    return bytes(buf)


def read_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("!I", _read_exact(sock, 4))
    if n > MAX_FRAME_BYTES:
        raise TransportError(f"frame of {n} bytes exceeds the "
                             f"{MAX_FRAME_BYTES}-byte cap")
    return _read_exact(sock, n)


def write_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("!I", len(payload)) + payload)


# -- payload encode/decode ---------------------------------------------------

def _pack(header: dict) -> bytes:
    manifest = []
    bufs = []
    for slot, name, arr in header.pop("_arrays"):
        a = np.ascontiguousarray(arr)
        manifest.append({
            "slot": slot, "name": name,
            "dtype": a.dtype.str, "shape": list(a.shape),
        })
        bufs.append(a.tobytes())
    header["arrays"] = manifest
    head = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return b"".join([struct.pack("!I", len(head)), head, *bufs])


def _unpack(payload: bytes) -> Tuple[dict, List[np.ndarray]]:
    (hlen,) = struct.unpack("!I", payload[:4])
    header = json.loads(payload[4: 4 + hlen].decode("utf-8"))
    pos = 4 + hlen
    arrays = []
    for entry in header.get("arrays", []):
        dtype = np.dtype(entry["dtype"])
        count = int(np.prod(entry["shape"], dtype=np.int64))
        nbytes = count * dtype.itemsize
        if pos + nbytes > len(payload):
            raise TransportError("truncated frame: array bytes short")
        arrays.append(
            np.frombuffer(payload[pos: pos + nbytes], dtype=dtype)
            .reshape(entry["shape"])
        )
        pos += nbytes
    if pos != len(payload):
        raise TransportError("trailing bytes after the array manifest")
    return header, arrays


def pack_request(request: ScoringRequest,
                 deadline_s: Optional[float] = None) -> bytes:
    """One scoring request as a wire payload.  Array order is pinned
    (sorted shard names, then sorted id columns, then offset) so the same
    request always produces the same bytes."""
    entries = []
    for shard in sorted(request.features):
        leaf = request.features[shard]
        if isinstance(leaf, tuple):
            entries.append(("ids", shard, leaf[0]))
            entries.append(("vals", shard, leaf[1]))
        else:
            entries.append(("feat", shard, leaf))
    for col in sorted(request.entity_ids):
        entries.append(("col", col, request.entity_ids[col]))
    if request.offset is not None:
        entries.append(("offset", "", request.offset))
    header = {
        "v": 1, "kind": "score",
        "deadline_ms": None if deadline_s is None else deadline_s * 1e3,
        "_arrays": entries,
    }
    return _pack(header)


def unpack_request(payload: bytes) -> Tuple[ScoringRequest, Optional[float]]:
    header, arrays = _unpack(payload)
    if header.get("kind") != "score":
        raise TransportError(f"unexpected request kind {header.get('kind')!r}")
    features: Dict[str, object] = {}
    sparse: Dict[str, dict] = {}
    entity_ids: Dict[str, np.ndarray] = {}
    offset = None
    for entry, arr in zip(header.get("arrays", []), arrays):
        slot, name = entry["slot"], entry["name"]
        if slot == "feat":
            features[name] = arr
        elif slot in ("ids", "vals"):
            sparse.setdefault(name, {})[slot] = arr
        elif slot == "col":
            entity_ids[name] = arr
        elif slot == "offset":
            offset = arr
        else:
            raise TransportError(f"unknown array slot {slot!r}")
    for name, pair in sparse.items():
        if "ids" not in pair or "vals" not in pair:
            raise TransportError(f"sparse shard {name!r} missing ids/vals")
        features[name] = (pair["ids"], pair["vals"])
    deadline_ms = header.get("deadline_ms")
    return (
        ScoringRequest(features=features, entity_ids=entity_ids,
                       offset=offset),
        None if deadline_ms is None else deadline_ms / 1e3,
    )


def pack_scores(scores: np.ndarray) -> bytes:
    return _pack(
        {"v": 1, "kind": "scores",
         # host-sync: response egress — wire serialization of the host
         # scores array the scorer already fetched (its ONE d2h).
         "_arrays": [("scores", "", np.asarray(scores, np.float32))]}
    )


def pack_shed(reason: str, detail: str = "") -> bytes:
    return _pack({"v": 1, "kind": "shed", "reason": reason,
                  "detail": detail, "_arrays": []})


def pack_error(message: str) -> bytes:
    return _pack({"v": 1, "kind": "error", "message": message[:2000],
                  "_arrays": []})


def unpack_response(payload: bytes) -> np.ndarray:
    header, arrays = _unpack(payload)
    kind = header.get("kind")
    if kind == "scores":
        return arrays[0]
    if kind == "shed":
        raise RequestShedError(header.get("reason", "unknown"),
                               header.get("detail", ""))
    if kind == "error":
        raise TransportError(f"remote scoring failed: {header.get('message')}")
    raise TransportError(f"unexpected response kind {kind!r}")


# -- server ------------------------------------------------------------------

class ScoringServer:
    """Threaded TCP ingest in front of a fleet router (or anything with a
    ``submit(request, deadline_s=None) -> Future`` — a single
    ``RequestBatcher`` works too, minus shedding).  One handler thread per
    connection; each connection is a serial request/response stream, so
    client-side concurrency = connection count.  Admission sheds and
    scoring errors travel back as typed frames, never as dropped
    connections."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 telemetry=None):
        from photon_tpu.telemetry import NULL_SESSION

        self.service = service
        self.telemetry = telemetry or NULL_SESSION
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):  # noqa: D102 — per-connection loop
                outer._serve_connection(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.address = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="serving-transport", daemon=True,
        )
        self._thread.start()

    def _serve_connection(self, sock: socket.socket) -> None:
        t = self.telemetry
        t.counter("serving.transport_connections").inc()
        # Request/response frames are latency-critical small writes: Nagle
        # + delayed-ACK on a chatty exchange stream adds tens of ms per
        # roundtrip (observed ~30 ms on loopback) — disable batching.
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                payload = read_frame(sock)
            except (OSError, TransportError):
                # Peer gone or a (possibly injected) transport fault: drop
                # the connection; the client reconnects and resends.
                t.counter("serving.transport_drops").inc()
                return
            t.counter("serving.transport_bytes", direction="in").inc(
                len(payload) + 4
            )
            try:
                request, deadline_s = unpack_request(payload)
                scores = self.service.submit(
                    request, deadline_s=deadline_s
                ).result()
                out = pack_scores(scores)
            except RequestShedError as e:
                out = pack_shed(e.reason, str(e))
            except BaseException as e:  # surfaced to the caller, not fatal
                out = pack_error(f"{type(e).__name__}: {e}")
            try:
                write_frame(sock, out)
                t.counter("serving.transport_bytes", direction="out").inc(
                    len(out) + 4
                )
            except OSError:
                t.counter("serving.transport_drops").inc()
                return

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10)


# -- client ------------------------------------------------------------------

class ScoringClient:
    """One persistent connection to a :class:`ScoringServer`.

    ``score()`` is a synchronous request/response exchange; it retries
    transient transport failures (reconnect + resend — scoring is
    idempotent) through the standard ``retry_call`` backoff, and raises
    :class:`~photon_tpu.serving.router.RequestShedError` when admission
    fast-failed the request remotely.  NOT thread-safe: use one client per
    concurrent caller (a connection is a serial exchange stream)."""

    def __init__(self, address, telemetry=None, timeout_s: float = 30.0):
        from photon_tpu.telemetry import NULL_SESSION

        self.address = tuple(address)
        self.telemetry = telemetry or NULL_SESSION
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def score(self, request: ScoringRequest,
              deadline_s: Optional[float] = None) -> np.ndarray:
        from photon_tpu.fault.retry import retry_call

        payload = pack_request(request, deadline_s)

        def attempt() -> np.ndarray:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self.address, timeout=self.timeout_s
                )
                # See the server side: Nagle stalls a chatty exchange.
                self._sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            try:
                write_frame(self._sock, payload)
                return unpack_response(read_frame(self._sock))
            except OSError:
                # Drop the wedged connection so the NEXT attempt starts
                # from a fresh connect instead of a half-written stream.
                self._drop()
                raise

        return retry_call(
            attempt, site="transport:read", telemetry=self.telemetry
        )

    def close(self) -> None:
        self._drop()

    def __enter__(self) -> "ScoringClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
