"""Process-backed serving replicas: own runtime, frame protocol, respawn.

PR 12's replicas are threads sharing one Python runtime — "replica
isolation" there is an honest fiction (one GIL, one jax runtime, one
process to crash).  This module makes it real (the ISSUE 13 tentpole;
Snap ML's hierarchy — node-level processes each owning their device set,
supervised from above — is the shape, PAPERS.md 1803.06333):

- **The child** (``python -m photon_tpu.serving.replica_proc``) is a full
  replica runtime: it loads the shared model ARTIFACT (the wire-format
  model file every replica of a fleet reads), builds its own
  :class:`~photon_tpu.serving.scorer.GameScorer`, AOT-warms the bucket
  ladder, then serves the PR 12 length-prefixed frame protocol on a
  loopback socket — ``score`` frames on the data connection, plus the
  supervision vocabulary on a control connection: ``ping``/``pong``
  (liveness), ``swap`` (hot-swap to a newer model artifact, zero child
  recompiles — the scorer's capacity-headroom swap), ``shutdown``.
  Device ownership comes from the environment the parent deals each child
  (``JAX_PLATFORMS`` + visible-device vars): on a multi-core/multi-device
  host each child owns its runtime and its devices; on the 1-core CPU
  fixture children share the core (the PR 12 honest-scaling bar applies).
- **The parent side** (:class:`SubprocessReplica`) is a drop-in
  :class:`~photon_tpu.serving.router.ScorerReplica`: the router's
  batcher coalesces requests exactly as for a thread replica, and the
  replica's "scorer" (:class:`_RemoteScorer`) exchanges each micro-batch
  as one frame on the data connection.  A dropped connection mid-batch is
  the crash signal: the batch raises
  :class:`~photon_tpu.serving.router.ReplicaDeadError` and the router
  reroutes it exactly-once — the same path an injected
  ``serve:replica_kill`` takes.
- **Fault surface**: ``replica:spawn`` fires at the top of every (re)spawn
  (retriable — the supervisor backs off and retries); ``replica:crash``
  consumed INSIDE the child hard-exits it (``os._exit``), a real crash
  with a real exit code; ``replica:hang`` consumed in the child wedges the
  handler, a real hang only the supervisor's probe deadline can see.

Residency contract (``tools/check_host_sync.py`` guards this module): the
parent side is pure host IO (frames, numpy); the one sanctioned fetch is
the artifact publish, which serializes the model tables to host once per
published version.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from photon_tpu.fault.injection import (
    InjectedKillError,
    consume_hang_injection,
    fault_point,
)
from photon_tpu.serving.netfault import maybe_shim
from photon_tpu.serving.router import (
    ReplicaDeadError,
    ScorerReplica,
)
from photon_tpu.serving.scorer import (
    ShardSpec,
    bucket_ladder,
    padded_cost,
)
from photon_tpu.serving.transport import (
    TransportError,
    pack_control,
    pack_error,
    pack_request,
    pack_scores,
    payload_kind,
    read_frame,
    unpack_control,
    unpack_request_ex,
    unpack_request_hx,
    unpack_response_ex,
    write_frame,
    _decode_response,
    _pack,
    _unpack,
)
from photon_tpu.telemetry.distributed import (
    FlightRecorder,
    MergeableHistogram,
    SpanRecord,
    shift_span_times,
    trace_of,
)

ARTIFACT_VERSION = 1
CRASH_EXIT_CODE = 86  # the child's injected-crash exit status


class ReplicaSpawnError(OSError):
    """Spawning a replica child failed (an ``OSError``: the supervisor's
    backoff-and-retry policy applies to a failed spawn exactly as the
    retry layer's does to failed IO)."""


# -- model wire artifact -------------------------------------------------------
#
# The shared model artifact every child loads (at boot and at swap) is ONE
# frame payload — the same header + array-manifest wire format the scoring
# protocol uses, so a model travels exactly like a request: fixed
# coordinates carry their coefficient vector, random coordinates their
# [entities, dim] table and sorted key vocabulary (string keys ride as
# their <U* buffers like any id column).  Serving needs means only; the
# artifact deliberately drops variances.


def pack_model(model, version: int) -> bytes:
    """One GAME model as a wire payload (the shared serving artifact)."""
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel

    entries = []
    meta = []
    for name, coord in model.coordinates.items():
        if isinstance(coord, FixedEffectModel):
            meta.append({"name": name, "kind": "fixed",
                         "shard": coord.shard_name,
                         "task": coord.model.task_type})
            entries.append(
                ("coef", name,
                 # host-sync: artifact publish — the coefficient vector is
                 # fetched to host once per published model version.
                 np.asarray(coord.coefficients.means, np.float32))
            )
        elif isinstance(coord, RandomEffectModel):
            meta.append({"name": name, "kind": "random",
                         "shard": coord.shard_name,
                         "column": coord.entity_column,
                         "task": coord.task_type})
            # host-sync: artifact publish — the per-entity table is fetched
            # to host once per published model version.
            entries.append(("table", name, np.asarray(coord.table,
                                                      np.float32)))
            # host-sync: keys are host numpy by construction (publish-time).
            entries.append(("keys", name, np.asarray(coord.keys)))
        else:
            raise TypeError(f"cannot publish a {type(coord).__name__}")
    return _pack({
        "v": ARTIFACT_VERSION, "kind": "model",
        "task": model.task_type, "version": int(version), "coords": meta,
        "_arrays": entries,
    })


def unpack_model(payload: bytes):
    """``(GameModel, version)`` from a model artifact payload."""
    from photon_tpu.game.model import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_tpu.models.glm import Coefficients, model_for_task

    header, arrays = _unpack(payload)
    if header.get("kind") != "model":
        raise TransportError(
            f"unexpected artifact kind {header.get('kind')!r}"
        )
    slots: Dict[Tuple[str, str], np.ndarray] = {}
    for entry, arr in zip(header.get("arrays", []), arrays):
        slots[(entry["slot"], entry["name"])] = arr
    coordinates = {}
    for meta in header["coords"]:
        name = meta["name"]
        if meta["kind"] == "fixed":
            coordinates[name] = FixedEffectModel(
                model_for_task(
                    meta["task"], Coefficients(slots[("coef", name)])
                ),
                meta["shard"],
            )
        else:
            coordinates[name] = RandomEffectModel(
                table=slots[("table", name)],
                keys=slots[("keys", name)],
                entity_column=meta["column"],
                shard_name=meta["shard"],
                task_type=meta["task"],
            )
    model = GameModel(coordinates=coordinates, task_type=header["task"])
    return model, int(header.get("version", 0))


def save_model_artifact(path: str, model, version: int) -> None:
    """Atomic artifact publish: temp + fsync + rename, so a reader (a
    booting child) sees the previous complete artifact or the new one."""
    payload = pack_model(model, version)
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp",
        dir=os.path.dirname(path) or ".",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_model_artifact(path: str, telemetry=None):
    """``(GameModel, version)`` from an artifact file (retried like any
    guarded model load)."""
    from photon_tpu.fault.retry import retry_call

    def attempt():
        with open(path, "rb") as f:
            return f.read()

    return unpack_model(
        retry_call(attempt, site="model:load", telemetry=telemetry)
    )


class ModelStore:
    """Versioned shared model artifacts under one fleet workdir.

    ``publish()`` writes the wire-format artifact ONCE per model object
    (cached by identity, with a strong reference so the cache key cannot
    be recycled) and returns its path+version; every child — at boot, at
    swap, at respawn — loads from the same file: the shared-model-artifact
    distribution the fleet tier is built on.

    Only the newest ``keep`` versions stay cached (default 2: the served
    model plus its predecessor, which an in-flight swap/rollback may
    still reference) — a long-running fleet rolling models out
    periodically must not grow host memory and workdir disk by one full
    table set per rollout forever.  Re-publishing an evicted model (a
    deep rollback) simply writes it again under a fresh version."""

    def __init__(self, workdir: str, keep: int = 2):
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.keep = max(1, int(keep))
        self._lock = threading.Lock()
        self._published = []  # [(model, path, version)] — strong refs
        self._next = 0

    def publish(self, model) -> Tuple[str, int]:
        with self._lock:
            for m, path, version in self._published:
                if m is model:
                    return path, version
            version = self._next
            self._next += 1
            path = os.path.join(self.workdir, f"model-v{version:06d}.bin")
            save_model_artifact(path, model, version)
            self._published.append((model, path, version))
            while len(self._published) > self.keep:
                _, old_path, _ = self._published.pop(0)
                try:
                    os.unlink(old_path)
                except OSError:
                    pass
            return path, version


# -- the child runtime ---------------------------------------------------------


class _ChildService:
    """The replica child's state: one scorer (+ artifact version) behind a
    lock so a ``swap`` and a concurrent ``score`` can never interleave a
    half-published model (the scorer's own one-assignment publication does
    the real work; the lock only orders version bookkeeping).

    ``telemetry`` is the child's own in-process registry: the scorer's
    ``serving.*`` counters (host_syncs, batches, cold_entities, ...)
    accrue HERE, in the child — the ``stats`` control frame is how they
    reach the parent's run report (ISSUE 14 satellite; ROADMAP fleet
    edge (e))."""

    def __init__(self, replica_id: str, scorer, version: int,
                 telemetry=None, flight_path: Optional[str] = None,
                 generation: int = 0):
        from collections import deque

        from photon_tpu.telemetry import NULL_SESSION

        self.replica_id = replica_id
        self.scorer = scorer
        self.version = version
        # Membership generation (ISSUE 19): seeded from the spawn config,
        # then ratcheted to the max stamp seen on any inbound frame — the
        # child adopts the parent's view and ECHOES its own on every
        # response, so a zombie (a child whose lease expired while a
        # newer generation took over) keeps answering with a stale stamp
        # the parent's exchange loop fences.
        self.generation = int(generation)
        self.telemetry = telemetry or NULL_SESSION
        self.lock = threading.Lock()
        # Observability: the crash flight recorder (flushed to
        # ``flight_path`` at traced-frame ingress, BEFORE scoring — so a
        # SIGKILL mid-batch still leaves the victim's last accepted work
        # on disk), the mergeable compute-latency histogram the parent
        # aggregates fleet-wide, and the overflow queue for spans whose
        # response frame could not carry them (error paths).
        self.process = f"replica-{replica_id}:{os.getpid()}"
        self.flight_path = flight_path
        self.flight = FlightRecorder(self.process)
        self.latency_hist = MergeableHistogram()
        self._pending_spans: deque = deque(maxlen=256)
        self._spans_lock = threading.Lock()

    def _flush_flight(self) -> None:
        if not self.flight_path:
            return
        try:
            self.flight.dump(self.flight_path)
        except OSError:
            pass  # a full disk must not fail the scoring path

    def _drain_spans(self) -> list:
        with self._spans_lock:
            out = list(self._pending_spans)
            self._pending_spans.clear()
        return out

    def _score_frame(self, payload: bytes) -> bytes:
        """One scoring exchange, with the traced-request hop recorded: a
        request carrying a wire trace context gets a child span (ingress →
        compute → egress) shipped back inline on the response header."""
        self.flight.note_frame("in", "score", len(payload))
        self.maybe_fault()
        request, _, seq, rheader = unpack_request_hx(payload)
        gen = rheader.get("gen")
        if gen is not None:
            self.generation = max(self.generation, int(gen))
        ctx = trace_of(request)
        span = None
        if ctx is not None:
            span = SpanRecord(ctx.trace_id, "replica.score", self.process,
                              parent_id=ctx.span_id)
            span.event("ingress", rows=request.num_rows,
                       nbytes=len(payload))
            self.flight.note_span(span, "open")
            self._flush_flight()
        t0 = time.monotonic()
        try:
            if span is not None:
                span.event("compute_begin")
            scores = self.scorer.score_batch(request)
            if span is not None:
                span.event("compute_end")
        except BaseException as e:
            if span is not None:
                span.finish(status="error")
                self.flight.note_span(span, "close")
                with self._spans_lock:
                    self._pending_spans.append(span.to_dict())
            # Echo ``seq`` on the error frame: the parent's seq-matching
            # exchange loop would FENCE a seq-less reply and resend until
            # its deadline — a scoring failure must settle the exchange
            # that caused it, not starve it (ISSUE 19).
            return pack_error(f"{type(e).__name__}: {e}", seq=seq)
        self.latency_hist.observe(time.monotonic() - t0)
        meta = {"version": self.version, "gen": self.generation}
        if span is not None:
            span.event("egress")
            span.attrs["rows"] = request.num_rows
            span.attrs["version"] = self.version
            span.finish()
            self.flight.note_span(span, "close")
            meta["spans"] = [span.to_dict()] + self._drain_spans()
        return pack_scores(scores, seq=seq, meta=meta)

    def serving_counters(self) -> list:
        """This child's scorer-level ``serving.*`` counters as JSON-ready
        ``{name, labels, value}`` rows — the ``stats`` frame payload.
        Values are CUMULATIVE for the child's lifetime; the parent merges
        deltas, so repeated pulls never double-count."""
        snapshot = self.telemetry.registry.snapshot()
        return [
            {"name": m["name"], "labels": dict(m.get("labels") or {}),
             "value": float(m["value"])}
            for m in snapshot.get("counters", [])
            if m["name"].startswith("serving.")
        ]

    def maybe_fault(self) -> None:
        """The child-side fault surface: an injected ``replica:crash``
        HARD-EXITS the child (a real crash with a real exit code — the
        supervisor sees it via ``poll_exit``/the dropped connection), an
        injected ``replica:hang`` wedges this handler thread (a real hang
        only the probe deadline can see; the supervisor kills the child)."""
        try:
            fault_point("replica:crash", replica=self.replica_id)
        except InjectedKillError:
            os._exit(CRASH_EXIT_CODE)
        if consume_hang_injection(self.replica_id):
            time.sleep(3600.0)

    def handle(self, sock: socket.socket, shutdown) -> None:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                payload = read_frame(sock)
            except (OSError, TransportError):
                return
            kind = payload_kind(payload)
            # Control frames echo the caller's ``seq`` (and the pong its
            # generation): the parent's exchange loops discard stale
            # replies left in the pipe by a timed-out earlier exchange —
            # without the echo, a late pong could satisfy the WRONG ping
            # and poison the clock-offset estimate (ISSUE 19).
            seq = None
            try:
                if kind == "score":
                    out = self._score_frame(payload)
                elif kind == "ping":
                    self.maybe_fault()
                    header = unpack_control(payload)
                    seq = header.get("seq")
                    gen = header.get("gen")
                    if gen is not None:
                        self.generation = max(self.generation, int(gen))
                    out = pack_control(
                        "pong", version=self.version, pid=os.getpid(),
                        compilations=self.scorer.compilations,
                        seq=seq, gen=self.generation,
                        # Clock-offset estimation: the child's wall clock,
                        # sampled mid-exchange — the parent subtracts the
                        # RTT midpoint to estimate this host's skew and
                        # de-skews child span timestamps before merging.
                        child_time=time.time(),
                    )
                elif kind == "stats":
                    # Deliberately NOT behind maybe_fault: a stats pull is
                    # advisory telemetry, not a liveness probe — the
                    # injected crash/hang sites stay on the frames whose
                    # failure semantics the supervisor tests pin.
                    seq = unpack_control(payload).get("seq")
                    out = pack_control(
                        "stats", version=self.version,
                        counters=self.serving_counters(),
                        hist=self.latency_hist.snapshot(),
                        seq=seq,
                    )
                elif kind == "spans":
                    # Drain completed-but-unshipped spans (error paths) —
                    # advisory like stats, so NOT behind maybe_fault.
                    seq = unpack_control(payload).get("seq")
                    out = pack_control("spans", spans=self._drain_spans(),
                                       seq=seq)
                elif kind == "swap":
                    header = unpack_control(payload)
                    seq = header.get("seq")
                    model, version = load_model_artifact(header["path"])
                    model_id = header.get("model_id")
                    with self.lock:
                        if model_id is None:
                            self.scorer.swap_model(model)
                        else:
                            # Multi-model arena child: replace ONE tenant
                            # slice; every other hosted model is untouched.
                            self.scorer.swap_model(model, model_id=model_id)
                        self.version = version
                    out = pack_control("ok", version=version, seq=seq)
                elif kind == "shutdown":
                    seq = unpack_control(payload).get("seq")
                    out = pack_control("ok", seq=seq)
                    try:
                        write_frame(sock, out)
                    except OSError:
                        pass
                    shutdown()
                    return
                else:
                    out = pack_error(f"unknown frame kind {kind!r}")
            except BaseException as e:  # surfaced as a typed frame
                out = pack_error(f"{type(e).__name__}: {e}", seq=seq)
            try:
                write_frame(sock, out)
            except OSError:
                return


def _child_main(argv=None) -> None:
    import argparse

    import socketserver

    p = argparse.ArgumentParser("photon_tpu.serving.replica_proc")
    # Optional when the config carries a multi-model "models" map (each
    # tenant then names its own artifact path).
    p.add_argument("--artifact", default=None)
    p.add_argument("--ready-file", required=True)
    p.add_argument("--config", required=True, help="JSON replica config")
    args = p.parse_args(argv)
    cfg = json.loads(args.config)

    # Parent-death watchdog: the parent holds our stdin pipe open for our
    # whole life and never writes to it — EOF means the parent is GONE
    # (crashed, SIGKILLed, or torn down racing a respawn), and an orphaned
    # replica serving nobody forever is a resource leak, not availability.
    def watch_parent():
        try:
            sys.stdin.buffer.read()
        except Exception:  # noqa: BLE001 — any stdin failure == orphaned
            pass
        os._exit(0)

    threading.Thread(target=watch_parent, name="parent-watch",
                     daemon=True).start()

    from photon_tpu.serving.scorer import GameScorer
    from photon_tpu.telemetry import TelemetrySession

    spec = {
        shard: ShardSpec(kind=s["kind"], dim=int(s["dim"]),
                         nnz=int(s.get("nnz", 0)))
        for shard, s in cfg["spec"].items()
    }
    # The child's own registry: scorer counters accrue in THIS process and
    # travel to the parent via the stats frame — never written to disk
    # here (the parent's run report is the one report of the fleet).
    session = TelemetrySession(f"replica-{cfg['replica_id']}")
    if cfg.get("models"):
        # Multi-model arena child: every hosted tenant loads from its own
        # artifact into ONE shared arena + ONE compiled bucket ladder.
        from photon_tpu.serving.arena import MultiModelScorer

        loaded, version = {}, 0
        for mid, path in cfg["models"].items():
            m, v = load_model_artifact(path)
            loaded[mid] = m
            version = max(version, v)
        scorer = MultiModelScorer(
            loaded,
            request_spec=spec,
            buckets=tuple(cfg["buckets"]) if cfg.get("buckets") else None,
            max_batch=int(cfg["max_batch"]),
            min_bucket=int(cfg["min_bucket"]),
            telemetry=session,
            table_capacity_factor=int(cfg.get("table_capacity_factor", 1)),
            table_dtype=cfg.get("table_dtype", "f32"),
            reserve_rows=int(cfg.get("reserve_rows", 0)),
        ).warmup()
    else:
        model, version = load_model_artifact(args.artifact)
        scorer = GameScorer(
            model,
            request_spec=spec,
            buckets=tuple(cfg["buckets"]) if cfg.get("buckets") else None,
            max_batch=int(cfg["max_batch"]),
            min_bucket=int(cfg["min_bucket"]),
            telemetry=session,
            table_capacity_factor=int(cfg.get("table_capacity_factor", 1)),
            table_dtype=cfg.get("table_dtype", "f32"),
        ).warmup()
    service = _ChildService(cfg["replica_id"], scorer, version,
                            telemetry=session,
                            flight_path=cfg.get("flight_path"),
                            generation=int(cfg.get("generation", 0)))

    class _Handler(socketserver.BaseRequestHandler):
        def handle(self):  # noqa: D102 — per-connection loop
            service.handle(self.request, shutdown)

    class _Server(socketserver.ThreadingTCPServer):
        daemon_threads = True
        allow_reuse_address = True

    server = _Server(("127.0.0.1", 0), _Handler)

    def shutdown():
        threading.Thread(target=server.shutdown, daemon=True).start()

    # Atomic readiness handshake: the parent polls for this file.
    ready = {
        "port": server.server_address[1],
        "pid": os.getpid(),
        "version": version,
        "compilations": scorer.compilations,
    }
    tmp = args.ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ready, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, args.ready_file)
    server.serve_forever()
    server.server_close()


# -- the parent side -----------------------------------------------------------


def child_device_env(index: int, n_replicas: int) -> Dict[str, str]:
    """The per-child device deal: each child pins the parent's platform via
    ``JAX_PLATFORMS`` and, on device-backed platforms, owns a round-robin
    slice of the visible devices — process-level replica isolation with
    real per-replica device ownership.  The slice is cut from the
    PARENT'S OWN visibility mask when one is set (``CUDA_VISIBLE_DEVICES=
    2,3`` must deal ``2``/``3`` to the children, never absolute ids the
    job was fenced away from).  On CPU there is nothing to deal (children
    share the host's cores; the honest 1-core bar applies)."""
    import jax

    platform = jax.default_backend()
    env = {"JAX_PLATFORMS": platform}
    if platform in ("gpu", "cuda", "rocm", "tpu"):
        var = ("TPU_VISIBLE_DEVICES" if platform == "tpu"
               else "CUDA_VISIBLE_DEVICES")
        mask = os.environ.get(var, "").strip()
        if mask:
            ids = [t.strip() for t in mask.split(",") if t.strip()]
        else:
            ids = [str(i) for i in range(jax.local_device_count())]
        mine = ids[index % len(ids):: n_replicas] or [ids[index % len(ids)]]
        env[var] = ",".join(mine)
    return env


class _RemoteScorer:
    """Parent-side facade of a child's scorer: mirrors the GameScorer
    surface the replica/batcher/router layers touch (bucket ladder, model,
    compilations, warmup, swap) while ``score_batch`` is one frame
    exchange on the data connection.  A dropped/reset connection raises
    :class:`ReplicaDeadError` — the crash signal the router reroutes on."""

    def __init__(self, replica_id: str, model, version: int,
                 store: ModelStore, request_spec: Dict[str, ShardSpec],
                 buckets, max_batch: int, min_bucket: int,
                 port: int, compilations: int, telemetry=None,
                 timeout_s: float = 300.0, span_sink=None,
                 table_dtype: str = "f32", models: Optional[Dict] = None,
                 generation: int = 0):
        from photon_tpu.telemetry import NULL_SESSION

        self.replica_id = replica_id
        self.model = model
        # Membership generation (ISSUE 19): stamped on every request and
        # ping; the child echoes the stamp on responses, and a response
        # whose stamp disagrees is FENCED — a zombie child (dead-declared
        # but still answering) cannot satisfy a live exchange.
        self.generation = int(generation)
        # Multi-model arena child: the hosted tenant map (id -> model),
        # mirrored parent-side so a respawn can rebuild the same arena and
        # a per-tenant rollout can read the old slice for rollback.
        self.models: Optional[Dict] = dict(models) if models else None
        self.version = version
        # Estimated child-minus-parent wall-clock offset (EWMA over ping
        # RTT midpoints) — applied to child span timestamps before they
        # merge into the parent's trace tree.
        self.clock_offset_s = 0.0
        # Mirrors the child scorer's storage tier so parent-side parity
        # gates (router canary histogram, fleet defaults) see one surface.
        self.table_dtype = str(table_dtype)
        # Observability: completed child spans piggybacked on response
        # headers (or pulled via the ``spans`` control frame) go here; the
        # last shipped histogram snapshot is what the observer aggregates.
        self.span_sink = span_sink
        self.last_hist_snapshot: Optional[dict] = None
        self.request_spec = request_spec
        self.buckets = bucket_ladder(buckets, max_batch, min_bucket)
        self.max_bucket = self.buckets[-1]
        self.compilations = int(compilations)
        self.telemetry = telemetry or NULL_SESSION
        self._store = store
        self._data_lock = threading.Lock()
        self._ctrl_lock = threading.Lock()
        # Last-seen child counter values per (name, labels) — the delta
        # base for stats pulls.  Lives on the scorer (fresh per spawned
        # child), so a respawned child's counters restarting at zero can
        # never produce negative deltas.  The lock serializes WHOLE pulls
        # (exchange + read-merge-update): a supervisor-thread pull racing
        # a direct pull_stats()/close() must not compute two deltas from
        # one stale base and double-count into the parent registry.
        self._stats_seen: Dict[tuple, float] = {}
        self._stats_lock = threading.Lock()
        # Exchange bookkeeping (ISSUE 19): every request/ping carries a
        # process-unique seq the child echoes; on a per-attempt timeout
        # the exchange RESENDS (the frame may have been black-holed by a
        # partition) until ``resend_deadline_s``, fencing any stale-seq
        # replies a prior timed-out attempt left in the pipe.  A dropped
        # CONNECTION (vs. dropped frame) gets one silent reconnect per
        # exchange — rejoin-within-lease, not death.
        self._seq = itertools.count(1)
        self._port = int(port)
        self._timeout_s = float(timeout_s)
        self._closed = False
        self.exchange_timeout_s = 30.0
        self.resend_deadline_s = float(timeout_s)
        self._data = self._connect(port, timeout_s, "data")
        self._ctrl = self._connect(port, timeout_s, "ctrl")

    def _connect(self, port: int, timeout_s: float, chan: str):
        sock = socket.create_connection(("127.0.0.1", port),
                                        timeout=timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Chaos seam: an installed NetFaultPlan wraps this socket so every
        # partition/duplicate/reorder scenario is reproducible (ISSUE 19).
        return maybe_shim(sock, f"{self.replica_id}:{chan}")

    def _reconnect(self, chan: str):
        """Silent rejoin within the lease window: a dropped control or
        data connection is NOT death — dial the same child again and let
        the caller resend.  Refused (child actually gone) raises, which
        the exchange surfaces as :class:`ReplicaDeadError`."""
        if self._closed:
            raise ConnectionError(
                f"replica {self.replica_id} scorer is disconnected"
            )
        old = self._data if chan == "data" else self._ctrl
        try:
            old.close()
        except OSError:
            pass
        sock = self._connect(self._port, self._timeout_s, chan)
        if chan == "data":
            self._data = sock
        else:
            self._ctrl = sock
        self.telemetry.counter("serving.replica_reconnects",
                               replica=self.replica_id, chan=chan).inc()
        return sock

    # -- GameScorer surface ---------------------------------------------------
    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} rows exceeds max bucket "
                         f"{self.max_bucket}")

    def padded_rows(self, n: int) -> int:
        return padded_cost(n, self.buckets)

    def warmup(self) -> "_RemoteScorer":
        return self  # the child AOT-warmed its ladder at boot

    def score_batch(self, request) -> np.ndarray:
        seq = next(self._seq)
        payload = pack_request(request, seq=seq, gen=self.generation)
        try:
            with self._data_lock:
                scores, header = self._exchange_scores(payload, seq)
        except (socket.timeout, OSError) as e:
            raise ReplicaDeadError(
                f"replica {self.replica_id} child connection lost: {e}"
            ) from e
        spans = header.get("spans")
        if spans and self.span_sink is not None:
            try:
                self.span_sink(spans)
            except Exception:  # noqa: BLE001 — span delivery is advisory
                pass
        return scores

    def _exchange_scores(self, payload: bytes, seq: int):
        """One at-least-once scoring exchange with fencing (ISSUE 19):
        send, then read until a response matching ``seq`` AND the current
        generation arrives.  A per-attempt ``exchange_timeout_s`` silence
        means the frame (either direction) may be black-holed — resend
        until ``resend_deadline_s``.  Duplicated/stale-seq replies are
        discarded and counted; a matching reply stamped with a STALE
        generation raises :class:`ReplicaDeadError` (the zombie fence —
        the router reroutes, exactly-once preserved).  Duplicate sends
        are safe: the child may score a request twice, but only ONE reply
        per seq ever settles the exchange."""
        deadline = time.monotonic() + self.resend_deadline_s
        reconnected = False
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ConnectionError(
                    f"no matching response for seq {seq} within "
                    f"{self.resend_deadline_s:g}s"
                )
            try:
                self._data.settimeout(
                    min(self.exchange_timeout_s, max(remaining, 0.05))
                )
                write_frame(self._data, payload)
                while True:
                    rseq, scores, exc, header = _decode_response(
                        read_frame(self._data)
                    )
                    if rseq is None:
                        if exc is not None:
                            raise exc  # seq-less child failure: backstop
                        continue
                    if int(rseq) != seq:
                        self.telemetry.counter(
                            "serving.fenced_responses",
                            replica=self.replica_id, reason="stale_seq",
                        ).inc()
                        continue
                    rgen = header.get("gen")
                    if rgen is not None and int(rgen) != int(self.generation):
                        self.telemetry.counter(
                            "serving.fenced_responses",
                            replica=self.replica_id, reason="stale_gen",
                        ).inc()
                        raise ReplicaDeadError(
                            f"replica {self.replica_id} answered from stale "
                            f"generation {rgen} (current {self.generation}) "
                            f"— response fenced"
                        )
                    if exc is not None:
                        raise exc
                    return scores, header
            except socket.timeout:
                self.telemetry.counter(
                    "serving.exchange_resends", replica=self.replica_id
                ).inc()
                continue
            except OSError:
                if reconnected or self._closed:
                    raise
                reconnected = True
                self._reconnect("data")
                continue

    def model_for(self, model_id: str):
        """The hosted model behind one tenant id (multi-model children):
        what a per-tenant rollout reads for its rollback slice."""
        if self.models is None or model_id not in self.models:
            raise KeyError(f"model {model_id!r} is not hosted on replica "
                           f"{self.replica_id}")
        return self.models[model_id]

    def swap_model(self, model, model_id: Optional[str] = None) -> None:
        """Hot-swap the CHILD to a newer model: publish the shared
        artifact (cached per model object — one file serves every replica
        of the fleet) and instruct the child over the control connection.
        The child's scorer does the capacity-headroom swap — zero child
        recompiles, same refusal semantics as a thread replica.
        ``model_id`` targets one tenant slice of a multi-model child; the
        other hosted models are untouched."""
        path, version = self._store.publish(model)
        frame = {"path": path, "version": version}
        if model_id is not None:
            frame["model_id"] = model_id
        header = self._ctrl_exchange("swap", **frame)
        if header.get("kind") != "ok":
            raise TransportError(
                f"swap refused: unexpected reply {header.get('kind')!r}"
            )
        if model_id is not None and self.models is not None:
            self.models[model_id] = model
        if model_id is None:
            self.model = model
            if self.models is not None and self.models:
                self.models[next(iter(self.models))] = model
        self.version = version

    def _ctrl_exchange(self, kind: str, **fields) -> dict:
        """One seq-tagged control exchange: send, then read until the
        reply echoes our seq (discarding stale replies a timed-out
        earlier exchange left in the pipe — counted as fenced)."""
        seq = next(self._seq)
        with self._ctrl_lock:
            write_frame(self._ctrl, pack_control(kind, seq=seq, **fields))
            while True:
                header = unpack_control(read_frame(self._ctrl))
                if header.get("seq") in (None, seq):
                    return header
                self.telemetry.counter(
                    "serving.fenced_responses",
                    replica=self.replica_id, reason="stale_ctrl",
                ).inc()

    # -- supervision ----------------------------------------------------------
    def ping(self, deadline_s: float, gen: Optional[int] = None) -> dict:
        """Liveness ping — the LEASE RENEWAL exchange (ISSUE 19).  The
        ping carries a ``seq`` (stale pongs from timed-out earlier probes
        are fenced, not mistaken for this renewal) and the membership
        generation stamp the child adopts; the deadline rides the socket
        (so a silent partition surfaces as ``socket.timeout`` promptly
        and RELEASES the control lock — the next probe after heal can
        renew), with the watchdog's ``call_with_timeout`` as the backstop
        for a wedged write.  A dropped control connection gets one silent
        reconnect — rejoin within the lease, not death.

        Each pong doubles as a clock-offset sample: the child echoes its
        wall clock, and ``child_time - (t_send + t_recv)/2`` estimates
        this child's skew (the RTT-midpoint trick — symmetric-path NTP).
        An EWMA smooths jitter; the offset de-skews child span timestamps
        before trace merge, so a skewed host cannot misorder hops.  The
        pong also refreshes ``compilations`` — the fleet-level recompile
        ledger stays honest across swaps without an extra frame."""
        from photon_tpu.fault.watchdog import call_with_timeout

        seq = next(self._seq)
        stamp = self.generation if gen is None else int(gen)

        def exchange():
            with self._ctrl_lock:
                deadline = time.monotonic() + deadline_s
                reconnected = False
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise socket.timeout(
                            f"ping seq {seq} unanswered within "
                            f"{deadline_s:g}s"
                        )
                    try:
                        self._ctrl.settimeout(max(remaining, 0.05))
                        t_send = time.time()
                        write_frame(
                            self._ctrl,
                            pack_control("ping", seq=seq, gen=stamp),
                        )
                        while True:
                            header = unpack_control(read_frame(self._ctrl))
                            if header.get("seq") in (None, seq):
                                break
                            self.telemetry.counter(
                                "serving.fenced_responses",
                                replica=self.replica_id,
                                reason="stale_pong",
                            ).inc()
                        t_recv = time.time()
                        break
                    except socket.timeout:
                        raise
                    except OSError:
                        if reconnected or self._closed:
                            raise
                        reconnected = True
                        self._reconnect("ctrl")
            child_time = header.get("child_time")
            if isinstance(child_time, (int, float)):
                sample = float(child_time) - (t_send + t_recv) / 2.0
                self.clock_offset_s = (
                    sample if self.clock_offset_s == 0.0
                    else 0.8 * self.clock_offset_s + 0.2 * sample
                )
            comps = header.get("compilations")
            if comps is not None:
                self.compilations = int(comps)
            return header

        return call_with_timeout(
            exchange, deadline_s + 1.0, site=f"replica:{self.replica_id}:ping"
        )

    def stats(self, deadline_s: float = 5.0) -> list:
        """Pull the child's cumulative ``serving.*`` counters over the
        control connection (the ``stats`` frame — ISSUE 14 satellite).
        Deadline-bounded like the ping: a wedged child must not hang the
        supervisor's stats pass."""
        from photon_tpu.fault.watchdog import call_with_timeout

        header = call_with_timeout(
            lambda: self._ctrl_exchange("stats"),
            deadline_s, site=f"replica:{self.replica_id}:stats"
        )
        self.last_hist_snapshot = header.get("hist") or self.last_hist_snapshot
        return header.get("counters", [])

    def pull_spans(self, deadline_s: float = 5.0) -> list:
        """Drain the child's completed-but-unshipped spans (error paths)
        over the control connection — deadline-bounded like every other
        control exchange."""
        from photon_tpu.fault.watchdog import call_with_timeout

        header = call_with_timeout(
            lambda: self._ctrl_exchange("spans"),
            deadline_s, site=f"replica:{self.replica_id}:spans"
        )
        return header.get("spans", [])

    def shutdown(self, deadline_s: float = 5.0) -> None:
        from photon_tpu.fault.watchdog import call_with_timeout

        call_with_timeout(lambda: self._ctrl_exchange("shutdown"),
                          deadline_s,
                          site=f"replica:{self.replica_id}:shutdown")

    def disconnect(self) -> None:
        # Latch first: a batcher thread mid-exchange must NOT dial the
        # (possibly respawned-on-the-same-port) child back after teardown.
        self._closed = True
        for sock in (self._data, self._ctrl):
            try:
                sock.close()
            except OSError:
                pass


class SubprocessReplica(ScorerReplica):
    """A serving replica whose runtime is a CHILD PROCESS — its own Python
    and jax runtime, its own device set (dealt via the spawn environment),
    speaking the frame protocol to the router over loopback sockets.

    Drop-in for :class:`ScorerReplica`: the router dispatches, sheds,
    reroutes, and rolls out against it unchanged.  Crash detection is
    structural (child exit code via :meth:`poll_exit`, dropped data
    connection mid-batch → :class:`ReplicaDeadError`); :meth:`respawn`
    spawns a fresh child from the fleet's CURRENT model artifact."""

    def __init__(
        self,
        replica_id: str,
        model,
        store: ModelStore,
        request_spec: Dict[str, ShardSpec],
        buckets=None,
        max_batch: int = 256,
        min_bucket: int = 8,
        max_delay_s: float = 0.002,
        telemetry=None,
        child_env: Optional[Dict[str, str]] = None,
        spawn_timeout_s: float = 120.0,
        table_capacity_factor: int = 1,
        table_dtype: str = "f32",
        models: Optional[Dict] = None,
        reserve_rows: int = 0,
    ):
        self._models = dict(models) if models else None
        self._reserve_rows = int(reserve_rows)
        self._store = store
        self._request_spec = dict(request_spec)
        self._buckets = buckets
        self._min_bucket = min_bucket
        self._table_capacity_factor = int(table_capacity_factor)
        self._table_dtype = str(table_dtype)
        self._spawn_timeout_s = float(spawn_timeout_s)
        self.child_env = dict(child_env or {})
        self._proc: Optional[subprocess.Popen] = None
        self._replica_id = replica_id
        self._cfg_max_batch = int(max_batch)
        # Observability: where the child flushes its flight-recorder ring
        # (the supervisor's postmortem collector reads it after a kill),
        # and the observer-installed sink completed child spans forward to.
        # The sink lives on the REPLICA (not the per-child scorer) so it
        # survives respawn; _spawn hands each child scorer the bound
        # forwarder.
        self.flight_path = os.path.join(store.workdir,
                                        f"{replica_id}.flight.json")
        self.span_sink = None
        scorer = self._spawn(model, telemetry=telemetry)
        super().__init__(replica_id, scorer, max_batch=max_batch,
                         max_delay_s=max_delay_s, telemetry=telemetry)

    # -- child lifecycle ------------------------------------------------------
    def _spawn(self, model, telemetry=None) -> _RemoteScorer:
        """Spawn one child on the current shared artifact and connect —
        the ``replica:spawn`` fault site (retriable: the supervisor backs
        off and retries a failed spawn)."""
        proc, scorer = self._launch_child(
            model, self._table_capacity_factor, telemetry=telemetry,
            generation=getattr(self, "generation", 0),
        )
        self._proc = proc
        return scorer

    def build_replacement(self, model,
                          table_capacity_factor: int) -> Tuple:
        """Spawn (and warm) a REPLACEMENT child at a new capacity factor
        while the current child keeps serving — the background half of a
        zero-downtime rebuild (ISSUE 19).  Returns ``(proc, scorer)``;
        nothing on this replica changes until :meth:`cutover_to`.  The
        replacement is born into generation+1, the stamp the router's
        cutover publishes — any answer the OLD child still produces after
        cutover carries the stale generation and is fenced."""
        return self._launch_child(
            model, int(table_capacity_factor), telemetry=self.telemetry,
            generation=getattr(self, "generation", 0) + 1,
        )

    def cutover_to(self, scorer, proc=None,
                   table_capacity_factor: Optional[int] = None) -> None:
        """Atomically swap serving to a replacement child: new
        submissions flow to the new scorer immediately, the OLD batcher
        drains its queued work against the old child (zero shed), then
        the old child is retired."""
        old_proc = self._proc
        old_scorer = self.scorer
        if table_capacity_factor is not None:
            self._table_capacity_factor = int(table_capacity_factor)
        if proc is not None:
            self._proc = proc
        super().cutover_to(scorer)  # swaps batcher + drains the old one
        try:
            old_scorer.shutdown(deadline_s=5.0)
        except Exception:  # noqa: BLE001 — retirement is best-effort
            pass
        old_scorer.disconnect()
        if old_proc is not None and old_proc.poll() is None:
            old_proc.kill()
            try:
                old_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def _launch_child(self, model, table_capacity_factor: int,
                      telemetry=None, generation: int = 0) -> Tuple:
        fault_point("replica:spawn", replica=self._replica_id)
        model_paths = None
        if self._models:
            # The store's eviction horizon must cover every hosted tenant
            # plus an in-flight rollout's predecessor — N live artifacts,
            # not the single-model "current + previous" default.
            self._store.keep = max(self._store.keep, len(self._models) + 2)
            # Multi-model arena child: one shared artifact PER tenant
            # (each cached per model object — untouched tenants re-use
            # their published file across respawns).
            model_paths, version = {}, 0
            for mid, m in self._models.items():
                path, v = self._store.publish(m)
                model_paths[mid] = path
                version = max(version, v)
            artifact = next(iter(model_paths.values()))
        else:
            artifact, version = self._store.publish(model)
        ready_path = os.path.join(
            self._store.workdir,
            f"{self._replica_id}-ready-{os.getpid()}-{time.monotonic_ns()}"
            ".json",
        )
        config = {
            "replica_id": self._replica_id,
            "spec": {
                shard: {"kind": s.kind, "dim": s.dim, "nnz": s.nnz}
                for shard, s in self._request_spec.items()
            },
            "buckets": list(self._buckets) if self._buckets else None,
            "max_batch": self._cfg_max_batch,
            "min_bucket": self._min_bucket,
            "table_capacity_factor": int(table_capacity_factor),
            "table_dtype": self._table_dtype,
            "flight_path": self.flight_path,
            "models": model_paths,
            "reserve_rows": self._reserve_rows,
            "generation": int(generation),
        }
        env = dict(os.environ)
        env.update(self.child_env)
        log_path = os.path.join(self._store.workdir,
                                f"{self._replica_id}.log")
        log = open(log_path, "ab")
        try:
            # stdin is a PIPE the parent never writes: the child's
            # parent-death watchdog reads it and exits on EOF, so a crashed
            # (or respawn-racing) parent can never leak orphan children.
            proc = subprocess.Popen(
                [sys.executable, "-m", "photon_tpu.serving.replica_proc",
                 "--artifact", artifact, "--ready-file", ready_path,
                 "--config", json.dumps(config)],
                env=env, stdin=subprocess.PIPE, stdout=log, stderr=log,
            )
        finally:
            log.close()
        deadline = time.monotonic() + self._spawn_timeout_s
        ready = None
        while time.monotonic() < deadline:
            code = proc.poll()
            if code is not None:
                raise ReplicaSpawnError(
                    f"replica {self._replica_id} child exited {code} during "
                    f"startup (log: {log_path})"
                )
            if os.path.exists(ready_path):
                with open(ready_path) as f:
                    ready = json.load(f)
                break
            time.sleep(0.02)
        if ready is None:
            proc.kill()
            raise ReplicaSpawnError(
                f"replica {self._replica_id} child not ready within "
                f"{self._spawn_timeout_s:g}s (log: {log_path})"
            )
        try:
            os.unlink(ready_path)
        except OSError:
            pass
        return proc, _RemoteScorer(
            self._replica_id, model, version, self._store,
            self._request_spec, self._buckets, self._cfg_max_batch,
            self._min_bucket, port=int(ready["port"]),
            compilations=int(ready.get("compilations", 0)),
            telemetry=telemetry, span_sink=self._deliver_spans,
            table_dtype=self._table_dtype, models=self._models,
            generation=int(generation),
        )

    def _deliver_spans(self, spans: list) -> None:
        sink = self.span_sink
        if sink is not None:
            # De-skew the child's wall-clock timestamps onto the parent's
            # clock before they merge into the trace tree (the ping-RTT
            # offset estimate — ROADMAP observability edge (a)).
            offset = getattr(self.scorer, "clock_offset_s", 0.0)
            sink(shift_span_times(spans, offset))

    def poll_exit(self) -> Optional[int]:
        return None if self._proc is None else self._proc.poll()

    @property
    def child_pid(self) -> Optional[int]:
        return None if self._proc is None else self._proc.pid

    def kill_backend(self) -> None:
        """Tear the child down hard (the unhealthy-replica reaper): close
        the sockets — which unwedges a batcher thread blocked on a hung
        exchange — then SIGKILL the process."""
        self.scorer.disconnect()
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
        if self._proc is not None:
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass

    def respawn(self, model=None) -> None:
        """Real resurrection: abandon whatever the old batcher held (the
        router reroutes it), reap the dead child, spawn a FRESH child from
        the fleet's current model artifact (re-warmed at boot), and attach
        a fresh batcher.  Dispatch resumes only after ``router.revive()``
        — the canary-gated rejoin.  A multi-model replica respawns its
        whole hosted set (``self._models`` tracks per-tenant swaps)."""
        self.abandon_for_respawn()
        self.kill_backend()
        if self._models:
            # Carry per-tenant swaps that landed on the old child forward.
            old = getattr(self.scorer, "models", None)
            if old:
                self._models = dict(old)
        model = model if model is not None else self.scorer.model
        self.scorer = self._spawn(model, telemetry=self.telemetry)
        self.attach_fresh_batcher()

    def ping(self, deadline_s: float, **kw) -> dict:
        return self.scorer.ping(deadline_s, **kw)

    def pull_spans(self, deadline_s: float = 5.0) -> list:
        spans = self.scorer.pull_spans(deadline_s)
        return shift_span_times(
            spans, getattr(self.scorer, "clock_offset_s", 0.0)
        )

    def pull_stats(self, deadline_s: float = 5.0) -> dict:
        """Pull the child's scorer-level ``serving.*`` counters and merge
        the DELTA since the last pull into the parent's telemetry registry
        under the same metric names plus a ``replica`` label (ISSUE 14
        satellite / ROADMAP fleet edge (e)) — so a subprocess fleet's
        host_syncs/batches/cold_entities land in the parent's run report
        exactly like a thread replica's do.  Idempotent across repeated
        pulls (cumulative child values, delta merge); the seen-state lives
        on the per-child scorer, so a respawned child restarts the base at
        zero.  Returns the merged deltas keyed by (name, labels)."""
        scorer = self.scorer
        seen = getattr(scorer, "_stats_seen", None)
        stats = getattr(scorer, "stats", None)
        lock = getattr(scorer, "_stats_lock", None)
        if seen is None or stats is None or lock is None:
            return {}
        with lock:
            merged = {}
            for m in stats(deadline_s):
                name = m.get("name")
                labels = {
                    str(k): str(v) for k, v in (m.get("labels") or {}).items()
                }
                value = float(m.get("value", 0.0))
                key = (name, tuple(sorted(labels.items())))
                delta = value - seen.get(key, 0.0)
                if delta <= 0.0:
                    continue
                seen[key] = value
                self.telemetry.counter(
                    name, replica=self.replica_id, **labels
                ).inc(delta)
                merged[key] = delta
            return merged

    def close(self) -> None:
        # Drain FIRST: close()'s contract (queued requests still get
        # scored) needs the child alive while the batcher empties; tearing
        # the child down first would fail every drained request with
        # ReplicaDeadError.  A dead/hung child makes the drain fail fast
        # (socket errors) inside the batcher's bounded join.
        super().close()
        if self._proc is not None and self._proc.poll() is None:
            # Final stats pull AFTER the drain (so the drained batches are
            # counted) and BEFORE teardown — a fleet that never ran a
            # supervisor still gets its children's counters in the report.
            try:
                self.pull_stats(deadline_s=5.0)
            except Exception:  # noqa: BLE001 — stats are advisory
                pass
            try:
                self.scorer.shutdown()
            except (OSError, TransportError):
                pass
        self.kill_backend()


if __name__ == "__main__":
    _child_main()
