"""Fleet router: replicated scorers, queue-depth dispatch, admission control.

The PR 9/10 serving stack is one scorer behind one batcher; this module is
the fleet tier above it (1612.01437's core finding — at scale the system
overheads *around* the math dominate — is why this layer exists at all):

- :class:`ScorerReplica` — one :class:`~photon_tpu.serving.scorer.GameScorer`
  owning its own device-resident tables behind its own dedicated
  :class:`~photon_tpu.serving.batcher.RequestBatcher`.  Replicas are
  thread-backed; their device residency comes from each scorer's own mesh
  placement (``reshard_to_mesh`` under the hood), so on a multi-device
  platform every replica's tables live on ITS devices.
- :class:`FleetRouter` — queue-depth-aware dispatch across the healthy
  replicas (least projected wait, from each replica's live ``pending_rows``
  and an EWMA of its measured per-row service time), deadline-aware
  ADMISSION CONTROL in front (a request whose queue-wait projection already
  blows its deadline is shed — fast-failed — instead of queued:
  ``serving.shed{reason}``), replica-death rerouting (an in-flight request
  on a dying replica re-dispatches to a healthy one, resolving its future
  exactly once — never lost, never duplicated), and the staggered/canary
  ``swap_model`` rollout (:meth:`FleetRouter.rollout`).

Residency contract (``tools/check_host_sync.py`` guards this module): the
router never touches device data — it moves REQUESTS between host queues;
the only sanctioned host fetches are in the parity oracle
(:func:`host_score_request`), which exists precisely to score on host.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional

import numpy as np

from photon_tpu.fault.injection import (
    InjectedKillError,
    consume_hang_injection,
    fault_point,
)
from photon_tpu.fault.watchdog import complete as retire_heartbeat
from photon_tpu.fault.watchdog import heartbeat
from photon_tpu.serving.batcher import DEFAULT_MAX_DELAY_S, RequestBatcher
from photon_tpu.serving.scorer import GameScorer, ScoringRequest
from photon_tpu.telemetry.distributed import (
    SpanRecord,
    attach_span,
    attach_trace,
    current_trace,
    new_trace_id,
)


_heartbeat_nonce = itertools.count(1)


def replica_heartbeat_site(replica_id: str) -> str:
    """A watchdog heartbeat site for one replica INSTANCE: the supervisor's
    hang check and the scoring path's progress marks share it through
    ``replica.heartbeat_site``.  The process-wide nonce keeps two fleets
    in one process (both naming replicas ``r0``…) from cross-talking each
    other's hang detection through a shared site name."""
    return f"serving.replica.{replica_id}#{next(_heartbeat_nonce)}"


class RequestShedError(RuntimeError):
    """A request fast-failed by admission control (never queued, never
    scored).  ``reason`` is the shed bucket: ``deadline`` (already past
    its deadline at arrival), ``overload`` (queue-wait projection blows
    the deadline), ``queue_full`` (hard per-replica depth cap),
    ``tenant_budget`` (the request's tenant is at its per-tenant queued-
    rows budget — other tenants keep admitting), ``no_replica`` (every
    replica dead), or ``closed`` (the router is shutting down)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or f"request shed ({reason})")
        self.reason = reason


class ReplicaDeadError(RuntimeError):
    """A replica's scoring path died (injected ``serve:replica_kill`` or a
    real device failure); the router reroutes its in-flight work."""


class NoHealthyReplicaError(RuntimeError):
    """Every replica is dead; nothing can serve this request."""


class RolloutParityError(RuntimeError):
    """The canary's mirrored-traffic parity probe disagreed with the new
    model's host oracle; the rollout was aborted and the canary rolled
    back to the previous model."""


def parity_worst(got, want) -> float:
    """Worst absolute disagreement between served scores and the host
    oracle — the ONE comparison the rollout canary gate, the supervisor's
    known-answer probe, and the resurrection rejoin gate all use.
    Deliberately paranoid: a shape mismatch or any non-finite value in
    the served answer is infinite disagreement (``np.abs(nan) > tol`` is
    False — a NaN-serving canary/replica must FAIL the gate, not slide
    through it and get promoted fleet-wide)."""
    # host-sync: probe-oracle comparison — host arrays both sides (the
    # served response vs the host-scored answer).
    got = np.asarray(got, np.float64)
    want = np.asarray(want, np.float64)
    if got.shape != want.shape:
        return float("inf")
    if got.size and not np.all(np.isfinite(got)):
        return float("inf")
    delta = np.abs(got - want)
    return float(delta.max()) if delta.size else 0.0


def host_score_request(model, request: ScoringRequest) -> np.ndarray:
    """HOST-side oracle scores for one request — pure numpy, no serving
    tables involved.  The fleet uses it two ways: the canary rollout's
    parity probe (does the canary serve the NEW model's scores?) and the
    fleet bench's per-request parity acceptance (served == host ≤ 1e-3).
    Unknown entities contribute zero margin, exactly like the serving
    zero-row fallback."""
    from photon_tpu.game.data import entity_index_for
    from photon_tpu.game.model import FixedEffectModel, RandomEffectModel

    n = request.num_rows
    total = np.zeros(n, np.float64)
    if request.offset is not None:
        # host-sync: parity oracle — deliberate host-side scoring.
        total += np.asarray(request.offset, np.float64)
    for coord in model.coordinates.values():
        leaf = request.features[coord.shard_name]
        if isinstance(coord, FixedEffectModel):
            # host-sync: parity oracle — the model tables are fetched to
            # host on purpose (this is the reference scoring path).
            w = np.asarray(coord.coefficients.means, np.float64)
            if isinstance(leaf, tuple):
                ids, vals = leaf
                # host-sync: parity oracle — caller-owned request leaves.
                total += np.sum(w[np.asarray(ids)] * np.asarray(vals),
                                axis=-1)
            else:
                # host-sync: parity oracle — caller-owned request leaves.
                total += np.asarray(leaf, np.float64) @ w
        elif isinstance(coord, RandomEffectModel):
            idx = entity_index_for(
                request.entity_ids[coord.entity_column], coord.keys
            )
            # host-sync: parity oracle — same deliberate host fetch.
            table = np.asarray(coord.table, np.float64)
            safe = np.maximum(idx, 0)
            if isinstance(leaf, tuple):
                ids, vals = leaf
                # host-sync: parity oracle — caller-owned request leaves.
                m = np.sum(
                    table[safe[:, None], np.asarray(ids)] * np.asarray(vals),
                    axis=-1,
                )
            else:
                # host-sync: parity oracle — caller-owned request leaves.
                m = np.einsum(
                    "nd,nd->n", np.asarray(leaf, np.float64), table[safe]
                )
            total += np.where(idx >= 0, m, 0.0)
        else:
            raise TypeError(f"cannot score a {type(coord).__name__}")
    return total.astype(np.float32)


class _KillableScorer:
    """The replica's scoring hook: delegates to the real scorer but (1)
    declares the ``serve:replica_kill`` and ``replica:crash`` fault sites
    so CI can kill/crash a named replica's scoring path deterministically,
    (2) latches death — once a kill fired, every later batch on this
    replica raises :class:`ReplicaDeadError` (a dead replica stays dead
    until the supervisor resurrects it; the one-shot fault rule must not
    let the next batch silently succeed) — and (3) marks watchdog
    heartbeats around each batch, the progress signal the supervisor's
    hang detection reads.  An injected ``replica:hang`` WEDGES the batch
    (the thread-backed shape of a hung runtime) until the replica is
    declared dead from outside — detection has to come from the
    supervisor's probe deadline, exactly like a real hang."""

    # The wedge-simulation backstop: an unsupervised hung replica fails its
    # batch after this long instead of holding the batcher thread forever.
    HANG_CAP_S = 60.0

    def __init__(self, replica: "ScorerReplica", scorer: GameScorer):
        self._replica = replica
        self._scorer = scorer

    def __getattr__(self, name):
        return getattr(self._scorer, name)

    def _die(self, cause: str, exc: BaseException) -> None:
        self._replica.death_cause = cause
        self._replica.alive = False
        raise ReplicaDeadError(
            f"replica {self._replica.replica_id} {cause}: {exc}"
        ) from exc

    def score_batch(self, request: ScoringRequest) -> np.ndarray:
        replica = self._replica
        # ``rejoining`` lifts the dead-latch for the supervisor's rejoin
        # parity probes only: the replica is still OUT of the dispatch set
        # (alive stays False until revive), so no caller traffic can reach
        # a replica that has not passed its canary gate.
        if not replica.alive and not replica.rejoining:
            raise ReplicaDeadError(f"replica {replica.replica_id} is dead")
        heartbeat(replica.heartbeat_site)
        if consume_hang_injection(replica.replica_id):
            deadline = time.monotonic() + self.HANG_CAP_S
            while replica.alive and time.monotonic() < deadline:
                time.sleep(0.02)
            raise ReplicaDeadError(
                f"replica {replica.replica_id} wedged (injected hang)"
            )
        try:
            fault_point("serve:replica_kill", replica=replica.replica_id)
        except InjectedKillError as e:
            self._die("kill", e)
        try:
            fault_point("replica:crash", replica=replica.replica_id)
        except InjectedKillError as e:
            self._die("crash", e)
        try:
            scores = self._scorer.score_batch(request)
        except ReplicaDeadError:
            # The backend itself died mid-batch (a subprocess child's
            # connection dropped): latch it like an injected crash.
            if replica.alive:
                replica.death_cause = replica.death_cause or "crash"
                replica.alive = False
            raise
        heartbeat(replica.heartbeat_site)
        return scores


class ScorerReplica:
    """One serving replica: scorer + dedicated batcher + health/latency
    state the router dispatches on.

    Supervision surface (the fleet supervisor drives these):
    ``generation`` counts resurrections — death accounting is per
    (replica, generation) so a replica that dies, rejoins, and dies again
    is two deaths, not one latched event; ``death_cause`` labels the
    death counter (kill/crash/hang/parity/error); ``quarantined`` is the
    permanent flap verdict; :meth:`respawn` stands the serving path back
    up (re-warmed, fresh batcher) WITHOUT returning it to dispatch — only
    :meth:`FleetRouter.revive`, after the canary-gated rejoin probe, does
    that."""

    def __init__(
        self,
        replica_id: str,
        scorer: GameScorer,
        max_batch: Optional[int] = None,
        max_delay_s: float = DEFAULT_MAX_DELAY_S,
        telemetry=None,
    ):
        from photon_tpu.telemetry import NULL_SESSION

        self.replica_id = replica_id
        self.scorer = scorer
        self.alive = True
        self.heartbeat_site = replica_heartbeat_site(replica_id)
        self.generation = 0
        self.death_cause: Optional[str] = None
        self.quarantined = False
        # True between respawn and revive: the supervisor's rejoin probes
        # may score, the router still never dispatches (alive is False).
        self.rejoining = False
        self._max_batch = max_batch
        self._max_delay_s = max_delay_s
        self.telemetry = telemetry or scorer.telemetry or NULL_SESSION
        self.batcher = RequestBatcher(
            _KillableScorer(self, scorer),
            max_batch=max_batch,
            max_delay_s=max_delay_s,
            telemetry=self.telemetry,
        )
        # EWMA seconds-per-PADDED-row through this replica (queue wait
        # included), the router's projection basis.  None until the first
        # completion: a cold replica admits optimistically.
        self.row_seconds: Optional[float] = None
        self.requests_served = 0
        self.depth_peak = 0

    def pending_rows(self) -> int:
        return self.batcher.pending_rows()

    def pending_padded_rows(self) -> int:
        return self.batcher.pending_padded_rows()

    def padded_rows(self, n: int) -> int:
        """``n`` request rows at their padded bucket-ladder cost (what the
        projection charges — padded rows cost compute too)."""
        try:
            return self.scorer.padded_rows(n)
        except Exception:
            return int(n)

    def projected_wait_s(self, extra_rows: int) -> float:
        """Projected time for a new ``extra_rows``-row request to clear
        this replica: live PADDED queue depth × measured per-padded-row
        pace (bucket padding folded in — a raw-rows projection under-
        estimates the wait and over-admits near saturation)."""
        if self.row_seconds is None:
            return 0.0
        return (
            self.pending_padded_rows() + self.padded_rows(extra_rows)
        ) * self.row_seconds

    def submit(self, request: ScoringRequest) -> Future:
        try:
            return self.batcher.submit(request)
        except RuntimeError:
            # A background-rebuild cutover can swap the batcher between
            # our read and the enqueue; the fresh batcher takes the
            # request — retry once instead of surfacing a phantom death.
            return self.batcher.submit(request)

    def cutover_to(self, scorer) -> None:
        """Zero-downtime serving-path cutover (ISSUE 19): swap in a
        replacement scorer and a fresh batcher so NEW submissions flow to
        the replacement immediately, then drain the old batcher — its
        ``_KillableScorer`` holds the OLD scorer reference, so everything
        already queued completes against the old backend.  Nothing is
        shed, nothing is lost; the router's generation bump
        (:meth:`FleetRouter.cutover`) fences any answer the retired
        backend produces after this point."""
        old_batcher = self.batcher
        self.scorer = scorer
        self.batcher = RequestBatcher(
            _KillableScorer(self, scorer),
            max_batch=self._max_batch,
            max_delay_s=self._max_delay_s,
            telemetry=self.telemetry,
        )
        old_batcher.close()

    # -- supervision ---------------------------------------------------------
    def poll_exit(self) -> Optional[int]:
        """Exit code of the replica's backing process, or None while it
        runs.  Thread-backed replicas have no backing process — always
        None; the subprocess replica overrides this with the child's
        ``Popen.poll()``."""
        return None

    def abandon_pending(self, exc: BaseException) -> None:
        """Fail everything queued on (and in flight through) this replica
        so the router's done-callbacks reroute it — the supervisor's
        teardown step when it declares a replica dead."""
        self.batcher.abandon(exc)

    def abandon_for_respawn(self) -> None:
        """First step of every respawn: fail whatever the dead batcher
        still held (the router reroutes it exactly once)."""
        self.batcher.abandon(
            ReplicaDeadError(f"replica {self.replica_id} is being respawned")
        )

    def attach_fresh_batcher(self) -> None:
        """Last step of every respawn: a fresh batcher over the (re)stood
        scorer, and ``rejoining`` lifted so ONLY the supervisor's rejoin
        probes can score — shared by the thread and subprocess respawn
        paths so their rebuild semantics cannot drift."""
        self.batcher = RequestBatcher(
            _KillableScorer(self, self.scorer),
            max_batch=self._max_batch,
            max_delay_s=self._max_delay_s,
            telemetry=self.telemetry,
        )
        self.rejoining = True

    def respawn(self, model=None) -> None:
        """Stand the dead serving path back up: abandon whatever the old
        batcher still held, sync the scorer to ``model`` (the fleet's
        CURRENT model — a replica resurrected mid-rollout must come back
        on the model the fleet serves now, never the one it died on),
        re-warm the bucket ladder, and attach a fresh batcher.  For a
        thread-backed replica the runtime survived the "crash", so the
        re-warm hits the cached programs — zero recompiles; the subprocess
        replica overrides this with a real child respawn.  The replica
        stays OUT of the dispatch set until ``router.revive()`` after the
        rejoin parity probe."""
        fault_point("replica:spawn", replica=self.replica_id)
        self.abandon_for_respawn()
        if model is not None and model is not self.scorer.model:
            self.scorer.swap_model(model)
        self.scorer.warmup()
        self.attach_fresh_batcher()

    def close(self) -> None:
        retire_heartbeat(self.heartbeat_site)
        self.batcher.close()


@dataclasses.dataclass(frozen=True)
class AdmissionPolicy:
    """Admission-control knobs.

    ``max_queue_rows`` — hard per-replica depth cap (rows); a request that
    would push the least-loaded replica past it sheds ``queue_full``.
    ``tenant_queue_rows`` — per-TENANT in-flight rows budget (tenant = the
    request's model id; unrouted requests share one default-tenant
    budget).  One tenant's cold-start storm saturates its OWN budget and
    sheds ``tenant_budget`` long before the global ``max_queue_rows``
    cap, so the other tenants' traffic keeps admitting (ISSUE 18
    admission isolation).  None disables the per-tenant gate.
    ``default_deadline_s`` — deadline budget applied to requests submitted
    without one (None = no deadline, never shed on time).
    ``safety`` — multiplier on the queue-wait projection before comparing
    against the deadline (projection error margin).
    ``ewma_alpha`` — smoothing of the per-row service-time estimate."""

    max_queue_rows: Optional[int] = None
    tenant_queue_rows: Optional[int] = None
    default_deadline_s: Optional[float] = None
    safety: float = 1.0
    ewma_alpha: float = 0.25


def request_tenant(request: ScoringRequest) -> str:
    """The admission-budget tenant of one request: its scalar model id,
    or the shared default tenant for unrouted (or per-row mixed — those
    never reach admission, coalescing happens after) requests."""
    model = getattr(request, "model", None)
    return model if isinstance(model, str) else "__default__"


class _Entry:
    __slots__ = ("request", "future", "rows", "deadline_at", "attempts",
                 "dispatched_at", "pending_before", "padded",
                 "padded_before", "projected_wait", "span", "admitted_at",
                 "tenant", "budget_held")

    def __init__(self, request: ScoringRequest, deadline_at: Optional[float]):
        self.request = request
        self.future: Future = Future()
        self.rows = request.num_rows
        self.deadline_at = deadline_at
        self.attempts = 0
        self.dispatched_at = 0.0
        self.pending_before = 0
        self.padded = 0
        self.padded_before = 0
        self.projected_wait: Optional[float] = None
        # Distributed-trace root span (sampled requests only) + the
        # admission timestamp its end-to-end latency is measured from.
        self.span = None
        self.admitted_at = 0.0
        # Per-tenant budget accounting: held from admission until the
        # request reaches a TERMINAL resolution (rerouting keeps holding
        # it — the rows are still in flight somewhere).
        self.tenant = request_tenant(request)
        self.budget_held = False


class FleetRouter:
    """Queue-depth-aware dispatch + deadline admission over N replicas.

    ``submit(request, deadline_s=...)`` either returns a future (admitted;
    it resolves to the scores or to the replica failure after rerouting is
    exhausted) or raises :class:`RequestShedError` synchronously — the
    fast-fail contract: a shed request costs the caller one projection, not
    a queue slot.  ``deadline_s`` is a RELATIVE budget (seconds from
    submit); the router converts it to an absolute deadline once at
    admission.
    """

    def __init__(
        self,
        replicas: List[ScorerReplica],
        telemetry=None,
        admission: Optional[AdmissionPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from photon_tpu.telemetry import NULL_SESSION

        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        self.telemetry = telemetry or NULL_SESSION
        self.admission = admission or AdmissionPolicy()
        self.clock = clock
        # Optional FleetObserver (set by ServingFleet.observe or directly):
        # when present, sampled requests get a root span that admit/shed/
        # dispatch/reroute events land on, and every outcome feeds the
        # live-metrics window + SLO monitor.  None costs one attribute read
        # per request — the untraced hot path stays untraced.
        self.observer = None
        # SLO-driven admission tightening (ISSUE 19 satellite): the
        # observer's burn-rate guard raises this above 1.0 while an SLO
        # budget is burning — the overload projection pads out, sheds
        # start earlier, queues drain — and relaxes it back to 1.0 when
        # the alert clears.  Mutable attribute (AdmissionPolicy is
        # frozen) so the control loop can actuate without republishing
        # policy.
        self.burn_safety = 1.0
        self._lock = threading.Lock()
        # Live per-tenant in-flight row counts (tenant = model id) — the
        # per-tenant admission budget's book; entries release exactly once
        # at terminal resolution (_release_tenant is idempotent).
        self._tenant_rows: dict = {}
        self._t0 = clock()
        # Recent admitted requests, mirrored to the canary as the rollout
        # parity probe's traffic sample.
        self._mirror: deque = deque(maxlen=8)
        self._rollout_seq = itertools.count(1)
        # Death accounting is per (replica, generation): a resurrected
        # replica's NEXT death is a new event, not a latched repeat — the
        # supervisor's flap counting depends on every death being counted.
        self._dead_keys: set = set()
        self._closed = False

    # -- admission + dispatch ------------------------------------------------
    def healthy_replicas(self) -> List[ScorerReplica]:
        return [r for r in self.replicas if r.alive]

    def _shed(self, reason: str, detail: str = "", span=None,
              rows: int = 0, model: Optional[str] = None) -> None:
        self.telemetry.counter("serving.shed", reason=reason).inc()
        if self.observer is not None:
            self.observer.on_shed(reason, rows, span=span, model=model)
        raise RequestShedError(reason, detail)

    def _release_tenant(self, entry: _Entry) -> None:
        """Return an entry's rows to its tenant's budget — exactly once,
        at terminal resolution (success, terminal failure, or the
        shutdown shed); rerouting keeps the hold, the rows are still in
        flight somewhere."""
        if not entry.budget_held:
            return
        entry.budget_held = False
        with self._lock:
            left = self._tenant_rows.get(entry.tenant, 0) - entry.rows
            if left > 0:
                self._tenant_rows[entry.tenant] = left
            else:
                self._tenant_rows.pop(entry.tenant, None)

    def submit(self, request: ScoringRequest,
               deadline_s: Optional[float] = None) -> Future:
        now = self.clock()
        if self._closed:
            self._shed("closed", "router is closed")
        span = (self.observer.maybe_start_span(request)
                if self.observer is not None else None)
        rows = request.num_rows
        tenant = request_tenant(request)
        if span is not None:
            span.event("enqueue", rows=rows)
        budget = (
            deadline_s if deadline_s is not None
            else self.admission.default_deadline_s
        )
        deadline_at = None if budget is None else now + float(budget)
        healthy = self.healthy_replicas()
        if not healthy:
            self._shed("no_replica", "every replica is dead",
                       span=span, rows=rows, model=tenant)
        replica = min(
            healthy, key=lambda r: (r.projected_wait_s(rows), r.pending_rows())
        )
        cap = self.admission.max_queue_rows
        if cap is not None and replica.pending_rows() + rows > cap:
            self._shed(
                "queue_full",
                f"least-loaded replica {replica.replica_id} is at "
                f"{replica.pending_rows()} of {cap} queued rows",
                span=span, rows=rows, model=tenant,
            )
        # The per-tenant gate sits BEFORE the deadline projection: a
        # storming tenant must burn its own budget, not everyone's
        # projection headroom.
        tenant_cap = self.admission.tenant_queue_rows
        if tenant_cap is not None:
            with self._lock:
                held = self._tenant_rows.get(tenant, 0)
            if held + rows > tenant_cap:
                self._shed(
                    "tenant_budget",
                    f"tenant {tenant!r} holds {held} of {tenant_cap} "
                    "budgeted in-flight rows",
                    span=span, rows=rows, model=tenant,
                )
        if deadline_at is not None:
            if now >= deadline_at:
                self._shed("deadline", "deadline already expired at arrival",
                           span=span, rows=rows, model=tenant)
            wait = (replica.projected_wait_s(rows)
                    * self.admission.safety * self.burn_safety)
            if now + wait > deadline_at:
                self._shed(
                    "overload",
                    f"projected queue wait {wait * 1e3:.1f} ms blows the "
                    f"{(deadline_at - now) * 1e3:.1f} ms deadline budget",
                    span=span, rows=rows, model=tenant,
                )
        entry = _Entry(request, deadline_at)
        entry.span = span
        entry.admitted_at = now
        if tenant_cap is not None:
            with self._lock:
                self._tenant_rows[tenant] = (
                    self._tenant_rows.get(tenant, 0) + rows
                )
            entry.budget_held = True
        if span is not None:
            span.event("admit", replica=replica.replica_id)
        self.telemetry.counter("serving.admitted").inc()
        self._mirror.append(request)
        self._dispatch(entry, replica)
        return entry.future

    def _dispatch(self, entry: _Entry, replica: ScorerReplica) -> None:
        entry.attempts += 1
        entry.pending_before = replica.pending_rows()
        entry.padded = replica.padded_rows(entry.rows)
        entry.padded_before = replica.pending_padded_rows()
        entry.projected_wait = (
            None if replica.row_seconds is None
            else replica.projected_wait_s(entry.rows)
        )
        entry.dispatched_at = self.clock()
        t = self.telemetry
        t.counter("serving.replica_requests", replica=replica.replica_id).inc()
        t.counter("serving.replica_rows", replica=replica.replica_id).inc(
            entry.rows
        )
        depth = entry.pending_before + entry.rows
        if depth > replica.depth_peak:
            replica.depth_peak = depth
            t.gauge(
                "serving.replica_depth", replica=replica.replica_id
            ).set(depth)
        if entry.span is not None:
            entry.span.event("dispatch", replica=replica.replica_id,
                             attempt=entry.attempts)
        try:
            fut = replica.submit(entry.request)
        except BaseException as e:  # batcher closed / replica torn down
            if self._closed:
                # Shutdown race: a handler thread admitted this request
                # before close() landed and hit the closing batcher.  The
                # fleet is shutting down, not losing replicas — shed the
                # request instead of recording phantom deaths/reroutes.
                self.telemetry.counter("serving.shed", reason="closed").inc()
                if self.observer is not None:
                    self.observer.on_shed("closed", entry.rows,
                                          span=entry.span,
                                          model=entry.tenant)
                    entry.span = None
                self._release_tenant(entry)
                entry.future.set_exception(
                    RequestShedError("closed", "router closed mid-dispatch")
                )
                return
            self._replica_failed(entry, replica, e)
            return
        fut.add_done_callback(
            lambda f, e=entry, r=replica: self._on_done(e, r, f)
        )

    def _served_version(self, replica: ScorerReplica):
        version = getattr(replica.scorer, "version", None)
        if version is None:
            version = getattr(replica, "served_version", None)
        return version

    def _on_done(self, entry: _Entry, replica: ScorerReplica,
                 fut: Future) -> None:
        exc = fut.exception()
        if exc is None:
            now = self.clock()
            replica.requests_served += 1
            # Per-PADDED-row pace sample: this request's submit->resolve
            # time over the padded rows that were ahead of (and in) it — a
            # Little's-law-ish estimate that tracks the replica's live
            # drain rate in the unit the device actually pays (padding
            # included), matching the projection's cost unit.
            observed = now - entry.dispatched_at
            sample = observed / max(1, entry.padded_before + entry.padded)
            alpha = self.admission.ewma_alpha
            replica.row_seconds = (
                sample if replica.row_seconds is None
                else (1 - alpha) * replica.row_seconds + alpha * sample
            )
            if entry.projected_wait is not None:
                # The over/under-shedding premium, measurable: how far the
                # admission projection was from this request's real wait.
                self.telemetry.histogram("serving.admission_error_s").observe(
                    observed - entry.projected_wait
                )
                # Per-bucket twin: projection error is a function of where
                # the request lands on the bucket ladder (padding distorts
                # small requests most) — the evidence base for a future
                # per-bucket service model.
                try:
                    bucket = replica.scorer.bucket_for(entry.rows)
                except Exception:  # a scorer stub without a ladder
                    bucket = None
                if bucket is not None:
                    self.telemetry.histogram(
                        "serving.admission_error_s", bucket=bucket
                    ).observe(observed - entry.projected_wait)
            if entry.deadline_at is not None and now > entry.deadline_at:
                self.telemetry.counter("serving.deadline_missed").inc()
                self.telemetry.histogram("serving.deadline_overrun_s").observe(
                    now - entry.deadline_at
                )
            version = self._served_version(replica)
            if entry.span is not None:
                entry.span.attrs["rows"] = entry.rows
                entry.span.attrs["replica"] = replica.replica_id
                if version is not None:
                    entry.span.attrs["version"] = version
                entry.span.finish()
                if self.observer is not None:
                    self.observer.collector.add(entry.span)
            if self.observer is not None:
                self.observer.on_done(
                    "ok", now - entry.admitted_at, entry.rows,
                    replica.replica_id, version=version,
                    model=entry.tenant,
                )
            self._release_tenant(entry)
            entry.future.set_result(fut.result())
            return
        if isinstance(exc, ReplicaDeadError):
            self._replica_failed(entry, replica, exc)
            return
        self._finish_entry_span(entry, replica, status="error")
        self._release_tenant(entry)
        entry.future.set_exception(exc)

    def _finish_entry_span(self, entry: _Entry, replica: ScorerReplica,
                           status: str) -> None:
        if entry.span is not None:
            entry.span.finish(status=status)
            if self.observer is not None:
                self.observer.collector.add(entry.span)
        if self.observer is not None:
            self.observer.on_done(
                status, self.clock() - entry.admitted_at, entry.rows,
                replica.replica_id, version=self._served_version(replica),
                model=entry.tenant,
            )

    def _replica_failed(self, entry: _Entry, replica: ScorerReplica,
                        exc: BaseException) -> None:
        """Mark the replica dead (once) and reroute the in-flight request.
        The entry's future resolves exactly once — with the rerouted scores
        or, when no replica is left, with the failure — so a replica death
        can neither lose nor duplicate a response."""
        self._mark_dead(replica, exc)
        self.telemetry.counter(
            "serving.rerouted", replica=replica.replica_id
        ).inc()
        if entry.span is not None:
            entry.span.event("reroute", from_replica=replica.replica_id,
                             cause=str(exc)[:200])
        healthy = self.healthy_replicas()
        if healthy and entry.attempts < len(self.replicas) + 1:
            target = min(
                healthy,
                key=lambda r: (r.projected_wait_s(entry.rows),
                               r.pending_rows()),
            )
            self._dispatch(entry, target)
            return
        self._finish_entry_span(entry, replica, status="error")
        self._release_tenant(entry)
        entry.future.set_exception(
            NoHealthyReplicaError(
                f"request could not be rerouted after replica "
                f"{replica.replica_id} died: {exc}"
            )
        )

    def _mark_dead(self, replica: ScorerReplica, exc: BaseException,
                   cause: Optional[str] = None) -> None:
        with self._lock:
            key = (replica.replica_id, replica.generation)
            first = key not in self._dead_keys
            self._dead_keys.add(key)
            replica.alive = False
            if cause and not replica.death_cause:
                replica.death_cause = cause
        if first:
            self.telemetry.counter(
                "serving.replica_deaths", replica=replica.replica_id,
                cause=replica.death_cause or cause or "error",
            ).inc()
            retire_heartbeat(replica.heartbeat_site)

    def mark_unhealthy(self, replica: ScorerReplica, cause: str,
                       detail: str = "") -> None:
        """Supervisor verdict: declare a replica dead (failed health probe
        — hang, crash, parity).  Death accounting + heartbeat retire; the
        caller tears down in-flight work via ``replica.abandon_pending``
        so the router reroutes it."""
        self._mark_dead(
            replica,
            RuntimeError(
                detail or f"replica {replica.replica_id} unhealthy ({cause})"
            ),
            cause=cause,
        )

    def revive(self, replica: ScorerReplica) -> None:
        """Return a resurrected replica to the dispatch set.  The
        supervisor calls this ONLY after the canary-gated rejoin parity
        probe passed — resurrection is gated exactly like a rollout canary.
        The generation bump re-arms death accounting; the pace EWMA resets
        so the rejoined replica admits optimistically like a cold one."""
        with self._lock:
            replica.generation += 1
            replica.death_cause = None
            replica.row_seconds = None
            replica.rejoining = False
            replica.alive = True
            # Sync the backend's membership stamp (ISSUE 19): frames the
            # revived replica sends from here on carry the new generation,
            # and any answer still in flight from the OLD incarnation is
            # fenced by the exchange loop's stale-generation check.
            scorer = replica.scorer
            if hasattr(scorer, "generation"):
                scorer.generation = replica.generation
        self.telemetry.counter(
            "serving.replica_resurrections", replica=replica.replica_id
        ).inc()

    def cutover(self, replica: ScorerReplica) -> None:
        """Publish a background-rebuild cutover (ISSUE 19): bump the
        replica's membership generation (fencing the retired backend —
        a zombie that keeps answering carries the old stamp) and reset
        its pace EWMA so the rebuilt backend re-measures like a cold one.
        The serving-path swap itself happened in
        :meth:`ScorerReplica.cutover_to`; this is the router-visible
        half — together they are the atomic generation-bump cutover."""
        with self._lock:
            replica.generation += 1
            replica.row_seconds = None
            scorer = replica.scorer
            if hasattr(scorer, "generation"):
                scorer.generation = replica.generation
        self.telemetry.counter(
            "serving.replica_rebuilds", replica=replica.replica_id
        ).inc()

    def recent_requests(self) -> List[ScoringRequest]:
        """The mirror of recently admitted requests — the rollout canary's
        AND the supervisor's rejoin-probe traffic sample."""
        return list(self._mirror)

    # -- canary rollout ------------------------------------------------------
    def _mark_rollout(self, replica_id: str, phase: str) -> None:
        """Timeline breadcrumb: a monotonic sequence number per (replica,
        phase) event — the report renderer sorts these into the rollout
        timeline."""
        self.telemetry.gauge(
            "serving.rollout_step", replica=replica_id, phase=phase
        ).set(next(self._rollout_seq))
        span = getattr(self, "_rollout_span", None)
        if span is not None:
            span.event(phase, replica=replica_id)

    def rollout(
        self,
        model,
        probe_requests: Optional[List[ScoringRequest]] = None,
        parity_tol: float = 1e-3,
        probe_oracle: Optional[Callable] = None,
        probe_timeout_s: float = 30.0,
        model_id: Optional[str] = None,
    ) -> None:
        """Staggered/canary ``swap_model`` across the fleet: ONE replica
        swaps first, a parity probe replays mirrored traffic through it
        against the new model's host oracle, and only then do the remaining
        replicas swap — so a bad artifact is caught while (n-1)/n of the
        fleet still serves the old model.  Each replica's swap is atomic
        (the scorer's one-assignment publication), so no response is ever a
        mix of two models; during the stagger, different replicas serve
        different models — each response wholly one of them.

        Probe traffic: ``probe_requests`` if given, else the router's
        mirror of recently admitted requests.  Probe responses never reach
        callers.  A parity failure rolls the canary back and raises
        :class:`RolloutParityError` — and any OTHER probe failure (a probe
        timeout, an oracle error) rolls it back the same way before
        propagating; a canary that DIES mid-probe is marked dead and the
        rollout restarts on the next healthy replica (the
        mid-rollout-kill path).

        ``model_id`` targets ONE tenant slice of a multi-model arena: the
        swap replaces only that model's rows, probes are stamped with the
        tenant id so the canary scores them against the swapped slice, and
        every other hosted model keeps serving untouched."""
        oracle = probe_oracle or (
            lambda req: host_score_request(model, req)
        )
        probes = list(probe_requests) if probe_requests else list(self._mirror)
        if model_id is not None:
            # Stamp BEFORE span attach: replace() builds a new frozen
            # request, which would drop spans attached to the old one.
            probes = [dataclasses.replace(req, model=model_id)
                      for req in probes]
        if not probes:
            raise ValueError(
                "rollout has no traffic to probe the canary with: pass "
                "probe_requests or roll out under live traffic"
            )
        # One rollout = one span: the canary/probe/promote timeline becomes
        # a trace, parented under the thread's ambient context when there
        # is one (the online refresh's publish span) so refresh→canary→swap
        # reads as one linked trace.  Probe requests carry its context, so
        # subprocess canaries link their scoring hops under it too.
        rspan = None
        probe_spans = []
        if self.observer is not None:
            ctx = current_trace()
            if ctx is not None:
                rspan = SpanRecord(ctx.trace_id, "serving.rollout",
                                   self.observer.process,
                                   parent_id=ctx.span_id)
            else:
                rspan = SpanRecord(new_trace_id(), "serving.rollout",
                                   self.observer.process)
            # Probe submissions bypass admission (canary.submit goes
            # straight to the replica), so the request path never opens a
            # span for them — open one per probe here so the canary's
            # parity replay shows up as serving.request hops under the
            # rollout span instead of vanishing from the trace.
            for req in probes:
                pspan = SpanRecord(rspan.trace_id, "serving.request",
                                   self.observer.process,
                                   parent_id=rspan.span_id)
                pspan.attrs["probe"] = True
                attach_trace(req, pspan.context())
                attach_span(req, pspan)
                probe_spans.append(pspan)
        self._rollout_span = rspan
        try:
            self._run_rollout(model, oracle, probes, parity_tol,
                              probe_timeout_s, model_id)
            if rspan is not None:
                rspan.finish()
        except BaseException:
            if rspan is not None:
                rspan.finish(status="error")
            raise
        finally:
            self._rollout_span = None
            if rspan is not None:
                for pspan in probe_spans:
                    self.observer.collector.add(pspan.finish())
                self.observer.collector.add(rspan)

    def _run_rollout(self, model, oracle, probes, parity_tol,
                     probe_timeout_s, model_id=None) -> None:
        def _swap(scorer, new_model):
            if model_id is None:
                scorer.swap_model(new_model)
            else:
                scorer.swap_model(new_model, model_id=model_id)

        while True:
            healthy = self.healthy_replicas()
            if not healthy:
                raise NoHealthyReplicaError(
                    "rollout aborted: every replica is dead"
                )
            canary = healthy[0]
            self._mark_rollout(canary.replica_id, "canary")
            if model_id is not None and hasattr(canary.scorer, "model_for"):
                old_model = canary.scorer.model_for(model_id)
            else:
                old_model = canary.scorer.model
            _swap(canary.scorer, model)
            # Per-codec parity histogram (ISSUE 17): every canary probe's
            # worst |delta| lands labeled with the served storage tier, so
            # the measured bound per dtype is an observable distribution,
            # not just a pass/fail gate.
            dtype = getattr(canary.scorer, "table_dtype", "f32")
            labels = {"dtype": dtype}
            if model_id is not None:
                labels["model"] = model_id
            parity_hist = self.telemetry.histogram(
                "serving.rollout_parity", **labels
            )
            try:
                futs = [canary.submit(req) for req in probes]
                for req, fut in zip(probes, futs):
                    got = fut.result(timeout=probe_timeout_s)
                    worst = parity_worst(got, oracle(req))
                    parity_hist.observe(worst)
                    if worst > parity_tol:
                        raise RolloutParityError(
                            f"canary {canary.replica_id} parity probe "
                            f"disagreed with the new model's host oracle "
                            f"(max |delta| {worst:.2e} > {parity_tol:g})"
                        )
            except ReplicaDeadError as e:
                # Mid-rollout kill: the canary died while probing.  It is
                # already marked dead (the proxy latched); restart the
                # rollout on the next healthy replica.
                self._mark_dead(canary, e)
                self._mark_rollout(canary.replica_id, "died")
                continue
            except BaseException:
                # ANY other probe failure — parity disagreement, a probe
                # future timeout, an oracle error — must not leave the
                # canary serving a model the rest of the fleet does not:
                # roll it back before surfacing the failure.
                if canary.alive:
                    _swap(canary.scorer, old_model)
                self._mark_rollout(canary.replica_id, "rolled_back")
                raise
            self._mark_rollout(canary.replica_id, "probe_ok")
            for replica in self.replicas:
                if replica is canary or not replica.alive:
                    continue
                try:
                    _swap(replica.scorer, model)
                    self._mark_rollout(replica.replica_id, "promoted")
                except Exception as e:
                    # The raw scorer's swap fails with its own error (a
                    # refusal or device failure), never ReplicaDeadError.
                    # A replica that cannot take the promoted model must
                    # not keep serving the old one: mark it dead so its
                    # in-flight work reroutes to promoted replicas.
                    self._mark_dead(replica, e)
                    self._mark_rollout(replica.replica_id, "died")
            self.telemetry.counter("serving.rollouts").inc()
            return

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        wall = max(self.clock() - self._t0, 1e-9)
        for replica in self.replicas:
            self.telemetry.gauge(
                "serving.replica_qps", replica=replica.replica_id
            ).set(replica.requests_served / wall)
            replica.close()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
