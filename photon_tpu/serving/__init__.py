"""Online GAME scoring service: device-resident tables, bucketed batching.

Everything before this package is batch — ``score_game`` loads a model per
invocation and scores one dataset.  This package is the serving layer the
ROADMAP's north star ("heavy traffic from millions of users") calls for,
shaped after Snap ML's hierarchical host/accelerator pipelining of GLM
serving (PAPERS.md, 1803.06333) and DrJAX's keep-everything-in-jit idiom
(PAPERS.md, 2403.07128):

- :class:`~photon_tpu.serving.scorer.GameScorer` — loads a saved GAME model
  ONCE into device-resident tables (fixed-effect weight vectors plus one
  sharded ``[entities + 1, dim]`` gather table per random coordinate, the
  trailing row all-zero for unknown entities) and keeps ONE pre-compiled
  scoring program alive per (bucket shape × coordinate set), serving request
  micro-batches with donated I/O buffers.  After :meth:`warmup`, arrival
  patterns can NEVER recompile: batches are padded to a small power-of-two
  bucket ladder and each bucket's program is AOT-compiled
  (``jit(...).lower(...).compile()`` — a shape outside the compiled set is
  an error, not a silent recompile).
- :class:`~photon_tpu.serving.batcher.RequestBatcher` — an async batcher
  thread (the ``io_pool`` / ``AsyncPublisher`` depth-1 lineage from PR 5)
  coalescing concurrent requests under a max-delay/max-batch policy.

The batch scoring driver (``drivers/score_game``, non-streamed) routes
through the same :class:`GameScorer` gather-table build, so the online and
batch paths cannot drift; ``python -m photon_tpu.drivers.serve_game`` is the
in-process request loop, and ``bench.py --mode serving`` measures p50/p99
latency + QPS against the per-request host-scoring baseline.
"""

from photon_tpu.serving.batcher import (  # noqa: F401
    RequestBatcher,
    run_closed_loop,
)
from photon_tpu.serving.scorer import (  # noqa: F401
    GameScorer,
    ScoringRequest,
    ShardSpec,
    build_requests,
    concat_requests,
    request_from_dataset,
    request_spec_for_dataset,
    request_spec_for_model,
    request_windows,
    slice_request,
)
