"""Online GAME scoring service: device-resident tables, bucketed batching.

Everything before this package is batch — ``score_game`` loads a model per
invocation and scores one dataset.  This package is the serving layer the
ROADMAP's north star ("heavy traffic from millions of users") calls for,
shaped after Snap ML's hierarchical host/accelerator pipelining of GLM
serving (PAPERS.md, 1803.06333) and DrJAX's keep-everything-in-jit idiom
(PAPERS.md, 2403.07128):

- :class:`~photon_tpu.serving.scorer.GameScorer` — loads a saved GAME model
  ONCE into device-resident tables (fixed-effect weight vectors plus one
  sharded ``[entities + 1, dim]`` gather table per random coordinate, the
  trailing row all-zero for unknown entities) and keeps ONE pre-compiled
  scoring program alive per (bucket shape × coordinate set), serving request
  micro-batches with donated I/O buffers.  After :meth:`warmup`, arrival
  patterns can NEVER recompile: batches are padded to a small power-of-two
  bucket ladder and each bucket's program is AOT-compiled
  (``jit(...).lower(...).compile()`` — a shape outside the compiled set is
  an error, not a silent recompile).
- :class:`~photon_tpu.serving.batcher.RequestBatcher` — an async batcher
  thread (the ``io_pool`` / ``AsyncPublisher`` depth-1 lineage from PR 5)
  coalescing concurrent requests under a max-delay/max-batch policy.
- The FLEET tier (ISSUE 12): :class:`~photon_tpu.serving.fleet.ServingFleet`
  assembles N scorer replicas (each owning device-resident tables on its
  own sub-mesh) behind the queue-depth-aware, deadline-admission
  :class:`~photon_tpu.serving.router.FleetRouter`, optionally fronted by
  the stdlib socket ingest (:mod:`photon_tpu.serving.transport`), with
  replayable generated traffic (:mod:`photon_tpu.serving.traffic`:
  power-law popularity, diurnal ramps, cold-start storms) and canary
  ``swap_model`` rollout with mirrored-traffic parity probes.
- The SELF-HEALING tier (ISSUE 13): ``ServingFleet(backend="subprocess")``
  runs each replica as a child process with its own Python/jax runtime
  (:mod:`photon_tpu.serving.replica_proc` — shared wire-format model
  artifact, frame protocol over loopback, per-child device deal), and
  :class:`~photon_tpu.serving.supervisor.ReplicaSupervisor`
  (``fleet.supervise()``) closes the availability loop: health probes
  (exit codes, heartbeat hangs, ping deadlines, known-answer scores vs
  the host oracle), backed-off resurrection whose rejoin is gated by
  mirrored-traffic parity probes against the CURRENT model, and
  permanent quarantine for flapping replicas.
- The OBSERVABILITY plane (ISSUE 16): ``fleet.observe()`` attaches a
  :class:`~photon_tpu.serving.observe.FleetObserver` — request-scoped
  distributed tracing over the existing frame protocol (trace ids ride
  request headers, child replicas stream completed spans back over the
  open control connection, the parent merges one cross-process trace
  tree with a critical-path breakdown), a live metrics plane
  (per-replica mergeable histograms aggregated to fleet QPS/p50/p99/
  shed-rate per model version, served over a stdlib-HTTP Prometheus
  endpoint and the ``python -m photon_tpu.telemetry.live`` console),
  declarative SLO burn-rate alerting, and a crash flight recorder whose
  per-replica ring the supervisor collects on death/quarantine.

The batch scoring driver (``drivers/score_game``, non-streamed) routes
through the same :class:`GameScorer` gather-table build, so the online and
batch paths cannot drift; ``python -m photon_tpu.drivers.serve_game`` is the
in-process request loop, and ``bench.py --mode serving`` measures p50/p99
latency + QPS against the per-request host-scoring baseline.
"""

from photon_tpu.serving.batcher import (  # noqa: F401
    RequestBatcher,
    run_closed_loop,
)
from photon_tpu.serving.fleet import ServingFleet  # noqa: F401
from photon_tpu.serving.observe import (  # noqa: F401
    DEFAULT_SLOS,
    FleetObserver,
    ObservePolicy,
    Slo,
    SloMonitor,
)
from photon_tpu.serving.replica_proc import (  # noqa: F401
    ModelStore,
    ReplicaSpawnError,
    SubprocessReplica,
)
from photon_tpu.serving.supervisor import (  # noqa: F401
    RejoinParityError,
    ReplicaSupervisor,
    SupervisorPolicy,
    probe_request_for,
)
from photon_tpu.serving.router import (  # noqa: F401
    AdmissionPolicy,
    FleetRouter,
    NoHealthyReplicaError,
    ReplicaDeadError,
    RequestShedError,
    RolloutParityError,
    ScorerReplica,
    host_score_request,
)
from photon_tpu.serving.scorer import (  # noqa: F401
    GameScorer,
    ScoringRequest,
    ShardSpec,
    build_requests,
    concat_requests,
    request_from_dataset,
    request_spec_for_dataset,
    request_spec_for_model,
    request_windows,
    slice_request,
)
from photon_tpu.serving.traffic import (  # noqa: F401
    Outcome,
    Traffic,
    TrafficSpec,
    generate_traffic,
    replay_open_loop,
    run_closed_loop_outcomes,
)
from photon_tpu.serving.transport import (  # noqa: F401
    AsyncScoringClient,
    ScoringClient,
    ScoringServer,
    TransportError,
)
