"""Deterministic network fault injection for the subprocess fleet.

The serving transport (``serving/transport.py``) moves length-prefixed
frames over loopback TCP: one ``sock.sendall`` per outbound frame, one
``_read_exact`` pair per inbound frame.  That framing makes the wire a
clean injection seam — this module wraps a connected socket in a shim
that sees WHOLE frames and applies a seeded :class:`NetFaultPlan` to
them, so every partition/duplicate/reorder/slow-link scenario the fleet
must survive is a reproducible test cell, never a flake (ISSUE 19).

Primitives (per :class:`LinkRule`, matched to links by fnmatch pattern,
per direction):

- ``drop_p`` / ``dup_p`` / ``reorder_p`` — seeded per-frame drop,
  duplicate, and adjacent-swap reordering.
- ``delay_s`` — fixed per-frame latency (slow-replica mode).
- ``rate_bytes_per_s`` — per-direction byte-rate throttle.
- ``partitions`` — scheduled ``(start_s, end_s)`` windows (relative to
  :meth:`NetFaultPlan.activate`) during which frames are black-holed;
  ``end_s=None`` is a permanent partition (frozen-replica mode).  A
  rule with ``direction="send"`` or ``"recv"`` makes it one-way.
- ``skew_s`` — rewrites the child's self-reported clock fields
  (``child_time`` and span timestamps) in inbound frames, simulating a
  replica whose wall clock disagrees with the parent's.

Faults are injected at the PARENT's socket (``_RemoteScorer._connect``
wraps via :func:`maybe_shim`), so "send" means parent->child and "recv"
means child->parent.  Drops black-hole frames without disturbing the
TCP connection itself — exactly how a mid-path partition looks to the
endpoints — which is what forces the lease/seq/generation machinery to
do the real work: a dropped frame is silence, not an error.

Determinism: every random decision draws from a per-(link, direction)
``random.Random`` seeded from ``plan.seed``, and partition windows are
anchored to the plan's activation instant — replaying the same plan
against the same traffic yields the same fault sequence.  (This module
deliberately does NOT use ``fault/injection.py``'s consume-one
``FaultPlan``: frame faults are probabilistic streams over an open-ended
frame sequence, not one-shot site triggers, and the two grammars would
fight over a name — hence ``NetFaultPlan``.)
"""

from __future__ import annotations

import fnmatch
import random
import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LinkRule",
    "NetFaultPlan",
    "FrameShimSocket",
    "set_net_plan",
    "active_net_plan",
    "maybe_shim",
    "partition",
]


@dataclass(frozen=True)
class LinkRule:
    """One fault recipe, applied to every frame on the links it matches.

    ``link`` is an fnmatch pattern over the shim's link names — the
    parent names its sockets ``"<replica_id>:data"`` and
    ``"<replica_id>:ctrl"``, so ``"r0:*"`` faults one replica's both
    channels and ``"*"`` faults the whole fleet.
    """

    link: str = "*"
    direction: str = "both"  # "send" (parent->child), "recv", or "both"
    drop_p: float = 0.0
    dup_p: float = 0.0
    reorder_p: float = 0.0
    delay_s: float = 0.0
    rate_bytes_per_s: float = 0.0
    #: ((start_s, end_s_or_None), ...) black-hole windows relative to
    #: plan activation; end None = never heals (frozen replica).
    partitions: Tuple[Tuple[float, Optional[float]], ...] = ()
    skew_s: float = 0.0

    def matches(self, link: str, direction: str) -> bool:
        return (
            self.direction in ("both", direction)
            and fnmatch.fnmatch(link, self.link)
        )


def partition(
    link: str,
    start_s: float,
    duration_s: Optional[float] = None,
    direction: str = "both",
) -> LinkRule:
    """Convenience: a pure partition rule healing after ``duration_s``
    (``None`` = never — the frozen-replica cell)."""
    end = None if duration_s is None else float(start_s) + float(duration_s)
    return LinkRule(
        link=link,
        direction=direction,
        partitions=((float(start_s), end),),
    )


class NetFaultPlan:
    """A seeded set of :class:`LinkRule`\\ s plus the bookkeeping that
    makes a chaos cell assertable: per-event counters keyed
    ``"{event}:{link}:{direction}"`` count every injected fault."""

    def __init__(self, rules: List[LinkRule], seed: int = 0):
        self.rules = tuple(rules)
        self.seed = int(seed)
        self.counters: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._rngs: Dict[Tuple[str, str], random.Random] = {}
        self._epoch: Optional[float] = None

    # -- lifecycle -----------------------------------------------------------
    def activate(self) -> "NetFaultPlan":
        """Anchor partition windows to NOW (idempotent)."""
        if self._epoch is None:
            self._epoch = time.monotonic()
        return self

    def elapsed_s(self) -> float:
        if self._epoch is None:
            self.activate()
        return time.monotonic() - self._epoch

    # -- matching / determinism ----------------------------------------------
    def applies(self, link: str) -> bool:
        return any(
            fnmatch.fnmatch(link, r.link) for r in self.rules
        )

    def rules_for(self, link: str, direction: str) -> List[LinkRule]:
        return [r for r in self.rules if r.matches(link, direction)]

    def rng(self, link: str, direction: str) -> random.Random:
        with self._lock:
            key = (link, direction)
            r = self._rngs.get(key)
            if r is None:
                r = random.Random(
                    self.seed ^ zlib.crc32(f"{link}:{direction}".encode())
                )
                self._rngs[key] = r
            return r

    def partition_active(self, rule: LinkRule) -> bool:
        if not rule.partitions:
            return False
        t = self.elapsed_s()
        for start, end in rule.partitions:
            if t >= start and (end is None or t < end):
                return True
        return False

    # -- counters ------------------------------------------------------------
    def count(self, event: str, link: str, direction: str) -> None:
        with self._lock:
            key = f"{event}:{link}:{direction}"
            self.counters[key] = self.counters.get(key, 0) + 1

    def total(self, event: str) -> int:
        with self._lock:
            prefix = event + ":"
            return sum(
                v for k, v in self.counters.items() if k.startswith(prefix)
            )


# Module-level installed plan: the parent process installs a plan before
# connecting (or reconnecting) to its children; _RemoteScorer._connect
# routes every new socket through maybe_shim so reconnects inside a chaos
# cell stay faulted too.
_PLAN: Optional[NetFaultPlan] = None
_PLAN_LOCK = threading.Lock()


def set_net_plan(plan: Optional[NetFaultPlan]) -> None:
    """Install (and activate) ``plan`` for every subsequently wrapped
    socket; ``None`` clears it.  Already-wrapped sockets keep their plan
    — clear BEFORE building a fleet for a clean run."""
    global _PLAN
    with _PLAN_LOCK:
        if plan is not None:
            plan.activate()
        _PLAN = plan


def active_net_plan() -> Optional[NetFaultPlan]:
    return _PLAN


def maybe_shim(sock: socket.socket, link: str):
    """Wrap ``sock`` in a :class:`FrameShimSocket` when the installed
    plan has a rule matching ``link``; otherwise return it untouched
    (zero overhead on the clean path)."""
    plan = _PLAN
    if plan is None or not plan.applies(link):
        return sock
    return FrameShimSocket(sock, link, plan)


def _rewrite_skew(frame: bytes, skew_s: float) -> bytes:
    """Shift the child's self-reported clock fields in one wire frame by
    ``skew_s``: ``child_time`` on pong frames, span ``start`` and event
    ``t`` stamps on score/spans frames.  Unparseable frames pass through
    untouched."""
    from photon_tpu.serving.transport import _pack, _unpack

    try:
        header, arrays = _unpack(frame[4:])
    except Exception:
        return frame
    touched = False
    if "child_time" in header:
        header["child_time"] = float(header["child_time"]) + skew_s
        touched = True
    for span in header.get("spans") or ():
        if "start" in span:
            span["start"] = float(span["start"]) + skew_s
            touched = True
        for ev in span.get("events") or ():
            if "t" in ev:
                ev["t"] = float(ev["t"]) + skew_s
                touched = True
    if not touched:
        return frame
    header["_arrays"] = [
        (m["slot"], m["name"], arr)
        for m, arr in zip(header.pop("arrays", []), arrays)
    ]
    payload = _pack(header)
    return struct.pack("!I", len(payload)) + payload


class FrameShimSocket:
    """Socket wrapper that reassembles the transport's length-prefixed
    frames and applies the plan's matching rules per frame.

    Send side: one ``sendall`` is one frame (the transport guarantees
    it), so drop/partition silently swallow the call — the sender sees
    success, exactly like a mid-path loss.  Recv side: wire bytes are
    buffered until a whole frame is available, faults are applied to the
    frame, and surviving bytes are replayed to the transport's
    ``recv(n)`` loop.  ``socket.timeout`` mid-frame is safe — partial
    wire bytes persist across calls.  EOF propagates as ``b""``.
    """

    def __init__(self, sock: socket.socket, link: str, plan: NetFaultPlan):
        self._sock = sock
        self.link = link
        self.plan = plan
        self._wire = bytearray()   # raw bytes off the wire, pre-framing
        self._rbuf = bytearray()   # post-fault frame bytes owed to recv()
        self._held_send: Optional[bytes] = None

    # -- passthrough ---------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self._sock, name)

    def settimeout(self, t):
        self._sock.settimeout(t)

    def gettimeout(self):
        return self._sock.gettimeout()

    def close(self):
        self._held_send = None
        self._sock.close()

    # -- send path -----------------------------------------------------------
    def sendall(self, data) -> None:
        rules = self.plan.rules_for(self.link, "send")
        if not rules:
            self._sock.sendall(data)
            return
        for rule in rules:
            if self.plan.partition_active(rule):
                self.plan.count("partitioned", self.link, "send")
                return  # black-holed: sender sees success
            if rule.drop_p and self.plan.rng(
                self.link, "send"
            ).random() < rule.drop_p:
                self.plan.count("dropped", self.link, "send")
                return
        self._sleep_for(rules, len(data), "send")
        held, self._held_send = self._held_send, None
        if held is None and any(
            r.reorder_p
            and self.plan.rng(self.link, "send").random() < r.reorder_p
            for r in rules
        ):
            # Hold this frame; it ships AFTER the next one (adjacent swap).
            self._held_send = bytes(data)
            self.plan.count("reordered", self.link, "send")
            return
        self._sock.sendall(data)
        if held is not None:
            self._sock.sendall(held)
        for rule in rules:
            if rule.dup_p and self.plan.rng(
                self.link, "send"
            ).random() < rule.dup_p:
                self.plan.count("duplicated", self.link, "send")
                self._sock.sendall(data)
                break

    def _sleep_for(self, rules, nbytes: int, direction: str) -> None:
        delay = 0.0
        for rule in rules:
            delay += rule.delay_s
            if rule.rate_bytes_per_s:
                delay += nbytes / rule.rate_bytes_per_s
                self.plan.count("throttled", self.link, direction)
        if delay > 0:
            time.sleep(delay)

    # -- recv path -----------------------------------------------------------
    def recv(self, n: int) -> bytes:
        while not self._rbuf:
            frame = self._next_wire_frame()
            if frame is None:
                return b""
            for out in self._inbound(frame):
                self._rbuf += out
        k = min(int(n), len(self._rbuf))
        out = bytes(self._rbuf[:k])
        del self._rbuf[:k]
        return out

    def _next_wire_frame(self) -> Optional[bytes]:
        """One whole wire frame (length prefix included), or None on EOF.
        Raises socket.timeout with partial bytes preserved."""
        while True:
            if len(self._wire) >= 4:
                (n,) = struct.unpack("!I", bytes(self._wire[:4]))
                if len(self._wire) >= 4 + n:
                    frame = bytes(self._wire[: 4 + n])
                    del self._wire[: 4 + n]
                    return frame
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                return None
            self._wire += chunk

    def _inbound(self, frame: bytes) -> List[bytes]:
        rules = self.plan.rules_for(self.link, "recv")
        if not rules:
            return [frame]
        for rule in rules:
            if self.plan.partition_active(rule):
                self.plan.count("partitioned", self.link, "recv")
                return []
            if rule.drop_p and self.plan.rng(
                self.link, "recv"
            ).random() < rule.drop_p:
                self.plan.count("dropped", self.link, "recv")
                return []
        self._sleep_for(rules, len(frame), "recv")
        skew = sum(r.skew_s for r in rules)
        if skew:
            frame = _rewrite_skew(frame, skew)
            self.plan.count("skewed", self.link, "recv")
        out = [frame]
        for rule in rules:
            if rule.dup_p and self.plan.rng(
                self.link, "recv"
            ).random() < rule.dup_p:
                self.plan.count("duplicated", self.link, "recv")
                out.append(frame)
                break
        if any(
            r.reorder_p
            and self.plan.rng(self.link, "recv").random() < r.reorder_p
            for r in rules
        ):
            # Adjacent swap: deliver the NEXT wire frame first (raw — the
            # swap itself is the fault under test), then this one.
            nxt = None
            old = self._sock.gettimeout()
            try:
                self._sock.settimeout(min(old, 0.2) if old else 0.2)
                nxt = self._next_wire_frame()
            except socket.timeout:
                nxt = None
            finally:
                try:
                    self._sock.settimeout(old)
                except OSError:
                    pass
            if nxt is not None:
                self.plan.count("reordered", self.link, "recv")
                out = [nxt] + out
        return out
