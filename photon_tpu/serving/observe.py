"""Fleet observability plane: tracing, live metrics, SLO burn rates, dumps.

The fleet-facing half of the observability layer (the wire-level half is
:mod:`photon_tpu.telemetry.distributed`).  One :class:`FleetObserver`
attaches to a :class:`~photon_tpu.serving.fleet.ServingFleet` and owns:

- **Request tracing** — it decides sampling, originates the root span the
  router stamps admit/shed/dispatch/score events onto, and is the merge
  point (:class:`~photon_tpu.telemetry.distributed.TraceCollector`) where
  child-replica spans shipped back over the data/control connections land
  as one cross-process trace tree per request.
- **The live metrics plane** — a sliding window of per-request outcomes
  (status, latency, rows, replica, model version) aggregated into
  fleet-level QPS/p50/p99/shed-rate per model version, merged with the
  children's shipped histogram snapshots, exposed via a stdlib-HTTP
  Prometheus endpoint (``/metrics``) and a JSON snapshot (``/fleet.json``
  — what ``python -m photon_tpu.telemetry.live`` renders), replacing
  "wait for run_report.json" with during-run visibility.
- **SLO burn-rate monitoring** — declarative :class:`Slo` objectives
  (p99 latency, shed fraction, canary parity) evaluated over fast/slow
  sliding windows; an alert fires only when BOTH windows burn error
  budget past their thresholds (the multiwindow rule: the fast window
  catches the cliff, the slow window filters the blip).  Observe-only by
  default — alerts land in telemetry and in subscriber callbacks; nothing
  here touches dispatch.
- **Flight-recorder collection** — on a replica death/quarantine the
  supervisor hands the victim here; the observer persists the child's
  on-disk flight ring (written by the child BEFORE each traced batch, so
  a SIGKILL still leaves its final seconds) plus the parent-side event
  ring next to the run report, and adopts any unfinished child spans as
  terminal "lost" stubs so the trace stays whole (no orphan hops).

Residency contract (``tools/check_host_sync.py`` guards this module): the
observability plane is pure host-side bookkeeping over plain dicts — it
must never fetch device data (an observer that syncs would BE the latency
it exists to measure).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

from photon_tpu.telemetry.distributed import (
    FlightRecorder,
    MergeableHistogram,
    SpanRecord,
    TraceCollector,
    TraceContext,
    TraceSampler,
    attach_span,
    attach_trace,
    current_trace,
    new_trace_id,
    span_of,
    trace_of,
)

__all__ = [
    "ObservePolicy",
    "Slo",
    "SloMonitor",
    "FleetObserver",
    "MetricsPlane",
]


@dataclasses.dataclass(frozen=True)
class ObservePolicy:
    """Observer knobs.

    ``sample_rate`` — fraction of requests traced (deterministic — see
    :class:`~photon_tpu.telemetry.distributed.TraceSampler`); 1.0 traces
    everything (tests), a production fleet runs 0.01–0.1.
    ``trace_capacity`` — most-recent traces kept in the collector.
    ``flight_capacity`` — ring size of the per-replica flight recorders.
    ``window_s`` — the live plane's sliding window (QPS/p50/p99 horizon).
    ``poll_interval_s`` — child span/snapshot pull cadence.
    ``http_port`` — bind the live HTTP plane here (None = no server;
    0 = ephemeral port, read it back from ``observer.http_address``).
    ``admission_guard`` — close the SLO→admission loop (ISSUE 19
    satellite / ROADMAP observability edge (b)): while any burn alert is
    active, the router's overload projection is multiplied by
    ``admission_tighten`` (sheds start earlier, queues drain); when
    every alert clears, admission relaxes back to 1.0.  Opt-in: the
    guard actuates the serving path, so attaching it is a deliberate
    control-loop decision, not a side effect of observing."""

    sample_rate: float = 1.0
    trace_capacity: int = 512
    flight_capacity: int = 128
    window_s: float = 30.0
    poll_interval_s: float = 0.5
    http_port: Optional[int] = None
    http_host: str = "127.0.0.1"
    admission_guard: bool = False
    admission_tighten: float = 4.0


@dataclasses.dataclass(frozen=True)
class Slo:
    """One declarative objective evaluated over sliding windows.

    ``kind`` picks the bad-event predicate: ``latency`` (a request slower
    than ``objective`` seconds is bad), ``shed_fraction`` (a shed request
    is bad; ``objective`` is unused for the predicate), ``parity`` (a
    probe whose worst disagreement exceeds ``objective`` is bad).
    ``budget`` is the allowed bad fraction; burn rate = bad_fraction /
    budget, so burn 1.0 spends budget exactly on schedule.  An alert
    fires when the FAST window burns past ``fast_burn`` AND the SLOW
    window past ``slow_burn`` — the standard multiwindow rule."""

    name: str
    kind: str  # "latency" | "shed_fraction" | "parity"
    objective: float
    budget: float = 0.01
    fast_window_s: float = 5.0
    slow_window_s: float = 60.0
    fast_burn: float = 14.0
    slow_burn: float = 2.0


DEFAULT_SLOS = (
    Slo("p99_latency", "latency", objective=1.0, budget=0.01),
    Slo("shed_fraction", "shed_fraction", objective=0.0, budget=0.05),
    Slo("canary_parity", "parity", objective=1e-3, budget=0.01),
)


class SloMonitor:
    """Sliding-window burn-rate evaluation over declarative SLOs.

    ``observe_request``/``observe_parity`` feed events; ``evaluate()``
    computes per-window burn rates, records them as telemetry gauges
    (``slo.burn_rate{slo,window}``), counts alerts (``slo.alerts{slo}``),
    and notifies subscribers.  Observe-only: subscribers decide what to do
    (the canary gate may refuse a promotion; the default is nothing)."""

    def __init__(self, slos: Sequence[Slo] = DEFAULT_SLOS, telemetry=None,
                 clock: Callable[[], float] = time.monotonic):
        from photon_tpu.telemetry import NULL_SESSION

        self.slos = list(slos)
        self.telemetry = telemetry or NULL_SESSION
        self.clock = clock
        self._lock = threading.Lock()
        # Per slo: deque of (t, bad) trimmed to the slow window.
        self._events = {slo.name: deque() for slo in self.slos}
        self._subscribers: List[Callable] = []
        self.alerts: List[dict] = []
        self._alerting: set = set()  # slo names currently in alert

    def subscribe(self, callback: Callable[[dict], None]) -> None:
        self._subscribers.append(callback)

    # -- feeds ---------------------------------------------------------------
    def observe_request(self, status: str, latency_s: Optional[float]) -> None:
        now = self.clock()
        with self._lock:
            for slo in self.slos:
                if slo.kind == "latency":
                    if status == "ok" and latency_s is not None:
                        self._events[slo.name].append(
                            (now, latency_s > slo.objective)
                        )
                elif slo.kind == "shed_fraction":
                    self._events[slo.name].append((now, status == "shed"))

    def observe_parity(self, worst: float) -> None:
        now = self.clock()
        with self._lock:
            for slo in self.slos:
                if slo.kind == "parity":
                    self._events[slo.name].append((now, worst > slo.objective))

    # -- evaluation ----------------------------------------------------------
    def _burn(self, slo: Slo, events, now: float, window_s: float) -> float:
        cut = now - window_s
        bad = total = 0
        for t, is_bad in reversed(events):
            if t < cut:
                break
            total += 1
            bad += bool(is_bad)
        if total == 0:
            return 0.0
        return (bad / total) / max(slo.budget, 1e-9)

    def alerting(self) -> bool:
        """True while ANY SLO is in alert state — what a control-loop
        subscriber (the admission guard) checks before relaxing."""
        with self._lock:
            return bool(self._alerting)

    def evaluate(self) -> List[dict]:
        """One evaluation pass; returns the alerts that FIRED this pass
        (entering alert state — a continuing alert is not re-fired).
        Subscribers additionally see CLEAR transitions (an alert leaving
        alert state) as events with ``"cleared": True`` — the edge a
        control loop needs to relax whatever it tightened."""
        now = self.clock()
        fired = []
        cleared = []
        with self._lock:
            for slo in self.slos:
                events = self._events[slo.name]
                cut = now - slo.slow_window_s
                while events and events[0][0] < cut:
                    events.popleft()
                fast = self._burn(slo, events, now, slo.fast_window_s)
                slow = self._burn(slo, events, now, slo.slow_window_s)
                self.telemetry.gauge(
                    "slo.burn_rate", slo=slo.name, window="fast"
                ).set(fast)
                self.telemetry.gauge(
                    "slo.burn_rate", slo=slo.name, window="slow"
                ).set(slow)
                alerting = fast >= slo.fast_burn and slow >= slo.slow_burn
                if alerting and slo.name not in self._alerting:
                    self._alerting.add(slo.name)
                    alert = {
                        "t": time.time(), "slo": slo.name,
                        "fast_burn": fast, "slow_burn": slow,
                        "objective": slo.objective, "budget": slo.budget,
                    }
                    self.alerts.append(alert)
                    fired.append(alert)
                    self.telemetry.counter("slo.alerts", slo=slo.name).inc()
                elif not alerting and slo.name in self._alerting:
                    self._alerting.discard(slo.name)
                    cleared.append({
                        "t": time.time(), "slo": slo.name, "cleared": True,
                        "fast_burn": fast, "slow_burn": slow,
                    })
                    self.telemetry.counter(
                        "slo.alert_clears", slo=slo.name
                    ).inc()
        for event in fired + cleared:
            for cb in self._subscribers:
                try:
                    cb(event)
                except Exception:  # noqa: BLE001 — observe-only: a bad
                    # subscriber must not take down the monitor.
                    pass
        return fired

    def export(self) -> dict:
        with self._lock:
            state = []
            for slo in self.slos:
                now = self.clock()
                events = self._events[slo.name]
                state.append({
                    "name": slo.name, "kind": slo.kind,
                    "objective": slo.objective, "budget": slo.budget,
                    "fast_burn": self._burn(slo, events, now,
                                            slo.fast_window_s),
                    "slow_burn": self._burn(slo, events, now,
                                            slo.slow_window_s),
                    "alerting": slo.name in self._alerting,
                })
            return {"slos": state, "alerts": list(self.alerts)}


class FleetObserver:
    """The fleet's observability plane — see the module docstring.

    Attach with :meth:`ServingFleet.observe` (which wires the router hook,
    the child span sinks, and the supervisor feed) or construct directly
    over a bare router in tests.  ``flight_dir`` is where collected flight
    dumps persist (pass the run's output dir to land them next to the run
    report)."""

    def __init__(self, fleet=None, router=None, telemetry=None,
                 policy: Optional[ObservePolicy] = None,
                 slos: Sequence[Slo] = DEFAULT_SLOS,
                 flight_dir: Optional[str] = None):
        from photon_tpu.telemetry import NULL_SESSION

        self.fleet = fleet
        self.router = router if router is not None else (
            fleet.router if fleet is not None else None
        )
        self.telemetry = telemetry or (
            fleet.telemetry if fleet is not None else None
        ) or NULL_SESSION
        self.policy = policy or ObservePolicy()
        self.process = f"router:{os.getpid()}"
        self.sampler = TraceSampler(self.policy.sample_rate)
        self.collector = TraceCollector(self.policy.trace_capacity)
        self.slo_monitor = SloMonitor(slos, telemetry=self.telemetry)
        self.flight_dir = flight_dir
        self.flight_dumps: List[dict] = []
        # Parent-side per-replica event rings: even a thread-backed replica
        # (no child process, no on-disk ring) leaves a postmortem.
        self._parent_rings: dict = {}
        self._events: deque = deque(maxlen=8192)  # live-plane window feed
        self._events_lock = threading.Lock()
        self._child_hists: dict = {}  # replica_id -> last shipped snapshot
        self._lock = threading.Lock()
        self._last_eval = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._http: Optional[MetricsPlane] = None

    # -- SLO -> admission feedback -------------------------------------------
    def attach_admission_guard(self, router, tighten: Optional[float] = None
                               ) -> None:
        """Close the loop from the SLO burn-rate monitor to the router's
        admission controller: while any multiwindow alert is live the
        router's ``burn_safety`` multiplier is raised to ``tighten``
        (projected waits look ``tighten``× worse, so the controller sheds
        earlier and protects the deadline SLO); when the last alert
        clears the multiplier relaxes back to 1.0.  Opt-in via
        ``ObservePolicy.admission_guard`` because it actuates the serving
        path rather than just observing it."""
        factor = float(tighten if tighten is not None
                       else self.policy.admission_tighten)

        def _on_slo_event(event: dict) -> None:
            if event.get("cleared"):
                # Relax only once every alert has cleared — one SLO
                # recovering while another still burns keeps the guard up.
                if self.slo_monitor.alerting():
                    return
                if router.burn_safety != 1.0:
                    router.burn_safety = 1.0
                    self.telemetry.counter("serving.admission_relaxed").inc()
            else:
                if router.burn_safety != factor:
                    router.burn_safety = factor
                    self.telemetry.counter("serving.admission_tightened").inc()

        self.slo_monitor.subscribe(_on_slo_event)

    # -- trace origination (router + client hooks) ---------------------------
    def maybe_start_span(self, request, name: str = "serving.request",
                         process: Optional[str] = None
                         ) -> Optional[SpanRecord]:
        """Root-span decision for one request: continue an attached wire
        context, else the thread's ambient trace (the refresh→rollout
        linkage), else sample a fresh trace.  Returns None when the
        request is not traced (the hot path's common case)."""
        ctx = trace_of(request)
        if ctx is None:
            ctx = current_trace()
        if ctx is None:
            if not self.sampler.should_sample():
                return None
            span = SpanRecord(new_trace_id(), name, process or self.process)
        else:
            span = SpanRecord(ctx.trace_id, name, process or self.process,
                              parent_id=ctx.span_id)
        attach_span(request, span)
        return span

    def client_span(self, request) -> Optional[SpanRecord]:
        """Client-side origination (the ``AsyncScoringClient`` hook): the
        span covers send→response on the client's clock, and its context
        rides the request frame so the server-side root span links under
        it."""
        span = self.maybe_start_span(
            request, name="client.request", process=f"client:{os.getpid()}"
        )
        if span is not None:
            attach_trace(request, span.context())
        return span

    # -- router feed ----------------------------------------------------------
    def _record_event(self, **event) -> None:
        event["t"] = time.monotonic()
        with self._events_lock:
            self._events.append(event)
        rid = event.get("replica")
        if rid:
            ring = self._parent_rings.get(rid)
            if ring is None:
                ring = self._parent_rings.setdefault(
                    rid, FlightRecorder(rid, self.policy.flight_capacity)
                )
            ring.record("request", **{
                k: v for k, v in event.items() if k != "t"
            })

    def _maybe_evaluate(self) -> None:
        """Throttled burn-rate evaluation for the per-request hooks: an
        evaluation scans the sliding windows (O(window events)), and doing
        that on EVERY request would make the observer the overhead it
        polices.  The poll thread (and ``poll_once`` in tests) evaluates
        unconditionally."""
        now = time.monotonic()
        if now - self._last_eval >= self.policy.poll_interval_s:
            self._last_eval = now
            self.slo_monitor.evaluate()

    def on_shed(self, reason: str, rows: int, span=None,
                model: Optional[str] = None) -> None:
        if span is not None:
            span.event("shed", reason=reason)
            span.finish(status="shed")
            self.collector.add(span)
        self._record_event(status="shed", reason=reason, rows=rows,
                           replica=None, version=None, latency_s=None,
                           model=model)
        self.slo_monitor.observe_request("shed", None)
        self._maybe_evaluate()

    def on_done(self, status: str, latency_s: Optional[float], rows: int,
                replica_id: Optional[str], version=None,
                model: Optional[str] = None) -> None:
        self._record_event(status=status, latency_s=latency_s, rows=rows,
                           replica=replica_id, version=version, model=model)
        self.slo_monitor.observe_request(status, latency_s)
        self._maybe_evaluate()

    # -- supervisor feed -------------------------------------------------------
    def on_parity(self, replica_id: str, worst: float) -> None:
        self.slo_monitor.observe_parity(worst)
        self._maybe_evaluate()

    def collect_flight(self, replica, cause: str) -> Optional[str]:
        """Collect + persist one dead replica's flight record: the child's
        on-disk ring (subprocess replicas — written before each traced
        batch, so it survives SIGKILL) plus the parent-side event ring.
        Unfinished child spans are adopted into the collector as terminal
        "lost" stubs — the trace that was mid-flight on the victim stays
        whole.  Returns the persisted dump path (None if persisting was
        impossible); always safe to call — never raises."""
        try:
            rid = replica.replica_id
            child = None
            child_path = getattr(replica, "flight_path", None)
            if child_path:
                child = FlightRecorder.load(child_path)
            ring = self._parent_rings.get(rid)
            dump = {
                "replica": rid,
                "generation": getattr(replica, "generation", 0),
                "cause": cause,
                "collected_at": time.time(),
                "parent": ring.snapshot() if ring is not None else None,
                "child": child,
            }
            lost = self._adopt_lost_spans(child, cause)
            dump["lost_spans_recovered"] = lost
            path = None
            if self.flight_dir:
                os.makedirs(self.flight_dir, exist_ok=True)
                path = os.path.join(
                    self.flight_dir,
                    f"flight-{rid}-g{dump['generation']}-{cause}.json",
                )
                tmp = f"{path}.tmp"
                with open(tmp, "w") as f:
                    json.dump(dump, f, default=str)
                os.replace(tmp, path)
            self.telemetry.counter(
                "observe.flight_dumps", replica=rid, cause=cause
            ).inc()
            with self._lock:
                self.flight_dumps.append({
                    "replica": rid, "cause": cause, "path": path,
                    "generation": dump["generation"],
                    "child_records": len((child or {}).get("records", ())),
                    "lost_spans_recovered": lost,
                    "collected_at": dump["collected_at"],
                })
            return path
        except Exception:  # noqa: BLE001 — postmortem collection must
            # never make a death worse.
            return None

    def _adopt_lost_spans(self, child_dump: Optional[dict],
                          cause: str) -> int:
        """Span-stream loss recovery: a child span opened on the victim but
        never shipped (the kill landed mid-batch) is adopted as a "lost"
        stub so its trace keeps the hop instead of orphaning it."""
        if not child_dump:
            return 0
        adopted = 0
        closed = set()
        opened = []
        for rec in child_dump.get("records", ()):
            if rec.get("kind") != "span":
                continue
            span = rec.get("span") or {}
            if rec.get("phase") == "close":
                closed.add(span.get("span_id"))
            elif rec.get("phase") == "open":
                opened.append(span)
        for span in opened:
            sid, tid = span.get("span_id"), span.get("trace_id")
            if not tid or sid in closed:
                continue
            have = {d.get("span_id") for d in self.collector.trace(tid)}
            if sid in have:
                continue  # it DID ship (inline with the response)
            self.collector.recover_lost(tid, span, reason=cause)
            self.telemetry.counter("observe.lost_spans_recovered").inc()
            adopted += 1
        return adopted

    # -- child polling ---------------------------------------------------------
    def poll_once(self) -> None:
        """One pull pass over the fleet's replicas: drain completed child
        spans over the control connection, pull the shipped mergeable
        histogram snapshots, and evaluate SLOs.  Advisory — any per-replica
        failure is skipped (liveness verdicts belong to the supervisor)."""
        replicas = list(self.router.replicas) if self.router else []
        for replica in replicas:
            if not getattr(replica, "alive", False):
                continue
            pull = getattr(replica, "pull_spans", None)
            if pull is not None:
                try:
                    self.collector.merge_remote(pull())
                except Exception:  # noqa: BLE001 — advisory pull
                    pass
            hist = getattr(replica.scorer, "last_hist_snapshot", None)
            if hist:
                self._child_hists[replica.replica_id] = hist
        self.slo_monitor.evaluate()

    def _run(self) -> None:
        while not self._stop.wait(self.policy.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — observation must outlive
                # a bad pass.
                pass

    def start(self) -> "FleetObserver":
        if self.policy.http_port is not None and self._http is None:
            self._http = MetricsPlane(
                self, host=self.policy.http_host,
                port=self.policy.http_port,
            )
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="photon-fleet-observer", daemon=True
            )
            self._thread.start()
        return self

    @property
    def http_address(self):
        return None if self._http is None else self._http.address

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._http is not None:
            self._http.close()
            self._http = None
        try:
            self.poll_once()  # final span drain before the fleet tears down
        except Exception:  # noqa: BLE001 — best-effort drain
            pass

    # -- the live plane --------------------------------------------------------
    def fleet_snapshot(self) -> dict:
        """Fleet-level live aggregates over the sliding window, grouped per
        model version AND per tenant model id: QPS, p50/p99 latency, shed
        rate — plus the merged child histogram (device-side compute
        seconds) and current SLO state.  The per-model grouping is what a
        multi-tenant arena's isolation claims are checked against: tenant
        A's storm shows up in A's shed rate, not B's."""
        now = time.monotonic()
        cut = now - self.policy.window_s
        with self._events_lock:
            window = [e for e in self._events if e["t"] >= cut]
        span_s = self.policy.window_s
        if window:
            span_s = min(span_s, max(now - window[0]["t"], 1e-3))

        def _aggregate(group_key: str) -> dict:
            groups: dict = {}
            for e in window:
                key = str(e.get(group_key))
                g = groups.setdefault(
                    key, {"ok": 0, "shed": 0, "error": 0, "rows": 0,
                          "latencies": []}
                )
                status = e.get("status", "ok")
                g[status if status in g else "error"] += 1
                g["rows"] += int(e.get("rows") or 0)
                if e.get("latency_s") is not None:
                    g["latencies"].append(float(e["latency_s"]))
            out = {}
            for key, g in sorted(groups.items()):
                lat = sorted(g["latencies"])

                def pct(p):
                    if not lat:
                        return None
                    return lat[min(len(lat) - 1,
                                   max(0, round(p * (len(lat) - 1))))]

                total = g["ok"] + g["shed"] + g["error"]
                out[key] = {
                    "qps": g["ok"] / span_s,
                    "rows_per_s": g["rows"] / span_s,
                    "p50_s": pct(0.50),
                    "p99_s": pct(0.99),
                    "shed_rate": g["shed"] / total if total else 0.0,
                    "error_rate": g["error"] / total if total else 0.0,
                    "requests": total,
                }
            return out

        merged_child = MergeableHistogram.merged(
            list(self._child_hists.values())
        )
        return {
            "at": time.time(),
            "window_s": span_s,
            "versions": _aggregate("version"),
            "models": _aggregate("model"),
            "child_compute": {
                "p50_s": merged_child.quantile(0.50),
                "p99_s": merged_child.quantile(0.99),
                "count": merged_child.count,
            },
            "traces": len(self.collector.trace_ids()),
            "flight_dumps": len(self.flight_dumps),
            "slo": self.slo_monitor.export(),
        }

    # -- report export ---------------------------------------------------------
    def export(self, trace_limit: int = 8) -> dict:
        """The run report's ``extra["observe"]`` payload: recent traces
        with their critical-path decompositions, SLO state, and the
        collected flight dumps — what the report renderer's "Fleet traces
        / SLOs" section draws."""
        paths = []
        for tid in self.collector.trace_ids()[-trace_limit:]:
            cp = self.collector.critical_path(tid)
            if cp is not None:
                paths.append(cp)
        with self._lock:
            dumps = list(self.flight_dumps)
        return {
            "sample_rate": self.sampler.rate,
            "spans_merged": self.collector.spans_merged,
            "traces_kept": len(self.collector.trace_ids()),
            "critical_paths": paths,
            "slo": self.slo_monitor.export(),
            "flight_dumps": dumps,
        }


class MetricsPlane:
    """Stdlib-HTTP live endpoint: ``/metrics`` is the Prometheus text
    exposition of the fleet's registry, ``/fleet.json`` the live snapshot
    the ``python -m photon_tpu.telemetry.live`` console view polls.  A
    scrape is read-only and lock-bounded — it can slow nothing but
    itself."""

    def __init__(self, observer: FleetObserver, host: str = "127.0.0.1",
                 port: int = 0):
        import http.server

        outer = observer

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: D102 — silence stderr
                pass

            def do_GET(self):  # noqa: N802 — stdlib handler name
                try:
                    if self.path.startswith("/metrics"):
                        body = outer.telemetry.registry.to_prometheus()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        body = json.dumps(outer.fleet_snapshot(),
                                          default=str)
                        ctype = "application/json"
                except Exception as e:  # noqa: BLE001 — a scrape error is
                    # the scraper's problem, never the fleet's.
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                data = body.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = http.server.ThreadingHTTPServer((host, port),
                                                       _Handler)
        self.address = self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="photon-metrics-plane", daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=10.0)
