"""Multi-model serving arena: N tenant models in ONE gather-table
allocation and ONE compiled bucket ladder (ISSUE 18).

No production GAME deployment serves one model — per-market variants and
A/B arms mean a fleet hosts many small models at once.  Pre-arena, each
``GameScorer`` paid its own device allocation and its own compiled
bucket ladder, so compiled-program count and table bytes both scaled
with model count.  The arena collapses that: per random coordinate, ONE
``[arena_rows, dim]`` gather table (stored at the PR 17 precision tier)
holds every hosted model's rows at per-model row OFFSETS, and per fixed
coordinate one ``[model_slots, dim]`` stacked weight table holds every
model's coefficient vector at its slot row.  Model identity is NOT
compiled into anything: every bucket program takes a per-row global
gather index and a per-row model-slot vector as ARGUMENTS, so the
programs are keyed on (bucket shape x coordinate layout x dtype) only —
hosting the 9th model compiles exactly nothing.

Residency/allocation contract:

- onboarding, retiring, or refreshing a model is a SLICE SCATTER
  (``lax.dynamic_update_slice`` at the model's base row, traced base so
  offsets never recompile) — no host re-upload of any untouched model's
  rows, no change to the compiled footprint;
- per-model slots carry amortized-doubling headroom (next pow2 past
  ``entities + 1``, times ``table_capacity_factor``) so a refreshed
  model whose vocabulary grew within its slot republishes in place; a
  model that outgrows its slot MIGRATES to a larger free extent (still
  zero recompiles — only its base offset moves); only when the whole
  arena is out of free rows does capacity double, which rebuilds the
  tables and the ladder (the documented "arena-growth migration"
  boundary, surfaced by a ``layout_version`` bump);
- the hot path keeps the scorer's contract: one compiled dispatch + ONE
  host sync per micro-batch; the entity join AND the model->slot
  resolution run host-side at ingest (the sanctioned edge), so cold
  entities are counted on host for free and the device program has no
  per-model branches at all.

``serving.arena_bytes`` / ``serving.arena_models`` gauge the shared
allocation; the serving bench asserts arena bytes stay within 1.15x the
sum of the hosted models' solo tables.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.data import entity_index_for
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    serving_gather_margins,
)
from photon_tpu.parallel.mesh import (
    abstract_like,
    mesh_shards,
    pad_to_multiple,
    put_replicated,
    put_request,
)
from photon_tpu.serving.scorer import (
    DEFAULT_MAX_BATCH,
    DEFAULT_MIN_BUCKET,
    ScoringRequest,
    ShardSpec,
    _pad_rows,
    bucket_ladder,
    padded_cost,
    request_spec_for_model,
    slice_request,
)
from photon_tpu.utils import pow2_at_least


@jax.jit
def _scatter_rows(table, update, base):
    """Row-slice scatter at a TRACED base offset: one compile per
    (table shape, update shape) pair, reused for every model/offset."""
    return jax.lax.dynamic_update_slice(
        table, update, (base, jnp.int32(0))
    )


@jax.jit
def _scatter_vec(vec, update, base):
    """1-D twin of :func:`_scatter_rows` (int8 per-row scale vectors)."""
    return jax.lax.dynamic_update_slice(vec, update, (base,))


def _encode_slot_rows(table, slot_rows: int, dim: int, dtype: str):
    """One model's coefficient table as a ``[slot_rows, dim]`` storage-
    form block: vocabulary rows first, then all-zero rows (the movable
    zero row + headroom).  Device-side — mirrors
    :meth:`RandomEffectModel.serving_table`'s encode so the arena slice
    and a solo scorer's table hold byte-identical content."""
    table = jnp.asarray(table, jnp.float32)
    block = jnp.concatenate(
        [table, jnp.zeros((slot_rows - table.shape[0], dim), jnp.float32)]
    )
    if dtype == "bf16":
        return block.astype(jnp.bfloat16)
    if dtype == "int8":
        absmax = jnp.max(jnp.abs(block), axis=-1)
        scale = (absmax / 127.0).astype(jnp.float32)
        divisor = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
        q = jnp.clip(
            jnp.round(block / divisor[:, None]), -127.0, 127.0
        ).astype(jnp.int8)
        return (q, scale)
    return block


@dataclasses.dataclass(frozen=True)
class _ArenaCoord:
    """Static per-coordinate layout of the arena (the compiled shape)."""

    name: str
    kind: str  # "fixed" | "random"
    shard: str
    dim: int
    column: Optional[str] = None  # random: id column joined on
    rows: int = 0  # random: total arena rows (the table's first axis)


@dataclasses.dataclass(frozen=True)
class _Slot:
    """One hosted model's placement inside the arena.

    ``row`` indexes the fixed-coordinate weight stacks (and the host-side
    per-slot base/zero arrays); ``base``/``size`` give each random
    coordinate's extent; ``zero`` the GLOBAL index of the model's movable
    zero row (``base + num_entities``)."""

    row: int
    base: Dict[str, int]
    size: Dict[str, int]
    zero: Dict[str, int]
    vocab: Dict[str, np.ndarray]
    model: GameModel
    version: int


class _ArenaState:
    """Immutable host-side routing snapshot published alongside the device
    tables: model-id -> slot resolution (sorted ids + searchsorted, the
    same join idiom as the entity vocabulary) and per-slot base/zero
    arrays the ingest staging indexes per row."""

    def __init__(self, slots: Dict[str, _Slot], coords, model_slots: int):
        self.slots = dict(slots)
        ids = sorted(slots)
        # host-sync: ingest routing tables — host numpy by construction
        # (model ids never live on device).
        self.ids_sorted = np.asarray(ids, dtype=object)
        self.row_sorted = np.asarray(
            [slots[i].row for i in ids], np.int32
        )
        self.id_of_row = {s.row: i for i, s in slots.items()}
        self.base: Dict[str, np.ndarray] = {}
        self.zero: Dict[str, np.ndarray] = {}
        for c in coords:
            if c.kind != "random":
                continue
            base = np.zeros(model_slots, np.int32)
            zero = np.zeros(model_slots, np.int32)
            for s in slots.values():
                base[s.row] = s.base[c.name]
                zero[s.row] = s.zero[c.name]
            self.base[c.name] = base
            self.zero[c.name] = zero

    def row_of(self, model_id: str) -> int:
        slot = self.slots.get(model_id)
        if slot is None:
            raise KeyError(
                f"model {model_id!r} is not hosted in this arena "
                f"(hosted: {sorted(self.slots)})"
            )
        return slot.row

    def rows_for(self, model_ids: np.ndarray) -> np.ndarray:
        """Per-row slot rows for a mixed-model batch; unknown ids raise
        (a request for an unhosted model must shed loudly, not gather
        another tenant's rows)."""
        pos = entity_index_for(model_ids, self.ids_sorted)
        if (pos < 0).any():
            # host-sync: error-path formatting over the host id vector.
            bad = sorted(set(np.asarray(model_ids, dtype=object)[pos < 0]))
            raise KeyError(
                f"request routes to unhosted model(s) {bad!r} "
                f"(hosted: {sorted(self.slots)})"
            )
        return self.row_sorted[pos]


class ModelArena:
    """The shared device allocation: per-coordinate arena tables plus the
    extent/slot bookkeeping that makes onboard/retire/refresh a slice
    scatter.  Pure state management — the compiled programs live in
    :class:`MultiModelScorer`, which owns an arena and re-publishes its
    ``(tables, state)`` snapshots."""

    def __init__(
        self,
        models: Dict[str, GameModel],
        mesh=None,
        table_dtype: str = "f32",
        table_capacity_factor: int = 1,
        model_slots: Optional[int] = None,
        reserve_rows: int = 0,
        telemetry=None,
    ):
        from photon_tpu.game.lowp import check_dtype
        from photon_tpu.telemetry import NULL_SESSION

        if not models:
            raise ValueError("ModelArena needs at least one hosted model")
        self.mesh = mesh
        self.table_dtype = check_dtype(table_dtype)
        self.table_capacity_factor = max(1, int(table_capacity_factor))
        self.telemetry = telemetry or NULL_SESSION
        self.layout_version = 0
        self._rebuilds = 0
        self._lock = threading.Lock()
        first = next(iter(models.values()))
        self.default_id = next(iter(models))
        self._coord_template = self._template_of(first)
        for mid, model in models.items():
            self._check_layout(mid, model)

        # Fixed-coordinate stacking: one slot row per hosted model, with
        # pow2 headroom so onboarding stays recompile-free until the slot
        # count itself doubles.
        self.model_slots = int(
            model_slots
            if model_slots is not None
            else pow2_at_least(max(2 * len(models), 4))
        )
        if self.model_slots < len(models):
            raise ValueError(
                f"model_slots={self.model_slots} < {len(models)} models"
            )

        slot_sizes = {
            mid: self._slot_sizes(model) for mid, model in models.items()
        }
        self._capacity: Dict[str, int] = {}
        for name, _, _, _ in self._random_coords():
            need = sum(s[name] for s in slot_sizes.values())
            self._capacity[name] = pad_to_multiple(
                need + int(reserve_rows), max(1, mesh_shards(mesh))
            )
        self._free: Dict[str, List[Tuple[int, int]]] = {
            name: [] for name in self._capacity
        }
        self._free_rows_of_slots = list(range(self.model_slots))

        self.coords = self._build_coords()
        self.tables = self._alloc_tables()
        self.slots: Dict[str, _Slot] = {}
        cursor = {name: 0 for name in self._capacity}
        for mid, model in models.items():
            slot = self._place_slot(mid, model, slot_sizes[mid], cursor)
            self.tables = self._publish_slot(self.tables, slot, model)
        for name, cap in self._capacity.items():
            used = cursor[name]
            if used < cap:
                self._free[name].append((used, cap - used))
        self.state = _ArenaState(self.slots, self.coords, self.model_slots)
        jax.block_until_ready(self.tables)
        self._record_gauges()

    # -- layout helpers ----------------------------------------------------
    @staticmethod
    def _template_of(model: GameModel):
        out = []
        for name, coord in model.coordinates.items():
            if isinstance(coord, FixedEffectModel):
                out.append((name, "fixed", coord.shard_name,
                            int(len(coord.coefficients.means)), None))
            elif isinstance(coord, RandomEffectModel):
                out.append((name, "random", coord.shard_name,
                            int(coord.dim), coord.entity_column))
            else:
                raise TypeError(
                    f"cannot serve a {type(coord).__name__} coordinate"
                )
        return tuple(out)

    def _check_layout(self, model_id: str, model: GameModel) -> None:
        """Every hosted model must share ONE coordinate layout — the arena
        compiles one ladder for all of them, so a model with different
        coordinates/shards/dims cannot share the allocation."""
        got = self._template_of(model)
        if got != self._coord_template:
            raise ValueError(
                f"model {model_id!r} does not match the arena's coordinate "
                f"layout (arena {self._coord_template}, model {got}); "
                "every hosted model must share one coordinate layout"
            )

    def _random_coords(self):
        return [
            (name, shard, dim, column)
            for name, kind, shard, dim, column in self._coord_template
            if kind == "random"
        ]

    def _slot_sizes(self, model: GameModel) -> Dict[str, int]:
        """Per-random-coordinate slot rows for one model: the model's own
        amortized-doubling serving capacity (entities + zero row, next
        pow2, times the pre-provisioning factor) — the same headroom a
        solo scorer would allocate, so arena bytes track the sum of solo
        tables."""
        sizes = {}
        for name, coord in model.coordinates.items():
            if isinstance(coord, RandomEffectModel):
                sizes[name] = pow2_at_least(
                    self.table_capacity_factor * (coord.num_entities + 1)
                )
        return sizes

    def _build_coords(self) -> Tuple[_ArenaCoord, ...]:
        coords = []
        for name, kind, shard, dim, column in self._coord_template:
            coords.append(
                _ArenaCoord(
                    name, kind, shard, dim, column=column,
                    rows=self._capacity.get(name, 0),
                )
            )
        return tuple(coords)

    def _alloc_tables(self) -> tuple:
        """Fresh all-zero arena tables at the current capacities, in
        coordinate order: fixed -> ``[model_slots, dim]`` f32 replicated;
        random -> ``[rows, dim]`` storage-form, row-sharded like a solo
        serving table."""
        from photon_tpu.parallel.mesh import reshard_to_mesh

        tables = []
        for c in self.coords:
            if c.kind == "fixed":
                tables.append(
                    put_replicated(
                        jnp.zeros((self.model_slots, c.dim), jnp.float32),
                        self.mesh,
                    )
                )
            elif self.table_dtype == "int8":
                tables.append((
                    reshard_to_mesh(
                        jnp.zeros((c.rows, c.dim), jnp.int8), self.mesh
                    ),
                    reshard_to_mesh(
                        jnp.zeros((c.rows,), jnp.float32), self.mesh
                    ),
                ))
            else:
                dt = jnp.bfloat16 if self.table_dtype == "bf16" else jnp.float32
                tables.append(
                    reshard_to_mesh(
                        jnp.zeros((c.rows, c.dim), dt), self.mesh
                    )
                )
        return tuple(tables)

    # -- extent allocator --------------------------------------------------
    def _alloc_extent(self, name: str, size: int) -> Optional[int]:
        """Best-fit over the coordinate's free list; splits the remainder
        back.  Returns the base row, or None when no extent fits (the
        caller then grows the arena)."""
        best = None
        for i, (base, extent) in enumerate(self._free[name]):
            if extent >= size and (best is None
                                   or extent < self._free[name][best][1]):
                best = i
        if best is None:
            return None
        base, extent = self._free[name].pop(best)
        if extent > size:
            self._free[name].append((base + size, extent - size))
        return base

    def _free_extent(self, name: str, base: int, size: int) -> None:
        """Return an extent, coalescing adjacent frees so churn (retire +
        onboard cycles) cannot fragment the arena into unusable slivers."""
        extents = sorted(self._free[name] + [(base, size)])
        merged: List[Tuple[int, int]] = []
        for b, s in extents:
            if merged and merged[-1][0] + merged[-1][1] == b:
                merged[-1] = (merged[-1][0], merged[-1][1] + s)
            else:
                merged.append((b, s))
        self._free[name] = merged

    def free_rows(self, name: str) -> int:
        return sum(s for _, s in self._free[name])

    # -- slot placement / publish -----------------------------------------
    def _place_slot(self, model_id: str, model: GameModel,
                    sizes: Dict[str, int], cursor: Dict[str, int]) -> _Slot:
        """Initial-build placement: slots pack densely from row 0."""
        row = self._free_rows_of_slots.pop(0)
        base, zero, vocab = {}, {}, {}
        for name, coord in model.coordinates.items():
            if not isinstance(coord, RandomEffectModel):
                continue
            base[name] = cursor[name]
            zero[name] = cursor[name] + coord.num_entities
            # host-sync: build-time only — entity vocabularies are host
            # numpy by construction (the key join runs at ingest).
            vocab[name] = np.asarray(coord.keys)
            cursor[name] += sizes[name]
        slot = _Slot(row=row, base=base, size=dict(sizes), zero=zero,
                     vocab=vocab, model=model, version=1)
        self.slots[model_id] = slot
        return slot

    def _publish_slot(self, tables: tuple, slot: _Slot,
                      model: GameModel) -> tuple:
        """Scatter one model's rows into its extents: the COPY-ON-WRITE
        slice update (functional ``dynamic_update_slice`` — in-flight
        batches keep reading the tables they captured; the new tuple
        publishes in one assignment upstream).  No host re-upload of any
        other model's rows ever happens here."""
        out = list(tables)
        for i, c in enumerate(self.coords):
            coord = model.coordinates[c.name]
            if c.kind == "fixed":
                w = jnp.asarray(
                    coord.coefficients.means, jnp.float32
                )[None, :]
                out[i] = _scatter_rows(out[i], w, jnp.int32(slot.row))
                continue
            block = _encode_slot_rows(
                coord.table, slot.size[c.name], c.dim, self.table_dtype
            )
            base = jnp.int32(slot.base[c.name])
            if self.table_dtype == "int8":
                q, scale = out[i]
                bq, bscale = block
                out[i] = (
                    _scatter_rows(q, bq, base),
                    _scatter_vec(scale, bscale, base),
                )
            else:
                out[i] = _scatter_rows(out[i], block, base)
        return tuple(out)

    # -- lifecycle ---------------------------------------------------------
    def onboard(self, model_id: str, model: GameModel) -> bool:
        """Host a new model.  Allocates one extent per random coordinate
        plus a fixed slot row and slice-scatters the rows in — zero new
        device allocations and zero recompiles while free extents and
        slot rows last.  Returns True when the LAYOUT changed (arena had
        to grow — the caller must rebuild its compiled ladder)."""
        with self._lock:
            if model_id in self.slots:
                raise ValueError(
                    f"model {model_id!r} is already hosted; use refresh()"
                )
            self._check_layout(model_id, model)
            sizes = self._slot_sizes(model)
            grew = self._ensure_room(sizes, need_slot_row=True)
            row = self._free_rows_of_slots.pop(0)
            base, zero, vocab = {}, {}, {}
            for name, size in sizes.items():
                b = self._alloc_extent(name, size)
                assert b is not None  # _ensure_room guaranteed space
                base[name] = b
                coord = model.coordinates[name]
                zero[name] = b + coord.num_entities
                # host-sync: onboard-time only — vocabulary join tables.
                vocab[name] = np.asarray(coord.keys)
            slot = _Slot(row=row, base=base, size=sizes, zero=zero,
                         vocab=vocab, model=model, version=1)
            self.slots[model_id] = slot
            self.tables = self._publish_slot(self.tables, slot, model)
            self.state = _ArenaState(
                self.slots, self.coords, self.model_slots
            )
            jax.block_until_ready(self.tables)
            self.telemetry.counter("serving.arena_onboards").inc()
            self._record_gauges()
            return grew

    def retire(self, model_id: str) -> None:
        """Un-host a model: its extents and slot row return to the free
        lists.  The rows themselves stay in device memory untouched —
        ingest routing refuses the id, so they are unreachable, and the
        next onboard overwrites them.  Never recompiles."""
        with self._lock:
            if len(self.slots) == 1:
                raise ValueError(
                    "cannot retire the last hosted model; the arena "
                    "always serves at least one"
                )
            slot = self.slots.pop(model_id, None)
            if slot is None:
                raise KeyError(f"model {model_id!r} is not hosted")
            for name, size in slot.size.items():
                self._free_extent(name, slot.base[name], size)
            self._free_rows_of_slots.insert(0, slot.row)
            if model_id == self.default_id:
                self.default_id = next(iter(self.slots))
            self.state = _ArenaState(
                self.slots, self.coords, self.model_slots
            )
            self.telemetry.counter("serving.arena_retires").inc()
            self._record_gauges()

    def refresh(self, model_id: str, model: GameModel) -> bool:
        """Republish one hosted model (the online-refresh publish path).

        In-slot when the grown vocabulary still fits the slot (the common
        case — slots carry pow2 headroom); MIGRATES to a larger free
        extent when it does not (base offset moves, zero recompiles);
        grows the arena only when no extent fits.  Returns True when the
        layout changed."""
        with self._lock:
            slot = self.slots.get(model_id)
            if slot is None:
                raise KeyError(f"model {model_id!r} is not hosted")
            self._check_layout(model_id, model)
            sizes = self._slot_sizes(model)
            grew = False
            moved = {
                name: size for name, size in sizes.items()
                if size > slot.size[name]
            }
            if moved:
                # Free the old extents FIRST so a doubled slot can reuse
                # its own rows when they adjoin free space; the old rows
                # stay readable until the new state publishes (frees are
                # bookkeeping, not writes).
                for name in moved:
                    self._free_extent(name, slot.base[name],
                                      slot.size[name])
                rebuilds = self._rebuilds
                grew = self._ensure_room(moved, need_slot_row=False)
                if self._rebuilds != rebuilds:
                    # The rebuild re-based every slot and reset the free
                    # lists (the pre-rebuild frees with them) — re-fetch
                    # this model's repacked placement and abandon its
                    # about-to-move extents again.
                    slot = self.slots[model_id]
                    for name in moved:
                        self._free_extent(name, slot.base[name],
                                          slot.size[name])
            new_base = dict(slot.base)
            new_size = dict(slot.size)
            if moved:
                for name, size in moved.items():
                    b = self._alloc_extent(name, size)
                    assert b is not None
                    new_base[name] = b
                    new_size[name] = size
            base_zero = {}
            vocab = {}
            for name, coord in model.coordinates.items():
                if not isinstance(coord, RandomEffectModel):
                    continue
                base_zero[name] = new_base[name] + coord.num_entities
                # host-sync: refresh-time only — vocabulary join tables.
                vocab[name] = np.asarray(coord.keys)
            new_slot = _Slot(
                row=slot.row, base=new_base, size=new_size,
                zero=base_zero, vocab=vocab, model=model,
                version=slot.version + 1,
            )
            self.slots[model_id] = new_slot
            self.tables = self._publish_slot(self.tables, new_slot, model)
            self.state = _ArenaState(
                self.slots, self.coords, self.model_slots
            )
            jax.block_until_ready(self.tables)
            self.telemetry.counter("serving.arena_refreshes").inc()
            self._record_gauges()
            return grew

    def _ensure_room(self, sizes: Dict[str, int],
                     need_slot_row: bool) -> bool:
        """Make one free extent of each requested size exist (+ a free
        slot row if asked).  When a coordinate has no fitting extent, the
        arena REBUILDS: every hosted slot repacks densely from row 0, and
        if even the repacked tail cannot hold the request the capacity
        doubles first — the amortized-doubling boundary.  Returns True
        when table SHAPES changed (the scorer must rebuild its ladder); a
        same-shape compaction rebuild returns False (the compiled
        programs take the tables as arguments, so only offsets moved)."""
        new_caps = dict(self._capacity)
        need_rebuild = False
        for name, size in sizes.items():
            if any(extent >= size for _, extent in self._free[name]):
                continue
            used = sum(
                s.size.get(name, 0) for s in self.slots.values()
            )
            cap = new_caps[name]
            while cap - used < size:
                cap *= 2
            new_caps[name] = pad_to_multiple(
                cap, max(1, mesh_shards(self.mesh))
            )
            need_rebuild = True
        new_slots = self.model_slots
        if need_slot_row and not self._free_rows_of_slots:
            new_slots = self.model_slots * 2
            need_rebuild = True
        if not need_rebuild:
            return False
        grew = (
            new_caps != self._capacity or new_slots != self.model_slots
        )
        self._rebuild(new_caps, new_slots)
        return grew

    def _rebuild(self, capacities: Dict[str, int], model_slots: int) -> None:
        """The arena-growth migration: fresh (bigger) tables, every hosted
        model re-placed densely and re-scattered.  The ONLY path that
        allocates device memory after construction; ``layout_version``
        bumps when the shapes changed so the scorer rebuilds its compiled
        ladder before publishing (in-flight batches finish on the old
        tables — the rebuild is double-buffered like any swap)."""
        shapes_changed = (
            capacities != self._capacity
            or model_slots != self.model_slots
        )
        self._capacity = dict(capacities)
        self.model_slots = int(model_slots)
        self.coords = self._build_coords()
        tables = self._alloc_tables()
        cursor = {name: 0 for name in self._capacity}
        used_rows = sorted(self.slots.values(), key=lambda s: s.row)
        self._free_rows_of_slots = [
            r for r in range(self.model_slots)
            if r not in {s.row for s in used_rows}
        ]
        for mid in list(self.slots):
            slot = self.slots[mid]
            sizes = dict(slot.size)
            base = {}
            zero = {}
            for name, size in sizes.items():
                base[name] = cursor[name]
                zero[name] = (
                    cursor[name] + (slot.zero[name] - slot.base[name])
                )
                cursor[name] += size
            new_slot = dataclasses.replace(slot, base=base, zero=zero)
            self.slots[mid] = new_slot
            tables = self._publish_slot(tables, new_slot, slot.model)
        self._free = {
            name: ([(cursor[name], cap - cursor[name])]
                   if cursor[name] < cap else [])
            for name, cap in self._capacity.items()
        }
        self.tables = tables
        self.state = _ArenaState(self.slots, self.coords, self.model_slots)
        self._rebuilds += 1
        if shapes_changed:
            self.layout_version += 1
        self.telemetry.counter("serving.arena_growths").inc()

    # -- observability -----------------------------------------------------
    def arena_bytes(self) -> int:
        return sum(
            leaf.nbytes for leaf in jax.tree_util.tree_leaves(self.tables)
        )

    def _record_gauges(self) -> None:
        self.telemetry.gauge("serving.arena_bytes").set(self.arena_bytes())
        self.telemetry.gauge("serving.arena_models").set(len(self.slots))
        self.telemetry.gauge("serving.arena_layout_version").set(
            self.layout_version
        )
        for name, cap in self._capacity.items():
            self.telemetry.gauge(
                "serving.arena_rows", coordinate=name
            ).set(cap)
            self.telemetry.gauge(
                "serving.arena_free_rows", coordinate=name
            ).set(self.free_rows(name))


class MultiModelScorer:
    """N hosted models behind ONE compiled bucket ladder.

    The :class:`~photon_tpu.serving.scorer.GameScorer` surface (warmup /
    score_batch / swap_model / bucket_for / compilations ...) over a
    :class:`ModelArena`: every bucket program takes the arena tables plus
    per-row ``(global gather index, model slot)`` vectors, so model
    identity is request DATA — the compiled-program count is
    O(log max_batch), independent of model count, and a mixed-model
    micro-batch (the batcher coalescing two tenants' requests) scores in
    one dispatch.

    Requests route by ``ScoringRequest.model`` (a scalar id, or a per-row
    id array after coalescing); a request without a model id scores
    against the arena's default model, which keeps every single-model
    caller (supervisor probes, canary rollouts, benches) working
    unchanged."""

    def __init__(
        self,
        models: Dict[str, GameModel],
        mesh=None,
        request_spec: Optional[Dict[str, ShardSpec]] = None,
        buckets: Optional[Tuple[int, ...]] = None,
        max_batch: int = DEFAULT_MAX_BATCH,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        telemetry=None,
        strict_after_warmup: bool = True,
        table_capacity_factor: int = 1,
        table_dtype: str = "f32",
        model_slots: Optional[int] = None,
        reserve_rows: int = 0,
    ):
        from photon_tpu.telemetry import NULL_SESSION

        self.telemetry = telemetry or NULL_SESSION
        self.mesh = mesh
        self.arena = ModelArena(
            models,
            mesh=mesh,
            table_dtype=table_dtype,
            table_capacity_factor=table_capacity_factor,
            model_slots=model_slots,
            reserve_rows=reserve_rows,
            telemetry=self.telemetry,
        )
        self.table_dtype = self.arena.table_dtype
        first = next(iter(models.values()))
        self.request_spec = request_spec or request_spec_for_model(first)
        for c in self.arena.coords:
            if c.shard not in self.request_spec:
                raise ValueError(
                    f"request spec is missing shard {c.shard!r}"
                )
        self.buckets = bucket_ladder(buckets, max_batch, min_bucket)
        self.max_bucket = self.buckets[-1]
        self.compilations = 0
        self._warm = False
        self.strict_after_warmup = strict_after_warmup
        self._programs: Dict[tuple, object] = {}
        self._swap_lock = threading.Lock()
        # The ONE published (tables, state, programs) triple: score_batch
        # unpacks it once at entry, so an onboard/retire/refresh — even an
        # arena-growth rebuild — can never hand one batch mixed state.
        self._serving = (self.arena.tables, self.arena.state, self._programs)

    # -- GameScorer-compatible surface ------------------------------------
    @property
    def model(self) -> GameModel:
        """The DEFAULT model — what single-model callers (supervisor
        known-answer probes, respawn identity checks) see."""
        return self.model_for(self.arena.default_id)

    @property
    def models(self) -> Dict[str, GameModel]:
        _, state, _ = self._serving
        return {mid: s.model for mid, s in state.slots.items()}

    @property
    def model_ids(self) -> Tuple[str, ...]:
        _, state, _ = self._serving
        return tuple(sorted(state.slots))

    def model_for(self, model_id: str) -> GameModel:
        _, state, _ = self._serving
        slot = state.slots.get(model_id)
        if slot is None:
            raise KeyError(f"model {model_id!r} is not hosted")
        return slot.model

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} rows exceeds max bucket "
                         f"{self.max_bucket}; chunk it (score_batch does)")

    def padded_rows(self, n: int) -> int:
        return padded_cost(n, self.buckets)

    def warmup(self) -> "MultiModelScorer":
        """AOT-compile every ladder bucket ONCE for all hosted models —
        the arena's headline invariant: warmup cost is independent of
        model count, and serving any hosted (or later-onboarded) model
        hits these same executables."""
        with self.telemetry.span(
            "serving.warmup", buckets=len(self.buckets),
            models=len(self.model_ids),
        ):
            tables, _, programs = self._serving
            for b in self.buckets:
                self._compile(b, "request", tables, programs)
        self._warm = True
        return self

    # -- program build -----------------------------------------------------
    def _donate_argnums(self) -> tuple:
        """Donate request buffers (args 1-4: feats/gidx/mslot/offset) on
        accelerators only — same CPU aliasing hazard as GameScorer."""
        leaves = jax.tree_util.tree_leaves(self.arena.tables)
        devices = leaves[0].devices() if leaves else set()
        if any(d.platform == "cpu" for d in devices):
            return ()
        return (1, 2, 3, 4)

    def _compile(self, bucket: int, layout: str, tables, programs):
        program = programs.get((bucket, layout))
        if program is not None:
            return program
        plan, spec = self.arena.coords, self.request_spec

        def score(tables, feats, gidx, mslot, offset, n_valid):
            valid = jnp.arange(bucket, dtype=jnp.int32) < n_valid
            total = offset
            for c, table in zip(plan, tables):
                dense = spec[c.shard].dense
                if c.kind == "fixed":
                    # Per-row weight gather from the model-slot stack:
                    # the fixed coordinate's "which model" is a data
                    # dependency, never a compiled branch.
                    w = table[mslot]
                    if dense:
                        total = total + jnp.einsum(
                            "nd,nd->n", feats[c.shard], w
                        )
                    else:
                        ids, vals = feats[c.shard]
                        total = total + jnp.sum(
                            jnp.take_along_axis(w, ids, axis=1) * vals,
                            axis=-1,
                        )
                else:
                    # gidx is already GLOBAL and already safe: ingest
                    # resolved model base + local entity index, mapped
                    # unknown entities to the model's own zero row, and
                    # padded rows to 0 (masked below).
                    total = total + serving_gather_margins(
                        table, gidx[c.name], feats[c.shard], dense
                    )
            return jnp.where(valid, total, 0.0)

        jitted = jax.jit(score, donate_argnums=self._donate_argnums())
        sample = self._place(*self._zero_request(bucket))
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            program = jitted.lower(
                tables, *abstract_like(sample)
            ).compile()
        programs[(bucket, layout)] = program
        self.compilations += 1
        self.telemetry.counter("serving.compilations").inc()
        return program

    def _program(self, bucket: int, layout: str, tables, programs):
        program = programs.get((bucket, layout))
        if program is not None:
            return program
        if self._warm and self.strict_after_warmup and layout == "request":
            raise RuntimeError(
                f"no pre-compiled program for bucket {bucket} after warmup "
                f"(compiled: {sorted(b for b, l in programs if l == 'request')}); "
                "widen `buckets` or chunk the batch — serving must never "
                "recompile"
            )
        return self._compile(bucket, layout, tables, programs)

    def _zero_request(self, bucket: int):
        feats: Dict[str, object] = {}
        gidx: Dict[str, np.ndarray] = {}
        for c in self.arena.coords:
            s = self.request_spec[c.shard]
            if c.shard not in feats:
                if s.dense:
                    feats[c.shard] = np.zeros((bucket, s.dim), np.float32)
                else:
                    feats[c.shard] = (
                        np.zeros((bucket, s.nnz), np.int32),
                        np.zeros((bucket, s.nnz), np.float32),
                    )
            if c.kind == "random":
                gidx[c.name] = np.zeros(bucket, np.int32)
        mslot = np.zeros(bucket, np.int32)
        offset = np.zeros(bucket, np.float32)
        return feats, gidx, mslot, offset, np.int32(0)

    def _place(self, feats, gidx, mslot, offset, n_valid):
        return put_request(
            (feats, gidx, mslot, offset, jnp.int32(n_valid)), self.mesh
        )

    # -- ingest (host side, the sanctioned edge) ---------------------------
    def _resolve_rows(self, request: ScoringRequest, n: int,
                      state: _ArenaState):
        """Per-row model-slot rows for one request — the model->slot join.
        ``model`` may be a scalar id (whole request one tenant), a per-row
        id array (a coalesced mixed batch), or None (default model)."""
        model = getattr(request, "model", None)
        if model is None:
            return np.full(n, state.row_of(self.arena.default_id),
                           np.int32), None
        if isinstance(model, str):
            return np.full(n, state.row_of(model), np.int32), model
        # host-sync: ingest routing — caller-owned host id array.
        ids = np.asarray(model, dtype=object)
        if len(ids) != n:
            raise ValueError(
                f"request.model has {len(ids)} rows, request has {n}"
            )
        # Rows whose request carried no model id (a mixed coalesced batch
        # of routed and unrouted requests) score the default model.
        none_mask = np.frompyfunc(lambda v: v is None, 1, 1)(ids)
        if none_mask.any():
            ids = ids.copy()
            ids[none_mask.astype(bool)] = self.arena.default_id
        return state.rows_for(ids), None

    def _stage(self, request: ScoringRequest, bucket: int, n: int,
               state: _ArenaState):
        """Validate + pad features, resolve model slots, and join entity
        keys per tenant into GLOBAL arena indices.  Unknown entities map
        to the owning model's zero row (counted host-side as
        ``serving.cold_entities`` — the arena staging already walks the
        keys, so the count is free and the device program carries no cold
        logic at all)."""
        feats: Dict[str, object] = {}
        for c in self.arena.coords:
            if c.shard in feats:
                continue
            s = self.request_spec[c.shard]
            leaf = request.features.get(c.shard)
            if leaf is None:
                raise ValueError(f"request is missing shard {c.shard!r}")
            if s.dense:
                # host-sync: request ingest — coercing caller-owned rows
                # to upload-ready numpy (no device data involved).
                x = np.asarray(leaf, np.float32)
                if x.shape != (n, s.dim):
                    raise ValueError(
                        f"shard {c.shard!r}: got {x.shape}, want {(n, s.dim)}"
                    )
                feats[c.shard] = _pad_rows(x, bucket)
            else:
                ids, vals = leaf
                # host-sync: request ingest — same coercion, sparse leaves.
                ids = np.asarray(ids, np.int32)
                vals = np.asarray(vals, np.float32)
                if ids.shape != (n, s.nnz) or vals.shape != (n, s.nnz):
                    raise ValueError(
                        f"shard {c.shard!r}: got {ids.shape}/{vals.shape}, "
                        f"want {(n, s.nnz)}"
                    )
                feats[c.shard] = (
                    _pad_rows(ids, bucket), _pad_rows(vals, bucket)
                )
        rows, scalar_id = self._resolve_rows(request, n, state)
        gidx: Dict[str, np.ndarray] = {}
        cold: Dict[str, int] = {}
        for c in self.arena.coords:
            if c.kind != "random":
                continue
            keys = request.entity_ids.get(c.column)
            if keys is None:
                raise ValueError(
                    f"request is missing id column {c.column!r}"
                )
            # host-sync: request ingest — the key->row join against each
            # tenant's vocabulary (host searchsorted), then base offsets.
            keys = np.asarray(keys)
            local = np.empty(n, np.int32)
            if scalar_id is not None or len(state.slots) == 1:
                mid = scalar_id or next(iter(state.slots))
                local[:] = entity_index_for(
                    keys, state.slots[mid].vocab[c.name]
                )
            else:
                for r in np.unique(rows):
                    mask = rows == r
                    vocab = state.slots[state.id_of_row[int(r)]].vocab
                    local[mask] = entity_index_for(keys[mask],
                                                   vocab[c.name])
            base = state.base[c.name][rows]
            zero = state.zero[c.name][rows]
            cold_mask = local < 0
            cold[c.name] = int(cold_mask.sum())
            g = np.where(cold_mask, zero, base + local).astype(np.int32)
            gidx[c.name] = _pad_rows(g, bucket)
        offset = (
            np.zeros(bucket, np.float32) if request.offset is None
            else _pad_rows(
                # host-sync: request ingest — offset coercion, host data.
                np.asarray(request.offset, np.float32), bucket
            )
        )
        return feats, gidx, _pad_rows(rows, bucket), offset, cold

    # -- scoring -----------------------------------------------------------
    def score_batch(self, request: ScoringRequest) -> np.ndarray:
        """One compiled dispatch + ONE host sync, any mix of hosted
        models in the batch; oversize requests chunk like GameScorer."""
        n = request.num_rows
        if n == 0:
            return np.zeros(0, np.float32)
        if n > self.max_bucket:
            return np.concatenate([
                self.score_batch(slice_request(request, lo,
                                               min(lo + self.max_bucket, n)))
                for lo in range(0, n, self.max_bucket)
            ])
        return self._score_padded(request, self.bucket_for(n), n)

    def _score_padded(self, request: ScoringRequest, bucket: int,
                      n: int) -> np.ndarray:
        t0 = time.monotonic()
        # ONE read of the published triple (see __init__).
        tables, state, programs = self._serving
        program = self._program(bucket, "request", tables, programs)
        feats, gidx, mslot, offset, cold = self._stage(
            request, bucket, n, state
        )
        placed = self._place(feats, gidx, mslot, offset, n)
        out = program(tables, *placed)
        # host-sync: response egress — THE one per-batch fetch (cold
        # counts came free at ingest, so only scores ride it).
        fetched = jax.device_get(out)
        scores = np.array(fetched, copy=True)
        t = self.telemetry
        t.counter("serving.host_syncs").inc()
        t.counter("serving.batches", bucket=bucket).inc()
        t.counter("serving.rows").inc(n)
        t.histogram("serving.batch_rows").observe(n)
        t.histogram("serving.bucket_occupancy", bucket=bucket).observe(
            n / bucket
        )
        t.histogram("serving.padded_fraction").observe((bucket - n) / bucket)
        t.histogram("serving.score_seconds").observe(time.monotonic() - t0)
        for name, count in cold.items():
            if count:
                t.counter("serving.cold_entities", coordinate=name).inc(
                    count
                )
        return scores[:n]

    # -- model lifecycle ---------------------------------------------------
    def _republish(self, grew: bool) -> None:
        """Publish the arena's new (tables, state) — and, after a growth
        rebuild, a freshly compiled ladder — in one assignment."""
        programs = self._programs
        if grew:
            programs = {}
            if self._warm:
                for b in self.buckets:
                    self._compile(b, "request", self.arena.tables, programs)
            self._programs = programs
        self._serving = (self.arena.tables, self.arena.state, programs)

    def add_model(self, model_id: str, model: GameModel) -> None:
        """Onboard a tenant under live traffic: slice scatter + one
        published snapshot; in-flight batches finish on the tables they
        captured — zero requests dropped, zero recompiles unless the
        arena itself had to grow."""
        with self._swap_lock:
            grew = self.arena.onboard(model_id, model)
            self._republish(grew)

    def retire_model(self, model_id: str) -> None:
        with self._swap_lock:
            self.arena.retire(model_id)
            self._republish(False)

    def swap_model(self, model: GameModel, model_id: Optional[str] = None,
                   table_dtype: Optional[str] = None) -> None:
        """Hot-swap ONE tenant's slice (the GameScorer signature plus
        ``model_id``; None targets the default model, which is what the
        single-model rollout/canary machinery passes).  A dtype-mismatched
        publish refuses exactly like GameScorer's gate — the decode is
        baked into the shared ladder, so one tenant cannot change it."""
        if table_dtype is not None and table_dtype != self.table_dtype:
            raise ValueError(
                f"swap_model: model published at table dtype "
                f"{table_dtype!r} but this arena's warmed programs decode "
                f"{self.table_dtype!r}; the storage tier is baked into the "
                "compiled bucket ladder — rebuild the arena to change it"
            )
        with self._swap_lock:
            mid = model_id or self.arena.default_id
            grew = self.arena.refresh(mid, model)
            self._republish(grew)
            self.telemetry.counter("serving.swaps").inc()

    def sync_models(self, models: Dict[str, GameModel]) -> None:
        """Converge the hosted set onto ``models`` (respawn/rejoin): new
        ids onboard, known ids refresh, absent ids retire."""
        with self._swap_lock:
            grew = False
            for mid, model in models.items():
                if mid in self.arena.slots:
                    grew |= self.arena.refresh(mid, model)
                else:
                    grew |= self.arena.onboard(mid, model)
            for mid in list(self.arena.slots):
                if mid not in models and len(self.arena.slots) > 1:
                    self.arena.retire(mid)
            self._republish(grew)
