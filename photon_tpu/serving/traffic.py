"""Seeded traffic generation + replay for the serving fleet bench/driver.

The PR 9 serving bench drove a seeded GEOMETRIC request-size stream over
consecutive row windows — a fine microbench arrival model and nothing like
production traffic.  This module generates the replayable traffic the
fleet tier is measured under (1612.01437's framing: at scale, the system
overheads around the math dominate — so the bench must model the traffic
that creates them):

- **Power-law entity popularity.**  Each request belongs to one entity
  ("user") drawn from a seeded Zipf-like distribution over the dataset's
  entity vocabulary (rank weight ``(rank+1)^-alpha``); its rows are that
  entity's dataset rows, resampled to the request size.  Hot entities
  dominate exactly the way production key distributions do.
- **Diurnal ramp.**  Arrival times follow a shaped intensity over the
  replay horizon (``1 + amplitude·sin²(π·t/T)`` — trough at the edges,
  peak mid-replay), so offered load sweeps through the fleet's saturation
  point instead of holding one rate.
- **Cold-start storm.**  A contiguous segment of requests whose entity
  keys are OUTSIDE every coordinate's vocabulary, arriving in a burst —
  the new-user stampede that must ride the serving zero-row fallback
  (``serving.cold_entities``) without recompiling or shedding the world.

``popularity="geometric"`` reproduces the PR 9 stream exactly (sizes from
:func:`photon_tpu.drivers.serve_game.request_sizes`, consecutive row
windows) so the old distribution stays available for bench continuity
(``serve_game --traffic geometric``).

Replay: :func:`replay_open_loop` submits on the generated schedule (the
offered-load model — sheds and deadline misses are the system's problem),
:func:`run_closed_loop_outcomes` drives concurrent closed-loop clients
(the capacity-measurement model).  Both return per-request
:class:`Outcome` records instead of raising on sheds.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from concurrent.futures import wait as futures_wait
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_tpu.serving.router import RequestShedError
from photon_tpu.serving.scorer import ScoringRequest, request_from_dataset


@dataclasses.dataclass(frozen=True)
class TrafficSpec:
    """One replayable traffic shape (fully determined by its fields +
    the dataset/model it is generated against)."""

    requests: int = 256
    mean_rows: float = 8.0
    max_rows: int = 64
    popularity: str = "powerlaw"  # "powerlaw" | "geometric"
    alpha: float = 1.1  # popularity exponent (rank^-alpha)
    ramp: str = "diurnal"  # "diurnal" | "flat"
    ramp_amplitude: float = 1.0  # peak rate = (1 + amplitude) x trough
    storm_frac: float = 0.0  # fraction of requests in the cold-start storm
    storm_at: float = 0.6  # storm segment start (fraction of the stream)
    target_qps: Optional[float] = None  # None = no arrival schedule
    deadline_ms: Optional[float] = None  # per-request budget (None = none)
    seed: int = 0
    # A/B experiment splits over a multi-model fleet: arm (tenant model
    # id) -> weight.  Each request's USER hashes to one arm —
    # hash(seed:user) → [0,1) against the cumulative weights — so an
    # entity sees one consistent model for the whole replay, assignment is
    # deterministic under the seed, and the split never perturbs the rng
    # stream (the PR 9 byte-exactness contract).  None = no splits.
    splits: Optional[Dict[str, float]] = None


def split_arm_for(seed: int, user_key, splits: Dict[str, float]) -> str:
    """Deterministic hash-of-user arm assignment: the same (seed, user)
    always lands the same arm, independent of request order and of every
    other draw — re-running a replay reproduces the experiment exactly."""
    if not splits:
        raise ValueError("split_arm_for needs a non-empty splits map")
    digest = hashlib.md5(f"{seed}:{user_key}".encode()).hexdigest()
    u = int(digest, 16) / float(1 << 128)
    total = float(sum(splits.values()))
    if total <= 0:
        raise ValueError("split weights must sum to a positive value")
    acc = 0.0
    for arm, weight in splits.items():
        acc += float(weight) / total
        if u < acc:
            return arm
    return arm  # float-roundoff tail lands in the last arm


@dataclasses.dataclass(frozen=True)
class TimedRequest:
    at_s: float
    request: ScoringRequest
    deadline_s: Optional[float]
    kind: str  # "normal" | "storm"
    arm: Optional[str] = None  # split arm (tenant model id), None = unsplit


@dataclasses.dataclass(frozen=True)
class Traffic:
    items: List[TimedRequest]
    spec: TrafficSpec
    duration_s: float

    @property
    def requests(self) -> List[ScoringRequest]:
        return [item.request for item in self.items]


@dataclasses.dataclass
class Outcome:
    """What happened to one replayed request.  ``finished_at_s`` is the
    completion time relative to the replay's start — the windowed-QPS
    measurements (outage/recovery analysis in the chaos bench) cut on it."""

    status: str  # "ok" | "shed" | "error"
    scores: Optional[np.ndarray]
    latency_s: Optional[float]
    item: TimedRequest
    reason: str = ""
    finished_at_s: Optional[float] = None


def _take_request(whole: ScoringRequest, rows: np.ndarray) -> ScoringRequest:
    def take(leaf):
        if isinstance(leaf, tuple):
            return tuple(a[rows] for a in leaf)
        return leaf[rows]

    return ScoringRequest(
        features={k: take(v) for k, v in whole.features.items()},
        entity_ids={k: v[rows] for k, v in whole.entity_ids.items()},
        offset=None if whole.offset is None else whole.offset[rows],
    )


def _unknown_keys(vocab: np.ndarray, n: int, salt: int) -> np.ndarray:
    """``n`` keys guaranteed OUTSIDE ``vocab`` (the cold-start identities),
    deterministic per salt so a regenerated traffic matches."""
    if vocab.dtype.kind in "iu":
        base = (int(vocab.max()) + 1 if len(vocab) else 0) + salt * n
        return np.arange(base, base + n, dtype=vocab.dtype)
    return np.asarray([f"zz-cold-{salt}-{i}" for i in range(n)])


def geometric_sizes(n_requests: int, mean: float, cap: int,
                    rng: np.random.Generator) -> np.ndarray:
    """Long-tailed request-size draw (geometric, clipped to ``[1, cap]``)
    — THE size distribution, shared with
    :func:`photon_tpu.drivers.serve_game.request_sizes` so the measured
    arrival pattern is the served one.  MUST stay the first draw on a
    freshly seeded ``rng``: that is what keeps ``--traffic geometric``
    byte-exact with the PR 9 stream (pinned by test)."""
    p = min(1.0, max(1.0 / max(mean, 1.0), 1e-6))
    return np.clip(rng.geometric(p, size=n_requests), 1, max(1, cap))


def _arrival_times(n: int, duration_s: float, spec: TrafficSpec) -> np.ndarray:
    """Request arrival offsets over ``[0, duration_s]`` shaped by the ramp:
    inverse-CDF placement against the intensity profile, so request density
    follows the diurnal curve deterministically."""
    if spec.ramp == "flat" or spec.ramp_amplitude <= 0:
        return np.linspace(0.0, duration_s, n, endpoint=False)
    grid = np.linspace(0.0, 1.0, 1025)
    intensity = 1.0 + spec.ramp_amplitude * np.sin(np.pi * grid) ** 2
    cdf = np.concatenate([[0.0], np.cumsum(
        (intensity[1:] + intensity[:-1]) * 0.5 * np.diff(grid)
    )])
    cdf /= cdf[-1]
    quantiles = (np.arange(n) + 0.5) / n
    return np.interp(quantiles, cdf, grid) * duration_s


def generate_traffic(data, model, spec: TrafficSpec) -> Traffic:
    """Deterministic (seeded) replayable traffic over one dataset+model."""
    from photon_tpu.game.model import RandomEffectModel

    rng = np.random.default_rng(spec.seed)
    n = int(spec.requests)
    whole = request_from_dataset(data, model)
    n_rows = data.num_examples

    sizes = geometric_sizes(n, spec.mean_rows, spec.max_rows, rng)

    user_keys: List[object] = list(range(n))  # split-arm hash identities
    if spec.popularity == "geometric":
        # PR 9 compatibility stream: consecutive row windows.
        row_sets = []
        pos = 0
        for size in sizes:
            row_sets.append(np.arange(pos, pos + int(size)) % n_rows)
            pos = (pos + int(size)) % n_rows
    elif spec.popularity == "powerlaw":
        random_coords = [
            c for c in model.coordinates.values()
            if isinstance(c, RandomEffectModel)
        ]
        if not random_coords:
            raise ValueError(
                "powerlaw traffic needs a random-effect coordinate to "
                "define entity popularity; use popularity='geometric'"
            )
        col = random_coords[0].entity_column
        uniq, inv = np.unique(data.id_columns[col], return_inverse=True)
        order = np.argsort(inv, kind="stable")
        counts = np.bincount(inv, minlength=len(uniq))
        starts = np.concatenate([[0], np.cumsum(counts)])
        # Popularity rank is a seeded permutation of the vocabulary (which
        # entity is "hot" is random; HOW hot follows the power law).
        rank_of = rng.permutation(len(uniq))
        weights = (rank_of + 1.0) ** -spec.alpha
        weights /= weights.sum()
        entities = rng.choice(len(uniq), size=n, p=weights)
        # The request's USER is its drawn entity — the split-arm identity
        # (cold-start storms keep the original user: a stormed request is
        # still that user's traffic, just with unseen ids).
        user_keys = [uniq[e] for e in entities]
        row_sets = []
        for e, size in zip(entities, sizes):
            mine = order[starts[e]: starts[e + 1]]
            row_sets.append(rng.choice(mine, size=int(size), replace=True))
    else:
        raise ValueError(f"unknown popularity model {spec.popularity!r}")

    storm_n = int(round(spec.storm_frac * n))
    storm_lo = min(int(spec.storm_at * n), n - storm_n)
    storm = set(range(storm_lo, storm_lo + storm_n))

    vocabs = {
        c.entity_column: np.asarray(c.keys)
        for c in model.coordinates.values()
        if isinstance(c, RandomEffectModel)
    }
    requests: List[ScoringRequest] = []
    for i, rows in enumerate(row_sets):
        req = _take_request(whole, rows)
        if i in storm:
            # Cold-start identities: every id column swapped for keys no
            # coordinate has seen — the zero-row fallback path.
            req = ScoringRequest(
                features=req.features,
                entity_ids={
                    col: _unknown_keys(vocabs.get(col, keys), len(keys), i)
                    for col, keys in req.entity_ids.items()
                },
                offset=req.offset,
            )
        requests.append(req)

    arms: List[Optional[str]] = [None] * n
    if spec.splits:
        # Arm assignment AFTER every rng draw (pure hashing — the rng
        # stream stays byte-exact with unsplit traffic); stamping replaces
        # the frozen request with one routed at its arm's tenant model.
        for i in range(n):
            arms[i] = split_arm_for(spec.seed, user_keys[i], spec.splits)
            requests[i] = dataclasses.replace(requests[i], model=arms[i])

    if spec.target_qps:
        duration = n / float(spec.target_qps)
        at = _arrival_times(n, duration, spec)
        if storm_n:
            # The storm arrives as a BURST: its segment compresses to a
            # quarter of its scheduled span, anchored at the segment start.
            lo, hi = storm_lo, storm_lo + storm_n
            span = at[hi - 1] - at[lo] if hi - 1 > lo else 0.0
            at = at.copy()
            at[lo:hi] = at[lo] + np.linspace(0.0, span * 0.25, hi - lo)
            at = np.maximum.accumulate(at)
    else:
        duration = 0.0
        at = np.zeros(n)

    deadline_s = None if spec.deadline_ms is None else spec.deadline_ms / 1e3
    items = [
        TimedRequest(
            at_s=float(at[i]), request=requests[i], deadline_s=deadline_s,
            kind="storm" if i in storm else "normal", arm=arms[i],
        )
        for i in range(n)
    ]
    return Traffic(items=items, spec=spec, duration_s=float(duration))


# -- replay ------------------------------------------------------------------

def replay_open_loop(
    submit: Callable[..., "object"],
    traffic: Traffic,
    speed: float = 1.0,
    timeout_s: float = 120.0,
) -> List[Outcome]:
    """OPEN-loop replay: submit each request at its scheduled arrival time
    regardless of completions (the offered-load model — queueing and
    shedding are the system's problem, not the generator's).  ``submit``
    is ``router/fleet.submit(request, deadline_s=...)``; a synchronous
    :class:`RequestShedError` (admission fast-fail) becomes a ``shed``
    outcome.  Latency is measured submit→resolve via done-callbacks."""
    items = traffic.items
    outcomes: List[Optional[Outcome]] = [None] * len(items)
    futures = []
    start = time.monotonic()
    for i, item in enumerate(items):
        delay = start + item.at_s / speed - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t0 = time.monotonic()
        try:
            fut = submit(item.request, deadline_s=item.deadline_s)
        except RequestShedError as e:
            outcomes[i] = Outcome("shed", None, None, item, e.reason,
                                  finished_at_s=t0 - start)
            continue

        def _collect(fut, i=i, item=item, t0=t0):
            now = time.monotonic()
            lat = now - t0
            try:
                outcomes[i] = Outcome("ok", fut.result(), lat, item,
                                      finished_at_s=now - start)
            except RequestShedError as e:
                outcomes[i] = Outcome("shed", None, lat, item, e.reason,
                                      finished_at_s=now - start)
            except BaseException as e:  # noqa: BLE001 — recorded, not raised
                outcomes[i] = Outcome(
                    "error", None, lat, item, f"{type(e).__name__}: {e}",
                    finished_at_s=now - start,
                )

        fut.add_done_callback(_collect)
        futures.append(fut)
    futures_wait(futures, timeout=timeout_s)
    for i, out in enumerate(outcomes):
        if out is None:
            outcomes[i] = Outcome("error", None, None, items[i], "timeout")
    return outcomes  # type: ignore[return-value]


def run_closed_loop_outcomes(
    score_fn_factory: Callable[[int], Callable[[TimedRequest], np.ndarray]],
    items: List[TimedRequest],
    clients: int = 4,
):
    """CLOSED-loop drive: ``clients`` workers, each scoring its next
    request only after the previous response lands (the capacity-
    measurement model).  ``score_fn_factory(tid)`` builds one synchronous
    scoring callable per worker — a router lambda, or one
    :class:`~photon_tpu.serving.transport.ScoringClient` per thread (a
    client connection is a serial exchange stream).  Returns
    ``(outcomes, wall_s)`` with outcomes in request order."""
    outcomes: List[Optional[Outcome]] = [None] * len(items)
    clients = max(1, min(int(clients), len(items) or 1))

    start = time.monotonic()

    def worker(tid: int) -> None:
        fn = score_fn_factory(tid)
        for i in range(tid, len(items), clients):
            item = items[i]
            t0 = time.monotonic()
            try:
                scores = fn(item)
                outcomes[i] = Outcome(
                    "ok", scores, time.monotonic() - t0, item,
                    finished_at_s=time.monotonic() - start,
                )
            except RequestShedError as e:
                outcomes[i] = Outcome(
                    "shed", None, time.monotonic() - t0, item, e.reason,
                    finished_at_s=time.monotonic() - start,
                )
            except BaseException as e:  # noqa: BLE001 — recorded per request
                outcomes[i] = Outcome(
                    "error", None, time.monotonic() - t0, item,
                    f"{type(e).__name__}: {e}",
                    finished_at_s=time.monotonic() - start,
                )

    threads = [
        threading.Thread(target=worker, args=(t,), daemon=True)
        for t in range(clients)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outcomes, time.monotonic() - t0
