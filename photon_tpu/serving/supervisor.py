"""Replica supervision: health probes, resurrection, quarantine.

PR 12's fleet sheds AROUND failure (a dead replica's work reroutes, the
fleet shrinks); this module makes it heal FROM failure (the ISSUE 13
tentpole).  One supervisor watches a fleet's replicas and closes the loop:

- **Detection.**  Every probe interval, each live replica is checked four
  ways: child exit code (``poll_exit`` — a subprocess replica that
  hard-exited), watchdog heartbeat age (work pending but no scoring
  progress — the mid-batch wedge, ``fault/watchdog.py`` machinery), a
  liveness ping frame with a hard deadline (subprocess control channel,
  via ``call_with_timeout``), and a tiny KNOWN-ANSWER score probe checked
  against the host oracle (a replica that answers quickly but wrongly is
  as dead as one that doesn't answer).
- **Declaration.**  An unhealthy replica is marked dead through the
  router (``serving.replica_deaths{replica,cause}``) and its pending
  futures are failed with ``ReplicaDeadError`` — they reroute through the
  existing exactly-once path, so a hang costs its callers a reroute, not
  a lost response.
- **Resurrection.**  The supervisor re-spawns with capped exponential
  backoff (the ``fault/retry.py`` policy shape), re-warms the bucket
  ladder, then gates the return through the PR 12 canary machinery:
  mirrored recent traffic (or a synthetic known-answer probe) replays
  through the rejoining replica against the CURRENT model's host oracle,
  and only parity ≤ ``rejoin_tol`` readmits it (``router.revive``).  The
  fleet's model version is re-checked around the probe, so a replica
  resurrected mid-rollout comes back on the model the fleet serves NOW,
  never the one it died on.
- **Quarantine.**  A flapping replica — ``max_deaths`` deaths inside
  ``flap_window_s`` — is quarantined permanently
  (``serving.replica_quarantined``): a replica that keeps dying is a
  capacity lie, and readmitting it again and again turns every death into
  fleet-wide reroute churn.

Timeline: every supervision event lands a monotonic
``serving.supervisor_step{replica,phase}`` gauge (``died-<cause>``,
``respawn``, ``rejoin-probe``, ``rejoined``, ``respawn-failed``,
``quarantined``) — the telemetry report renders them in order.

Residency contract (``tools/check_host_sync.py`` guards this module): the
supervisor is pure host-side control; its only sanctioned fetches are the
probe-oracle parity comparisons, which exist precisely to score on host.
"""

from __future__ import annotations

import dataclasses
import itertools
import random
import threading
import time
from collections import deque
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Dict, Optional, Tuple

import numpy as np

from photon_tpu.fault.retry import RetryPolicy
from photon_tpu.fault.watchdog import IOStallTimeoutError, age_of
from photon_tpu.serving.router import (  # noqa: F401 — parity_worst is
    # re-exported here (the supervision-facing name tests/callers use).
    ReplicaDeadError,
    host_score_request,
    parity_worst,
)
from photon_tpu.serving.scorer import ScoringRequest


class RejoinParityError(RuntimeError):
    """A resurrected replica's rejoin probe disagreed with the current
    model's host oracle; it was NOT readmitted (the attempt counts as a
    respawn failure and backs off)."""


@dataclasses.dataclass(frozen=True)
class SupervisorPolicy:
    """Supervision knobs.

    ``probe_interval_s`` — seconds between health passes.
    ``probe_deadline_s`` — hard deadline on the ping and the known-answer
    probe; a probe that misses it declares the replica hung.
    ``hang_timeout_s`` — heartbeat age past which a replica WITH pending
    work is declared hung even between probes.
    ``lease_s`` — the membership lease (ISSUE 19): a replica stays a
    member as long as SOME ping succeeded within the last ``lease_s``
    seconds.  Ping failures inside the window are tolerated misses
    (transient partition / dropped connection — the replica rejoins
    silently); only lease expiry declares death (cause ``"lease"``).
    ``parity_tol`` — known-answer and rejoin probes vs the host oracle.
    ``respawn_base_s``/``respawn_max_s``/``respawn_jitter`` — the capped
    exponential backoff between resurrection attempts (the
    ``fault/retry.py`` policy shape).
    ``max_deaths``/``flap_window_s`` — the permanent-quarantine verdict:
    ``max_deaths`` deaths inside the window.
    ``max_respawn_failures`` — consecutive failed resurrection attempts
    (spawn faults, rejoin-probe failures) before the replica is
    quarantined like a flapper: a spawn path that never succeeds is a
    capacity lie too, and retrying it forever is a hot loop.
    ``resurrect`` — False supervises (detect + declare) without healing.
    """

    probe_interval_s: float = 0.5
    probe_deadline_s: float = 5.0
    probe_rows: int = 2
    hang_timeout_s: float = 5.0
    lease_s: float = 15.0
    parity_tol: float = 1e-3
    respawn_base_s: float = 0.05
    respawn_max_s: float = 2.0
    respawn_jitter: float = 0.25
    max_deaths: int = 3
    flap_window_s: float = 60.0
    max_respawn_failures: int = 64
    resurrect: bool = True


def probe_request_for(model, request_spec, rows: int = 2,
                      seed: int = 0) -> ScoringRequest:
    """A tiny deterministic known-answer probe request built from the
    request spec: seeded feature rows, entity keys drawn from each random
    coordinate's own vocabulary (so the gather path — not just the
    fixed-effect path — is probed).  The same (model, spec, seed) always
    builds the same probe, so its oracle answer is a known answer."""
    from photon_tpu.game.model import RandomEffectModel

    rng = np.random.default_rng(seed)
    features: Dict[str, object] = {}
    entity_ids: Dict[str, np.ndarray] = {}
    for coord in model.coordinates.values():
        spec = request_spec[coord.shard_name]
        if coord.shard_name not in features:
            if spec.dense:
                features[coord.shard_name] = rng.standard_normal(
                    (rows, spec.dim)
                ).astype(np.float32)
            else:
                features[coord.shard_name] = (
                    rng.integers(0, spec.dim, (rows, spec.nnz),
                                 dtype=np.int32),
                    rng.standard_normal((rows, spec.nnz)).astype(np.float32),
                )
        if isinstance(coord, RandomEffectModel):
            # host-sync: probe construction — entity vocabularies are host
            # numpy by construction (build-time, not the serving hot path).
            keys = np.asarray(coord.keys)
            entity_ids[coord.entity_column] = keys[
                rng.integers(0, len(keys), rows)
            ]
    return ScoringRequest(features=features, entity_ids=entity_ids,
                          offset=None)


class ReplicaSupervisor:
    """Health-checked supervision + canary-gated resurrection for one
    :class:`~photon_tpu.serving.fleet.ServingFleet`.

    ``check_once()`` is one full supervision pass (tests drive it
    directly, deterministically); ``start()`` runs it on a background
    thread every ``probe_interval_s``.  The supervisor never blocks the
    serving path: probes ride the replicas' own batchers, and declaration
    /resurrection touch only router bookkeeping and the dead replica."""

    def __init__(self, fleet, policy: Optional[SupervisorPolicy] = None,
                 telemetry=None, logger=None,
                 clock=time.monotonic):
        from photon_tpu.telemetry import NULL_SESSION

        self.fleet = fleet
        self.router = fleet.router
        self.policy = policy or SupervisorPolicy()
        self.telemetry = telemetry or fleet.telemetry or NULL_SESSION
        self.logger = logger
        self.clock = clock
        self._seq = itertools.count(1)
        self._rng = random.Random(0)
        self._backoff = RetryPolicy(
            attempts=1_000_000,  # max_respawn_failures bounds attempts
            base_delay_s=self.policy.respawn_base_s,
            max_delay_s=self.policy.respawn_max_s,
            jitter=self.policy.respawn_jitter,
        )
        self._noted: set = set()  # (replica_id, generation) deaths recorded
        # Per-replica lease expiry instants (ISSUE 19): renewed by every
        # successful ping, popped on death so a rejoined replica starts a
        # fresh lease.
        self._leases: Dict[str, float] = {}
        self._deaths: Dict[str, deque] = {}
        self._attempts: Dict[str, Tuple[int, float]] = {}  # id -> (n, at)
        # Per probed tenant: model_id (None = single-model fleet) ->
        # (model, request, want).  A multi-model fleet rotates the probed
        # tenant across passes, so the cache holds one oracle per tenant.
        self._probe_cache: Dict[Optional[str], Tuple] = {}
        self._probe_rr = 0  # round-robin cursor over hosted tenants
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- bookkeeping ----------------------------------------------------------
    def _mark(self, replica_id: str, phase: str) -> None:
        """Timeline breadcrumb, same shape as the rollout timeline: a
        monotonic sequence number per (replica, phase) event."""
        self.telemetry.gauge(
            "serving.supervisor_step", replica=replica_id, phase=phase
        ).set(next(self._seq))

    def _known_answer(self, model, model_id: Optional[str] = None):
        """``(request, want)`` for the health probe: a tiny SYNTHETIC
        request (deterministic, ``probe_rows`` rows — mirrored live
        requests can be max-batch sized, too heavy to score on host every
        probe pass) with its host-oracle answer computed ONCE per model.
        ``model_id`` stamps the probe for a multi-model fleet so the
        replica scores it against that tenant's arena slice."""
        cached = self._probe_cache.get(model_id)
        if cached is not None and cached[0] is model:
            return cached[1], cached[2]
        request = probe_request_for(
            model, self._request_spec(), rows=self.policy.probe_rows
        )
        if model_id is not None:
            request = dataclasses.replace(request, model=model_id)
        want = host_score_request(model, request)
        self._probe_cache[model_id] = (model, request, want)
        return request, want

    def _probe_target(self):
        """Which model this pass's known-answer probe scores: a
        single-model fleet probes THE model; a multi-model fleet rotates
        the probed tenant across passes, so every hosted slice gets
        periodic known-answer coverage without multiplying probe cost."""
        model, version = self.fleet.current_model()
        hosted = getattr(self.fleet, "models", None)
        if hosted:
            ids = list(hosted)
            mid = ids[self._probe_rr % len(ids)]
            return hosted[mid], mid, version
        return model, None, version

    def _request_spec(self):
        for replica in self.router.replicas:
            spec = getattr(replica.scorer, "request_spec", None)
            if spec:
                return spec
        raise RuntimeError("no replica exposes a request spec to probe with")

    # -- one supervision pass -------------------------------------------------
    def check_once(self) -> None:
        # Known-answer parity failures are COLLECTED, not declared inline:
        # when EVERY live replica fails parity right after a swap, the
        # fleet — not N replicas — regressed, and the fix is ONE rollout
        # rollback to the predecessor artifact instead of N quarantines
        # (ROADMAP fleet edge (d); ISSUE 15 satellite).  Crash/hang causes
        # stay replica-local and declare immediately inside _health_check.
        parity: dict = {}
        self._probe_rr += 1  # rotate the probed tenant once per pass
        for replica in self.router.replicas:
            if replica.quarantined:
                continue
            if replica.alive:
                verdict = self._health_check(replica)
                if verdict is not None:
                    parity[replica] = verdict
                elif replica.alive:
                    self._pull_stats(replica)
            # Crash/hang declarations keep the PR 13 per-replica
            # interleaving: teardown + resurrection happen here, before
            # the next replica's probes — a replica that just absorbed a
            # dead sibling's rerouted work gets the resurrection window to
            # complete a batch before its own heartbeat is judged.
            if not replica.alive and not replica.quarantined:
                self._note_death(replica)
                if self.policy.resurrect and not replica.quarantined:
                    self._maybe_resurrect(replica)
        live = {
            r for r in self.router.replicas
            if r.alive and not r.quarantined
        }
        if parity and set(parity) == live:
            _model, version = self.fleet.current_model()
            if self._fleet_rollback(version):
                return
            if self.fleet.current_model()[1] != version:
                # A publish landed while the rollback waited for the
                # fleet's publish lock: every parity verdict was collected
                # against a model nobody serves any more — drop them and
                # re-probe next pass instead of declaring on stale
                # evidence.
                return
        for replica, (cause, detail) in parity.items():
            self._declare(replica, cause, detail)
            if not replica.alive and not replica.quarantined:
                if self.policy.resurrect and not replica.quarantined:
                    self._maybe_resurrect(replica)

    def _fleet_rollback(self, expected_version) -> bool:
        """Every live replica failed its known-answer probe: republish the
        predecessor artifact fleet-wide (``ServingFleet.
        rollback_to_previous``).  Returns False when there is no
        predecessor (nothing ever rolled out) or the model version moved
        past ``expected_version`` (the probe evidence is stale) — the
        caller then declares per-replica or drops the stale verdicts."""
        rollback = getattr(self.fleet, "rollback_to_previous", None)
        if rollback is None or not rollback(expected_version):
            return False
        # The model changed: drop the cached probe oracles so the next
        # pass probes against the restored artifact.
        self._probe_cache = {}
        for replica in self.router.replicas:
            if replica.alive and not replica.quarantined:
                self._mark(replica.replica_id, "fleet-rollback")
        if self.logger is not None:
            self.logger.warning(
                "supervisor: every replica failed its known-answer probe "
                "after a swap — rolled the fleet back to the predecessor "
                "artifact (one rollback, zero quarantines)"
            )
        return True

    def _pull_stats(self, replica) -> None:
        """Child-telemetry aggregation (ISSUE 14 satellite / ROADMAP fleet
        edge (e)): each healthy pass also pulls a subprocess replica's
        scorer-level ``serving.*`` counters into the parent registry
        (``SubprocessReplica.pull_stats`` — delta merge, idempotent).
        Advisory only: a failed pull never declares a replica — liveness
        verdicts belong to the probes above."""
        pull = getattr(replica, "pull_stats", None)
        if pull is None:
            return
        try:
            pull(self.policy.probe_deadline_s)
        except Exception:  # noqa: BLE001 — stats must never fail a pass
            pass

    # -- detection ------------------------------------------------------------
    def _health_check(self, replica):
        # 1. Crash: the backing process hard-exited (subprocess replicas).
        code = replica.poll_exit()
        if code is not None:
            self._declare(replica, "crash",
                          f"child exited with code {code}")
            return
        # 2. Hang between probes: work is pending but the heartbeat the
        # scoring path marks around each batch has gone stale.
        age = age_of(replica.heartbeat_site)
        if (age is not None and age > self.policy.hang_timeout_s
                and replica.pending_rows() > 0):
            self._declare(replica, "hang",
                          f"no scoring progress for {age:.1f}s with "
                          f"{replica.pending_rows()} rows pending")
            return
        # 3. Liveness ping (subprocess control channel) under LEASE
        # semantics (ISSUE 19): a successful ping RENEWS the replica's
        # time-bounded lease; a failed one inside the lease window is a
        # MISS — tolerated, because over a real network a dropped control
        # connection or a transient partition is indistinguishable from
        # death at single-probe granularity, and a false declaration
        # spawns a twin of a live replica (the double-serve the
        # generation fence then has to catch).  Only lease EXPIRY — no
        # successful renewal for ``lease_s`` — declares.  A genuinely
        # wedged child is still caught promptly by step 2 (stale
        # heartbeat with work pending) and a hard-exited one by step 1;
        # the lease only governs the silent-network signal.
        ping = getattr(replica, "ping", None)
        if ping is not None:
            rid = replica.replica_id
            now = self.clock()
            expires = self._leases.get(rid)
            if expires is None:
                expires = now + self.policy.lease_s
                self._leases[rid] = expires
            try:
                ping(self.policy.probe_deadline_s, gen=replica.generation)
            except (IOStallTimeoutError, OSError, RuntimeError) as e:
                now = self.clock()
                if now < expires:
                    self.telemetry.counter(
                        "serving.lease_probe_misses", replica=rid
                    ).inc()
                    self.telemetry.gauge(
                        "serving.lease_remaining_s", replica=rid
                    ).set(expires - now)
                    self._mark(rid, "lease-miss")
                    # Skip the score probe too: it would ride the same
                    # partitioned link and turn one miss into a deadline
                    # pile-up.  Re-probe next pass.
                    return
                self._leases.pop(rid, None)
                self._declare(
                    replica, "lease",
                    f"lease expired ({self.policy.lease_s:g}s without a "
                    f"successful renewal): {e}",
                )
                return
            self._leases[rid] = self.clock() + self.policy.lease_s
            self.telemetry.gauge(
                "serving.lease_remaining_s", replica=rid
            ).set(self.policy.lease_s)
        # 4. Known-answer score probe vs the host oracle (rotated across
        # hosted tenants on a multi-model fleet).
        model, model_id, version = self._probe_target()
        request, want = self._known_answer(model, model_id)
        try:
            got = replica.submit(request).result(
                timeout=self.policy.probe_deadline_s
            )
        except FutureTimeoutError:
            # The probe rides the replica's OWN queue: under heavy load a
            # saturated-but-progressing replica can miss the deadline just
            # by queueing.  Busy is not hung — only a replica whose
            # heartbeat ALSO went stale (no batch completed either) is
            # declared; otherwise a load spike would cascade into a mass
            # abandon+reroute and, repeated, a permanent quarantine of a
            # perfectly healthy fleet.
            age = age_of(replica.heartbeat_site)
            if age is not None and age <= self.policy.hang_timeout_s:
                return
            self._declare(replica, "hang",
                          f"score probe missed its "
                          f"{self.policy.probe_deadline_s:g}s deadline "
                          f"with no scoring progress")
            return
        except ReplicaDeadError:
            # Already latched by the scoring path; cause rides the replica.
            self._declare(replica, replica.death_cause or "crash",
                          "probe found the replica dead")
            return
        except Exception as e:  # noqa: BLE001 — any probe failure is fatal
            self._declare(replica, "error", f"score probe failed: {e}")
            return
        if self.fleet.current_model()[1] != version:
            return  # a rollout landed mid-probe: the oracle is stale
        worst = parity_worst(got, want)
        # Per-codec parity histogram (ISSUE 17): known-answer probe deltas
        # labeled by the replica's serving storage tier.
        self.telemetry.histogram(
            "serving.probe_parity",
            dtype=getattr(replica.scorer, "table_dtype", "f32"),
        ).observe(worst)
        observer = getattr(self.fleet, "observer", None)
        if observer is not None:
            # Feed BOTH verdicts to the SLO monitor: the canary-parity
            # burn rate needs good probes in its denominator.
            try:
                observer.on_parity(replica.replica_id, worst)
            except Exception:  # noqa: BLE001 — observation is advisory
                pass
        if worst > self.policy.parity_tol:
            if self.fleet.rollout_in_progress():
                # Mid-rollout, different replicas LEGITIMATELY serve
                # different versions (the stagger window); a version
                # mismatch here is the rollout's job to resolve, not a
                # replica fault — declaring would kill healthy replicas
                # on every rollout.
                return None
            # DEFERRED verdict: check_once declares it per-replica unless
            # the whole fleet failed parity (→ one rollout rollback).
            return (
                "parity",
                f"known-answer probe off by {worst:.2e} "
                f"(> {self.policy.parity_tol:g})",
            )
        return None

    def _declare(self, replica, cause: str, detail: str) -> None:
        if self.logger is not None:
            self.logger.warning("supervisor: replica %s unhealthy (%s): %s",
                                replica.replica_id, cause, detail)
        self.router.mark_unhealthy(replica, cause, detail)
        self._note_death(replica)

    def _note_death(self, replica) -> None:
        """Record one death exactly once per (replica, generation): flap
        accounting, the timeline mark, teardown of whatever the dead
        replica still held (failed futures reroute), and the permanent
        quarantine verdict."""
        key = (replica.replica_id, replica.generation)
        if key in self._noted:
            return
        self._noted.add(key)
        rid = replica.replica_id
        self._leases.pop(rid, None)  # a rejoin starts a fresh lease
        cause = replica.death_cause or "error"
        # Idempotent router-side accounting: a death latched by the scoring
        # proxy outside any router dispatch (e.g. a probe submitted straight
        # to the replica) still lands its serving.replica_deaths count.
        self.router.mark_unhealthy(replica, cause, "noted by supervisor")
        now = self.clock()
        self._deaths.setdefault(rid, deque(maxlen=64)).append(now)
        self._mark(rid, f"died-{cause}")
        replica.abandon_pending(
            ReplicaDeadError(f"replica {rid} declared dead ({cause})")
        )
        kill = getattr(replica, "kill_backend", None)
        if kill is not None:
            kill()
        # Postmortem collection AFTER the kill (the child's on-disk flight
        # ring is final by then): persist the victim's last seconds next
        # to the run report and adopt its mid-flight spans as lost stubs.
        observer = getattr(self.fleet, "observer", None)
        if observer is not None:
            observer.collect_flight(replica, cause)
        window = [
            t for t in self._deaths[rid]
            if now - t <= self.policy.flap_window_s
        ]
        if len(window) >= self.policy.max_deaths:
            replica.quarantined = True
            self.telemetry.counter(
                "serving.replica_quarantined", replica=rid
            ).inc()
            self._mark(rid, "quarantined")
            if self.logger is not None:
                self.logger.warning(
                    "supervisor: replica %s quarantined permanently "
                    "(%d deaths inside %.0fs)", rid, len(window),
                    self.policy.flap_window_s,
                )

    # -- resurrection ---------------------------------------------------------
    def _maybe_resurrect(self, replica) -> None:
        rid = replica.replica_id
        attempt, not_before = self._attempts.get(rid, (0, 0.0))
        if self.clock() < not_before:
            return  # still backing off
        try:
            self._mark(rid, "respawn")
            model, version = self.fleet.current_model()
            # Re-spawn + re-warm (thread replicas re-warm against cached
            # programs — zero recompiles; subprocess replicas boot a fresh
            # warmed child from the current shared artifact).
            replica.respawn(model=model)
            # Canary-gated rejoin: mirrored recent traffic (or the
            # synthetic known-answer probe) through the rejoining replica
            # vs the CURRENT model's host oracle — dispatch readmission is
            # gated on parity exactly like a rollout canary.
            self._mark(rid, "rejoin-probe")
            probes = self.router.recent_requests() or [
                self._known_answer(model)[0]
            ]
            hosted = getattr(self.fleet, "models", None)
            for request in probes:
                # Per-tenant oracle: a mirrored request stamped with a
                # tenant id must be checked against THAT tenant's model,
                # not the fleet default.  Per-row-routed mirrors have no
                # single oracle — skip them (the synthetic probe and
                # scalar-routed mirrors cover the rejoin gate).
                probe_model = model
                req_mid = getattr(request, "model", None)
                if req_mid is not None and not isinstance(req_mid, str):
                    continue
                if isinstance(req_mid, str) and hosted:
                    probe_model = hosted.get(req_mid)
                    if probe_model is None:
                        continue  # tenant retired since the mirror
                got = replica.submit(request).result(
                    timeout=self.policy.probe_deadline_s
                )
                worst = parity_worst(
                    got, host_score_request(probe_model, request)
                )
                if worst > self.policy.parity_tol:
                    raise RejoinParityError(
                        f"rejoin probe off by {worst:.2e} "
                        f"(> {self.policy.parity_tol:g})"
                    )
            # Model-version re-sync: a rollout may have published while
            # this replica was being resurrected — it must come back on
            # the model the fleet serves NOW, never the one it died on.
            # A multi-model replica converges its whole hosted set (an
            # add/retire/per-tenant swap may have landed mid-respawn).
            current, current_version = self.fleet.current_model()
            if current_version != version:
                sync = getattr(replica.scorer, "sync_models", None)
                if hosted and sync is not None:
                    sync(dict(hosted))
                else:
                    replica.scorer.swap_model(current)
            self.router.revive(replica)
            self._attempts.pop(rid, None)
            # A rejoined member starts a fresh lease: the misses that led
            # to its death must not count against the new incarnation.
            self._leases[rid] = self.clock() + self.policy.lease_s
            self._mark(rid, "rejoined")
            if self.logger is not None:
                self.logger.info("supervisor: replica %s rejoined the "
                                 "dispatch set", rid)
        except BaseException as e:  # noqa: BLE001 — spawn/probe failures
            replica.rejoining = False
            self.telemetry.counter(
                "serving.respawn_failures", replica=rid
            ).inc()
            delay = self._backoff.delay(attempt, self._rng)
            self._attempts[rid] = (attempt + 1, self.clock() + delay)
            self._mark(rid, "respawn-failed")
            observer = getattr(self.fleet, "observer", None)
            if observer is not None:
                observer.collect_flight(replica, "respawn-failed")
            if self.logger is not None:
                self.logger.warning(
                    "supervisor: resurrecting %s failed (%s: %s); retrying "
                    "in %.2fs (attempt %d)", rid, type(e).__name__, e,
                    delay, attempt + 1,
                )
            # A spawn path that NEVER succeeds must not retry forever:
            # the flap quarantine counts deaths per generation (one per
            # failed-resurrection streak), so consecutive respawn
            # failures get their own bound.
            if attempt + 1 >= self.policy.max_respawn_failures:
                replica.quarantined = True
                self.telemetry.counter(
                    "serving.replica_quarantined", replica=rid
                ).inc()
                self._mark(rid, "quarantined")
                if self.logger is not None:
                    self.logger.warning(
                        "supervisor: replica %s quarantined after %d "
                        "consecutive failed resurrection attempts",
                        rid, attempt + 1,
                    )

    # -- lifecycle ------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.policy.probe_interval_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — supervision must outlive a
                # bad pass (one probe hiccup must not silently end
                # detection for the rest of the run).
                pass

    def start(self) -> "ReplicaSupervisor":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="photon-replica-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
