"""Device-resident GAME scorer with recompile-free bucketed micro-batching.

The hot path's residency contract (enforced by ``tools/check_host_sync.py``):
model tables live on device for the scorer's whole lifetime; each scored
batch pays exactly ONE host round-trip — the request buffers go up in one
``put_request`` placement and the ``(scores, cold_counts)`` pair comes back
in one ``jax.device_get`` (``serving.host_syncs`` == 1 per batch, pinned by
tests).  Everything between those two edges is a single pre-compiled XLA
program per bucket shape, with the request buffers DONATED on accelerators
so XLA recycles them for outputs (not on CPU, where placed buffers can
alias host memory — see ``_donate_argnums``).

Bucketing: batch sizes are padded to a small power-of-two ladder
(``buckets``, default 8 … ``max_batch``), so arrival patterns map onto
O(log max_batch) compiled programs.  :meth:`GameScorer.warmup` AOT-compiles
the whole ladder up front (``jax.jit(...).lower(...).compile()``); after
warmup a request can never trigger a compile — an off-ladder shape raises
instead of silently recompiling.  Padded rows carry entity index -1 and are
masked out of the cold-entity counts by the device-side ``n_valid`` bound;
their scores are sliced off before anything leaves the scorer.

Unknown entities: each random coordinate's table is the model's
:meth:`~photon_tpu.game.model.RandomEffectModel.serving_table` —
``[capacity, dim]`` with every row past the vocabulary all-zero — and
request rows whose entity key is outside the vocabulary gather the zero
row at index ``num_entities``, falling back to a fixed-effect-only score.
They are counted on device and surface as
``serving.cold_entities{coordinate=...}``.

Capacity headroom: tables allocate at the model's amortized-doubling
:attr:`~photon_tpu.game.model.RandomEffectModel.serving_capacity` (next
pow2 past entities + 1), and the zero-row index rides the published
serving state as a DEVICE argument — not a constant baked into the
compiled programs.  A retrained model whose grown vocabulary still fits
the served capacity therefore hot-swaps in place with zero recompiles
(the zero row just moves); only a capacity/dim change — a real
layout-shape change — still refuses and requires a new scorer.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.game.data import (
    DenseShard,
    GameDataset,
    entity_index_for,
)
from photon_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    _fixed_margins,
    serving_gather_margins,
)
from photon_tpu.parallel.mesh import abstract_like, put_request
from photon_tpu.utils import pow2_at_least

DEFAULT_MAX_BATCH = 256
DEFAULT_MIN_BUCKET = 8


def bucket_ladder(
    buckets: Optional[Tuple[int, ...]], max_batch: int, min_bucket: int
) -> Tuple[int, ...]:
    """The ONE bucket-ladder construction: an explicit ladder is
    deduped/sorted, a default one is the powers of two from
    ``min_bucket`` through ``pow2(max_batch)``.  Shared by
    :class:`GameScorer` and the subprocess replica's parent-side mirror so
    the two can never pad differently."""
    if buckets is None:
        b, ladder = max(1, pow2_at_least(min_bucket)), []
        max_bucket = pow2_at_least(max_batch)
        while b < max_bucket:
            ladder.append(b)
            b *= 2
        ladder.append(max_bucket)
        buckets = tuple(ladder)
    return tuple(sorted(set(int(b) for b in buckets)))


def padded_cost(n: int, buckets: Tuple[int, ...]) -> int:
    """Device rows an ``n``-row request actually COSTS through the bucket
    ladder: the smallest holding bucket, with oversize requests chunked
    into max-bucket slabs first (exactly what ``score_batch`` does).  The
    admission projection charges queue wait in these padded rows — padding
    costs compute too, so a raw-rows projection systematically under-
    estimates the wait and over-admits near saturation."""
    n = int(n)
    if n <= 0:
        return 0
    max_bucket = buckets[-1]
    full, rem = divmod(n, max_bucket)
    cost = full * max_bucket
    if rem:
        cost += next(b for b in buckets if rem <= b)
    return cost


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Fixed request layout of one feature shard: serving programs compile
    against ONE shape per shard, so the spec — dense width, or the sparse
    padded-COO nonzero width — is part of the scorer's identity."""

    kind: str  # "dense" | "sparse"
    dim: int
    nnz: int = 0  # padded-COO width (sparse only)

    @property
    def dense(self) -> bool:
        return self.kind == "dense"


@dataclasses.dataclass(frozen=True)
class ScoringRequest:
    """One scoring request: per-shard feature rows, raw per-row entity keys
    for each id column a random coordinate joins on, and an optional
    per-row offset — a :class:`~photon_tpu.game.data.GameDataset` minus
    labels/weights.  All arrays are host-side; the scorer owns placement.

    ``model`` routes the request in a MULTI-MODEL fleet (ISSUE 18): a
    scalar model id (the whole request scores against one tenant), or —
    after the batcher coalesces requests from different tenants — a
    per-row id array.  ``None`` means the default model, which keeps every
    single-model caller unchanged; a single-model :class:`GameScorer`
    ignores the field entirely."""

    features: Dict[str, object]  # shard -> [n, d] dense | (ids, vals) sparse
    entity_ids: Dict[str, np.ndarray]  # id column -> [n] raw keys
    offset: Optional[np.ndarray] = None  # [n] float32
    model: Optional[object] = None  # None | str | [n] object array

    @property
    def num_rows(self) -> int:
        for leaf in self.features.values():
            arr = leaf[0] if isinstance(leaf, tuple) else leaf
            return int(arr.shape[0])
        for col in self.entity_ids.values():
            return int(len(col))
        return 0


def request_spec_for_model(model: GameModel) -> Dict[str, ShardSpec]:
    """Dense request layout straight from the model's own dimensions — the
    default for request sources that send dense feature vectors."""
    spec: Dict[str, ShardSpec] = {}
    for coord in model.coordinates.values():
        if isinstance(coord, FixedEffectModel):
            spec[coord.shard_name] = ShardSpec(
                "dense", int(len(coord.coefficients.means))
            )
        else:
            spec[coord.shard_name] = ShardSpec("dense", int(coord.dim))
    return spec


def request_spec_for_dataset(
    model: GameModel, data: GameDataset
) -> Dict[str, ShardSpec]:
    """Request layout matching a concrete dataset's shard storage (the
    batch ``score_game`` route: Avro input arrives as padded-COO sparse
    shards, whose nonzero width fixes the compiled program's shape)."""
    spec: Dict[str, ShardSpec] = {}
    for coord in model.coordinates.values():
        shard = data.shard(coord.shard_name)
        if isinstance(shard, DenseShard):
            spec[coord.shard_name] = ShardSpec("dense", int(shard.dim))
        else:
            spec[coord.shard_name] = ShardSpec(
                "sparse", int(shard.dim), nnz=int(shard.ids.shape[1])
            )
    return spec


def request_from_dataset(data: GameDataset, model: GameModel) -> ScoringRequest:
    """The whole dataset as one request (batch scoring through the serving
    tables); only the shards/id-columns the model actually joins ride."""
    features: Dict[str, object] = {}
    entity_ids: Dict[str, np.ndarray] = {}
    for coord in model.coordinates.values():
        shard = data.shard(coord.shard_name)
        features[coord.shard_name] = (
            shard.x if isinstance(shard, DenseShard) else (shard.ids, shard.vals)
        )
        if isinstance(coord, RandomEffectModel):
            entity_ids[coord.entity_column] = data.id_columns[coord.entity_column]
    return ScoringRequest(
        features=features, entity_ids=entity_ids, offset=data.offset
    )


def request_model_rows(model, n: int):
    """One request's model-id routing as per-row values: ``None``/scalar
    ids broadcast over the rows; a per-row array passes through.  The ONE
    widening rule :func:`concat_requests` and the wire transport share."""
    if model is None or isinstance(model, str):
        return np.full(n, model, dtype=object)
    # host-sync: ingest routing — caller-owned host id array.
    return np.asarray(model, dtype=object)


def slice_request(req: ScoringRequest, lo: int, hi: int) -> ScoringRequest:
    """Row window ``[lo, hi)`` of a request (oversize-batch chunking)."""
    def cut(leaf):
        if isinstance(leaf, tuple):
            return tuple(a[lo:hi] for a in leaf)
        return leaf[lo:hi]

    model = req.model
    if model is not None and not isinstance(model, str):
        # host-sync: request model-id routing vectors are host object
        # arrays end to end — never device data.
        model = np.asarray(model, dtype=object)[lo:hi]
    return ScoringRequest(
        features={k: cut(v) for k, v in req.features.items()},
        entity_ids={k: v[lo:hi] for k, v in req.entity_ids.items()},
        offset=None if req.offset is None else req.offset[lo:hi],
        model=model,
    )


def concat_requests(requests: List[ScoringRequest]) -> ScoringRequest:
    """Coalesce requests into one micro-batch (the batcher's merge step).
    Every request must carry the same shards/id-columns; offsets default to
    zero rows so requests with and without offsets can share a batch."""
    if len(requests) == 1:
        return requests[0]
    first = requests[0]

    def cat(key):
        leaves = [r.features[key] for r in requests]
        if isinstance(leaves[0], tuple):
            return tuple(
                np.concatenate([leaf[i] for leaf in leaves])
                for i in range(len(leaves[0]))
            )
        return np.concatenate(leaves)

    offsets = []
    for r in requests:
        offsets.append(
            np.zeros(r.num_rows, np.float32) if r.offset is None
            # host-sync: request ingest — caller-owned host offsets.
            else np.asarray(r.offset, np.float32)
        )
    # Model-id routing must survive coalescing: all-same scalars (the
    # common single-tenant batch) stay scalar; any mix widens to a
    # per-row id array the multi-model scorer resolves per row.
    model = None
    scalars = set()
    for r in requests:
        m = r.model
        scalars.add(m if (m is None or isinstance(m, str)) else False)
    if scalars != {None}:
        if len(scalars) == 1 and False not in scalars:
            model = next(iter(scalars))
        else:
            model = np.concatenate([
                request_model_rows(r.model, r.num_rows) for r in requests
            ])
    return ScoringRequest(
        features={k: cat(k) for k in first.features},
        entity_ids={
            k: np.concatenate([r.entity_ids[k] for r in requests])
            for k in first.entity_ids
        },
        offset=np.concatenate(offsets),
        model=model,
    )


def request_windows(n_rows: int, sizes, start: int = 0) -> List[np.ndarray]:
    """Consecutive row windows of the given sizes, wrapping modulo the
    dataset.  The ONE definition of the request-stream cut: the serving
    bench's host baseline scores the same windows the served requests were
    built from, so the parity comparison can never drift onto misaligned
    rows."""
    out: List[np.ndarray] = []
    pos = start
    for size in sizes:
        out.append(np.arange(pos, pos + int(size)) % n_rows)
        pos = (pos + int(size)) % n_rows
    return out


def build_requests(
    data: GameDataset, model: GameModel, sizes, start: int = 0
) -> List[ScoringRequest]:
    """Cut a dataset into a request stream over :func:`request_windows`.
    Shared by the serve_game driver, the serving bench, and the tests —
    one request shape everywhere."""
    whole = request_from_dataset(data, model)
    out: List[ScoringRequest] = []
    for rows in request_windows(data.num_examples, sizes, start=start):

        def take(leaf):
            if isinstance(leaf, tuple):
                return tuple(a[rows] for a in leaf)
            return leaf[rows]

        out.append(
            ScoringRequest(
                features={k: take(v) for k, v in whole.features.items()},
                entity_ids={k: v[rows] for k, v in whole.entity_ids.items()},
                offset=None if whole.offset is None else whole.offset[rows],
            )
        )
    return out


@dataclasses.dataclass(frozen=True)
class _CoordPlan:
    """Static per-coordinate scoring plan baked into every bucket program.

    Deliberately carries the table CAPACITY (the compiled shape) and not
    the entity count: the zero-row index is dynamic published state, so a
    swap that only grows the vocabulary within capacity compares equal.
    The storage ``dtype`` IS part of the plan: the decode is baked into
    every bucket program, so a dtype-mismatched swap must refuse through
    the same plan-equality gate as a capacity change."""

    name: str
    kind: str  # "fixed" | "random"
    shard: str
    column: Optional[str] = None  # random: id column joined on
    capacity: int = 0  # random: table rows (vocabulary + zero-row headroom)
    dtype: str = "f32"  # random: gather-table storage tier (f32|bf16|int8)


class GameScorer:
    """Device-resident GAME model + per-bucket pre-compiled scoring programs.

    Built once per served model; :meth:`score_batch` is the request hot
    path (one compiled dispatch + one host sync per micro-batch) and
    :meth:`score_dataset` the batch route sharing the same tables and
    kernels.  ``buckets`` is the padded-batch ladder; ``max_batch`` caps it
    (a bigger request is chunked).  ``strict_after_warmup`` (default True)
    makes any shape outside the compiled set an error instead of a compile.
    """

    def __init__(
        self,
        model: GameModel,
        mesh=None,
        request_spec: Optional[Dict[str, ShardSpec]] = None,
        buckets: Optional[Tuple[int, ...]] = None,
        max_batch: int = DEFAULT_MAX_BATCH,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        telemetry=None,
        strict_after_warmup: bool = True,
        table_capacity_factor: int = 1,
        table_dtype: str = "f32",
    ):
        from photon_tpu.game.lowp import check_dtype
        from photon_tpu.telemetry import NULL_SESSION

        self.model = model
        self.mesh = mesh
        # Gather-table storage tier (ISSUE 17): f32 | bf16 | int8.  Baked
        # into every bucket program's decode (and into the plan, so a
        # mismatched swap refuses); accumulation stays f32 regardless.
        self.table_dtype = check_dtype(table_dtype)
        self.telemetry = telemetry or NULL_SESSION
        self.request_spec = request_spec or request_spec_for_model(model)
        self.buckets = bucket_ladder(buckets, max_batch, min_bucket)
        self.max_bucket = self.buckets[-1]
        self.compilations = 0
        self._warm = False
        self.strict_after_warmup = strict_after_warmup
        self._programs: Dict[int, object] = {}

        # -- device-resident model tables (loaded once; replaceable by
        # swap_model without recompiling — the programs take them as
        # arguments).  ``table_capacity_factor`` > 1 PRE-PROVISIONS gather-
        # table headroom past the default next-power-of-two: an online-
        # learning deployment expecting vocabulary growth provisions 2x/4x
        # so refresh after refresh hot-swaps in place before hitting the
        # capacity rebuild boundary. ------------------------------------------
        capacities = None
        if int(table_capacity_factor) > 1:
            from photon_tpu.utils import pow2_at_least

            capacities = {
                name: pow2_at_least(
                    int(table_capacity_factor) * (coord.num_entities + 1)
                )
                for name, coord in model.coordinates.items()
                if isinstance(coord, RandomEffectModel)
            }
        plan, tables, zero_rows, vocab = self._build_tables(
            model, capacities=capacities
        )
        self._plan = tuple(plan)
        self._tables = tuple(tables)
        self._zero_rows = zero_rows
        self._vocab = vocab
        # The ONE published (tables, zero_rows, vocab) triple: score_batch
        # unpacks it once at entry, so a swap can never hand one batch a
        # mixed state.
        self._serving = (self._tables, self._zero_rows, self._vocab)
        self._record_model_gauges(model, self._tables)

    def _build_tables(self, model: GameModel,
                      capacities: Optional[Dict[str, int]] = None):
        """Device placement of one model's serving state: the static
        per-coordinate plan, the device table tuple, the movable zero-row
        index vector (one int32 per random coordinate, in plan order —
        published state, never baked into a program), and the host
        vocabularies the ingest join runs against.  Shared by ``__init__``
        and :meth:`swap_model` so the two can never build differently;
        the swap passes its SERVED ``capacities`` so a grown vocabulary
        builds at the compiled shape (and refuses past it).
        Sets NO gauges — :meth:`_record_model_gauges` publishes telemetry
        only for a model that actually serves (a refused swap must not
        leave gauges describing the rejected model)."""
        plan: List[_CoordPlan] = []
        tables: List[jax.Array] = []
        zero_rows: List[int] = []
        vocab: Dict[str, np.ndarray] = {}
        for name, coord in model.coordinates.items():
            if isinstance(coord, FixedEffectModel):
                plan.append(_CoordPlan(name, "fixed", coord.shard_name))
                tables.append(coord.serving_weights(self.mesh))
            elif isinstance(coord, RandomEffectModel):
                capacity = (capacities or {}).get(
                    name, coord.serving_capacity
                )
                plan.append(
                    _CoordPlan(
                        name, "random", coord.shard_name,
                        column=coord.entity_column,
                        capacity=int(capacity),
                        dtype=self.table_dtype,
                    )
                )
                tables.append(
                    coord.serving_table(
                        self.mesh, capacity=capacity,
                        dtype=self.table_dtype,
                    )
                )
                zero_rows.append(coord.num_entities)
                # host-sync: build/swap-time only — entity vocabularies are
                # host numpy by construction (the key join runs at ingest).
                vocab[name] = np.asarray(coord.keys)
            else:
                raise TypeError(
                    f"cannot serve a {type(coord).__name__} coordinate"
                )
            if coord.shard_name not in self.request_spec:
                raise ValueError(
                    f"request spec is missing shard {coord.shard_name!r}"
                )
        # host-sync: build/swap-time only — the movable zero-row vector is
        # assembled on host and uploaded once per published model.
        zero_dev = put_request(
            jnp.asarray(np.asarray(zero_rows, np.int32)), self.mesh
        )
        return plan, tables, zero_dev, vocab

    def _record_model_gauges(self, model: GameModel, tables) -> None:
        """Publish the SERVED model's residency/entity gauges (called only
        after a model is actually installed)."""
        for name, coord in model.coordinates.items():
            if isinstance(coord, RandomEffectModel):
                self.telemetry.gauge(
                    "serving.entities", coordinate=name
                ).set(coord.num_entities)
                self.telemetry.gauge(
                    "serving.table_capacity", coordinate=name
                ).set(next(
                    c.capacity for c in self._plan if c.name == name
                ))
        # Leaf-wise: an int8 table is a (q, scale) tuple — count both.
        total_bytes = sum(
            leaf.nbytes for leaf in jax.tree_util.tree_leaves(tables)
        )
        self.telemetry.gauge("serving.model_bytes").set(total_bytes)
        # The precision tier's headline gauge: gather-table bytes under
        # the SERVED storage dtype (bf16 >= 1.9x, int8 >= 3.5x smaller
        # than f32 at equal entity count — asserted by the serving bench).
        self.telemetry.gauge(
            "serving.table_bytes", dtype=self.table_dtype
        ).set(total_bytes)

    def swap_model(self, model: GameModel,
                   table_dtype: Optional[str] = None) -> None:
        """HOT-SWAP a retrained model under live traffic: the new device
        table tuple is built (uploaded) FIRST — double-buffered next to the
        serving tables — then published in one reference assignment, so no
        request is dropped and nothing recompiles (every bucket program
        takes the tables AND the zero-row index vector as arguments; the
        per-coordinate plan, which IS baked into the programs, must match
        the served model's — same coordinate names/kinds/shards/table
        capacities.  A GROWN vocabulary that still fits the served
        capacity swaps in place: the new entities' rows upload into the
        headroom and the zero-row index advances — ROADMAP continual-
        training blocker (b) cleared.  Growth PAST capacity, or a changed
        dim/coordinate set, is a layout-shape change and refuses).

        In-flight requests complete against whichever triple they captured
        at dispatch: the old tables stay alive until their last dispatch
        retires (the runtime holds the references), then free.  Counted as
        ``serving.swaps``.

        ``table_dtype``, when given, asserts the caller's expected storage
        tier: the decode is baked into the warmed bucket programs, so an
        artifact published at a DIFFERENT dtype must refuse here instead
        of silently re-encoding (serving it would change the fleet's
        parity bound under live traffic)."""
        if table_dtype is not None and table_dtype != self.table_dtype:
            raise ValueError(
                f"swap_model: model published at table dtype "
                f"{table_dtype!r} but this scorer's warmed programs decode "
                f"{self.table_dtype!r}; the storage tier is baked into the "
                "compiled bucket ladder — rebuild the scorer to change it"
            )
        capacities = {
            c.name: c.capacity for c in self._plan if c.kind == "random"
        }
        plan, tables, zero_rows, vocab = self._build_tables(
            model, capacities=capacities
        )
        if tuple(plan) != self._plan:
            raise ValueError(
                "swap_model: the new model's serving plan does not match "
                f"the compiled programs (served {self._plan}, new "
                f"{tuple(plan)}); a changed coordinate layout, table "
                "capacity, or storage dtype requires a new GameScorer"
            )
        # Leaf-wise: an int8 table is a (q, scale) tuple; its structure,
        # every leaf shape, AND every leaf dtype must match the compiled
        # programs exactly or nothing recompile-free can serve it.
        new_leaves, new_treedef = jax.tree_util.tree_flatten(tuple(tables))
        old_leaves, old_treedef = jax.tree_util.tree_flatten(self._tables)
        if new_treedef != old_treedef:
            raise ValueError(
                "swap_model: table pytree structure changed "
                f"({old_treedef} -> {new_treedef}); a changed table "
                "layout requires a new GameScorer"
            )
        for new, old in zip(new_leaves, old_leaves):
            if new.shape != old.shape or new.dtype != old.dtype:
                raise ValueError(
                    "swap_model: table shape/dtype changed "
                    f"({old.shape}/{old.dtype} -> {new.shape}/{new.dtype}); "
                    "a changed table layout requires a new GameScorer"
                )
        import jax as _jax

        # The upload completes BEFORE publication: a request arriving the
        # instant after the swap reads fully-materialized tables.
        _jax.block_until_ready((tables, zero_rows))
        # One-assignment publication: score_batch reads ``self._serving``
        # exactly once at entry, so every batch scores against ONE model's
        # tables + zero rows + vocabulary — never a mix of old and new.
        self._tables = tuple(tables)
        self._zero_rows = zero_rows
        self._vocab = vocab
        self._serving = (self._tables, self._zero_rows, self._vocab)
        self.model = model
        self._record_model_gauges(model, self._tables)
        self.telemetry.counter("serving.swaps").inc()

    # -- bucket policy -------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket holding ``n`` rows (n <= max_bucket)."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"batch of {n} rows exceeds max bucket "
                         f"{self.max_bucket}; chunk it (score_batch does)")

    def padded_rows(self, n: int) -> int:
        """Padded device rows ``n`` request rows cost through this ladder
        (the admission projection's cost unit)."""
        return padded_cost(n, self.buckets)

    def warmup(self) -> "GameScorer":
        """AOT-compile every ladder bucket's program.  After this, serving
        arrival patterns can never compile: each micro-batch maps onto one
        of these executables, and (under ``strict_after_warmup``) an
        off-ladder shape raises instead of silently compiling."""
        with self.telemetry.span("serving.warmup", buckets=len(self.buckets)):
            for b in self.buckets:
                self._program(b)
        self._warm = True
        return self

    def _donate_argnums(self) -> tuple:
        """Donate request buffers (args 2–4: feats/idx/offset) on
        accelerators only.  See the comment at the jit site: on CPU the
        placed buffers can alias the staged host memory and each other
        across replicas, and donating an aliased buffer corrupts scores."""
        leaves = jax.tree_util.tree_leaves(self._tables)
        devices = leaves[0].devices() if leaves else set()
        if any(d.platform == "cpu" for d in devices):
            return ()
        return (2, 3, 4)

    # -- program build -------------------------------------------------------
    def _program(self, bucket: int, layout: str = "request"):
        program = self._programs.get((bucket, layout))
        if program is not None:
            return program
        if self._warm and self.strict_after_warmup and layout == "request":
            raise RuntimeError(
                f"no pre-compiled program for bucket {bucket} after warmup "
                f"(compiled: {sorted(b for b, l in self._programs if l == 'request')}); "
                "widen `buckets` or chunk the batch — serving must never "
                "recompile"
            )
        plan, spec = self._plan, self.request_spec

        def score(tables, zero_rows, feats, idx, offset, n_valid):
            valid = jnp.arange(bucket, dtype=jnp.int32) < n_valid
            total = offset
            colds = []
            random_pos = 0
            for c, table in zip(plan, tables):
                dense = spec[c.shard].dense
                if c.kind == "fixed":
                    total = total + _fixed_margins(table, feats[c.shard], dense)
                else:
                    raw = idx[c.name]
                    # The zero row is DYNAMIC published state (it moves when
                    # a grown vocabulary hot-swaps in), never a baked
                    # constant — otherwise growth would mean recompiles.
                    safe = jnp.where(raw >= 0, raw, zero_rows[random_pos])
                    random_pos += 1
                    total = total + serving_gather_margins(
                        table, safe, feats[c.shard], dense
                    )
                    colds.append(
                        jnp.sum((raw < 0) & valid, dtype=jnp.int32)
                    )
            cold = (
                jnp.stack(colds) if colds else jnp.zeros((0,), jnp.int32)
            )
            return jnp.where(valid, total, 0.0), cold

        # Request buffers (feats/idx/offset) are DONATED on accelerators:
        # XLA recycles the uploaded buffers for outputs, so steady-state
        # serving allocates nothing per batch beyond the h2d staging
        # itself.  NOT on CPU — there "device" buffers can zero-copy alias
        # the staged host numpy AND each other across a replicated mesh
        # placement, and a donated alias lets one replica's output write
        # clobber a buffer another replica still reads (observed as
        # intermittent whole-batch garbage; the only CPU-donatable buffer
        # was the offset, whose shape/dtype matches the scores output).
        # On TPU/GPU every h2d is a real copy into device memory, so
        # donation is both safe and the allocation win it exists for.
        jitted = jax.jit(score, donate_argnums=self._donate_argnums())
        sample = self._place(*self._zero_request(bucket), layout=layout)
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            program = jitted.lower(
                self._tables, self._zero_rows, *abstract_like(sample)
            ).compile()
        self._programs[(bucket, layout)] = program
        self.compilations += 1
        self.telemetry.counter("serving.compilations").inc()
        return program

    def _zero_request(self, bucket: int):
        """Host-side zero request buffers at a bucket's exact layout."""
        feats: Dict[str, object] = {}
        for c in self._plan:
            s = self.request_spec[c.shard]
            if s.dense:
                feats[c.shard] = np.zeros((bucket, s.dim), np.float32)
            else:
                feats[c.shard] = (
                    np.zeros((bucket, s.nnz), np.int32),
                    np.zeros((bucket, s.nnz), np.float32),
                )
        idx = {
            c.name: np.full(bucket, -1, np.int32)
            for c in self._plan if c.kind == "random"
        }
        offset = np.zeros(bucket, np.float32)
        return feats, idx, offset, np.int32(0)

    def _place(self, feats, idx, offset, n_valid, layout: str = "request"):
        """One h2d placement of a staged request, matching the layout the
        bucket program was lowered against.  ``"request"`` replicates the
        micro-batch (put_request — tiny next to the tables); ``"dataset"``
        SHARDS the per-row buffers over the mesh rows: a whole-dataset
        batch replicated would cost one full dataset copy PER DEVICE,
        inverting the micro-batch rationale."""
        if layout == "dataset" and self.mesh is not None:
            from photon_tpu.parallel.mesh import put_replicated, put_sharded

            return (
                *put_sharded((feats, idx, offset), self.mesh),
                put_replicated(jnp.int32(n_valid), self.mesh),
            )
        return put_request((feats, idx, offset, jnp.int32(n_valid)), self.mesh)

    # -- request staging (host side, the sanctioned ingest edge) -------------
    def _stage(self, request: ScoringRequest, bucket: int, n: int,
               vocab: Optional[Dict[str, np.ndarray]] = None):
        """Validate + pad one request to its bucket, join entity keys
        against each coordinate's vocabulary, and coerce dtypes — the
        request-ingest host work.  Padding rows carry zero features, entity
        index -1 (masked from cold counts by ``n_valid``), zero offset."""
        if vocab is None:
            vocab = self._vocab
        feats: Dict[str, object] = {}
        for c in self._plan:
            if c.shard in feats:
                continue
            s = self.request_spec[c.shard]
            leaf = request.features.get(c.shard)
            if leaf is None:
                raise ValueError(f"request is missing shard {c.shard!r}")
            if s.dense:
                # host-sync: request ingest — coercing caller-owned feature
                # rows to upload-ready numpy (no device data involved).
                x = np.asarray(leaf, np.float32)
                if x.shape != (n, s.dim):
                    raise ValueError(
                        f"shard {c.shard!r}: got {x.shape}, want {(n, s.dim)}"
                    )
                feats[c.shard] = _pad_rows(x, bucket)
            else:
                ids, vals = leaf
                # host-sync: request ingest — same coercion, sparse leaves.
                ids = np.asarray(ids, np.int32)
                vals = np.asarray(vals, np.float32)
                if ids.shape != (n, s.nnz) or vals.shape != (n, s.nnz):
                    raise ValueError(
                        f"shard {c.shard!r}: got {ids.shape}/{vals.shape}, "
                        f"want {(n, s.nnz)}"
                    )
                feats[c.shard] = (
                    _pad_rows(ids, bucket), _pad_rows(vals, bucket)
                )
        idx: Dict[str, np.ndarray] = {}
        for c in self._plan:
            if c.kind != "random":
                continue
            keys = request.entity_ids.get(c.column)
            if keys is None:
                raise ValueError(
                    f"request is missing id column {c.column!r}"
                )
            # The key->row join (host searchsorted against the sorted
            # vocabulary) is the serving-time shape of the reference's
            # scoring shuffle-join; unknown keys become -1 -> zero row.
            rows = entity_index_for(keys, vocab[c.name])
            idx[c.name] = _pad_rows(rows, bucket, fill=-1)
        offset = (
            np.zeros(bucket, np.float32) if request.offset is None
            else _pad_rows(
                # host-sync: request ingest — offset coercion, host data.
                np.asarray(request.offset, np.float32), bucket
            )
        )
        return feats, idx, offset

    # -- scoring -------------------------------------------------------------
    def score_batch(self, request: ScoringRequest) -> np.ndarray:
        """Score one request micro-batch; returns ``[n]`` float32 raw
        scores (offset + every coordinate's margin; unknown entities get
        the fixed-effect-only fallback).  ONE compiled dispatch + ONE host
        sync; requests wider than the bucket ladder are chunked."""
        n = request.num_rows
        if n == 0:
            return np.zeros(0, np.float32)
        if n > self.max_bucket:
            return np.concatenate([
                self.score_batch(slice_request(request, lo,
                                               min(lo + self.max_bucket, n)))
                for lo in range(0, n, self.max_bucket)
            ])
        return self._score_padded(request, self.bucket_for(n), n)

    def score_dataset(self, data: GameDataset) -> np.ndarray:
        """Batch scoring through the SAME device tables and kernels: the
        dataset is one request padded to the next power of two (its own
        bucket, compiled once per dataset shape — the ``score_game``
        non-streamed route), so the batch and online paths cannot drift.
        Unlike request micro-batches, the per-row buffers are SHARDED over
        the mesh (one dataset copy across devices, not one per device)."""
        from photon_tpu.parallel.mesh import mesh_shards, pad_to_multiple

        req = request_from_dataset(data, self.model)
        n = req.num_rows
        if n == 0:
            return np.zeros(0, np.float32)
        # pow2 for shape bucketing, then up to a mesh multiple so the row
        # sharding divides (a no-op on power-of-two meshes).
        bucket = pad_to_multiple(pow2_at_least(n), mesh_shards(self.mesh))
        return self._score_padded(req, bucket, n, layout="dataset")

    def _score_padded(self, request: ScoringRequest, bucket: int,
                      n: int, layout: str = "request") -> np.ndarray:
        t0 = time.monotonic()
        # ONE read of the published (tables, zero_rows, vocab) triple: a
        # concurrent swap_model cannot hand this batch old tables + a new
        # vocabulary (or a moved zero row).
        tables, zero_rows, vocab = self._serving
        program = self._program(bucket, layout=layout)
        feats, idx, offset = self._stage(request, bucket, n, vocab)
        placed = self._place(feats, idx, offset, n, layout=layout)
        out, cold_dev = program(tables, zero_rows, *placed)
        # The response must OWN its memory (the copy below): on CPU the
        # fetch can alias the device output buffer, and with donated inputs
        # that buffer is recycled by the very next batch — a zero-copy view
        # would read the next request's scores (the egress twin of
        # _pad_rows' ingest copy).
        # host-sync: response egress — THE one per-batch fetch; scores and
        # the per-coordinate cold-entity counts ride one device_get.
        fetched_scores, cold = jax.device_get((out, cold_dev))
        scores = np.array(fetched_scores, copy=True)
        t = self.telemetry
        t.counter("serving.host_syncs").inc()
        t.counter("serving.batches", bucket=bucket).inc()
        t.counter("serving.rows").inc(n)
        t.histogram("serving.batch_rows").observe(n)
        t.histogram("serving.bucket_occupancy", bucket=bucket).observe(
            n / bucket
        )
        t.histogram("serving.padded_fraction").observe((bucket - n) / bucket)
        t.histogram("serving.score_seconds").observe(time.monotonic() - t0)
        cold_plan = [c for c in self._plan if c.kind == "random"]
        for c, count in zip(cold_plan, cold):
            if count:
                t.counter("serving.cold_entities", coordinate=c.name).inc(
                    int(count)
                )
        return scores[:n]


def _pad_rows(a: np.ndarray, target: int, fill=0) -> np.ndarray:
    """Pad rows to the bucket — ALWAYS returning memory this module owns.

    The staged buffers are DONATED to the bucket programs, and on CPU
    ``device_put`` can alias suitably-aligned host numpy zero-copy: donating
    an aliased view of the caller's dataset would let XLA write outputs
    into the caller's own arrays (the exact corruption class PR 3's
    XLA-born-donation rule exists for).  ``np.pad`` copies when padding is
    needed; the exact-size case must copy explicitly."""
    short = target - a.shape[0]
    if short <= 0:
        return np.array(a, copy=True)
    widths = [(0, short)] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, widths, constant_values=fill)
