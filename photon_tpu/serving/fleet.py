"""Serving fleet: N scorer replicas + router + optional socket ingest.

The one assembly point for the fleet tier (ISSUE 12 tentpole): builds N
:class:`~photon_tpu.serving.router.ScorerReplica` instances from ONE model
artifact (shared model distribution — the host-side model object is loaded
once; each replica uploads its OWN device-resident tables from it), wires
them behind a :class:`~photon_tpu.serving.router.FleetRouter` with
deadline-aware admission control, and optionally attaches the
:class:`~photon_tpu.serving.transport.ScoringServer` socket ingest.

Per-replica device residency: with ``devices="split"`` (the default) the
addressable devices are dealt round-robin across replicas and each scorer
places its tables on its own sub-mesh (``reshard_to_mesh`` under each
scorer's mesh) — on a multi-device platform replicas genuinely own
disjoint device memory; on a single device they share it (thread-backed
replicas, the CPU fixture's shape).

Rollout and model lifecycle ride the router: :meth:`ServingFleet.rollout`
is the staggered/canary ``swap_model`` (one replica first, mirrored-
traffic parity probe, then the rest), and capacity-headroom serving
tables (amortized doubling + movable zero row) mean a GROWN vocabulary
publishes in place fleet-wide with zero recompiles.

Residency contract (``tools/check_host_sync.py`` guards this module): the
fleet layer moves requests and models between components — it never
fetches device data itself.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from photon_tpu.serving.router import (
    AdmissionPolicy,
    FleetRouter,
    ScorerReplica,
)
from photon_tpu.serving.scorer import (
    DEFAULT_MAX_BATCH,
    DEFAULT_MIN_BUCKET,
    GameScorer,
    ScoringRequest,
    ShardSpec,
)
from photon_tpu.serving.batcher import DEFAULT_MAX_DELAY_S


def _replica_meshes(n_replicas: int, mesh, devices) -> List[object]:
    """One mesh (or None) per replica.  An explicit ``mesh`` is shared by
    every replica; ``devices="split"`` deals the addressable devices
    round-robin so each replica's tables live on its own sub-mesh; any
    other value places every replica on the default device."""
    if mesh is not None or devices != "split":
        return [mesh] * n_replicas
    import jax

    devs = list(jax.devices())
    if len(devs) <= 1:
        return [None] * n_replicas
    from photon_tpu.parallel.mesh import create_mesh

    groups = [devs[i::n_replicas] for i in range(n_replicas)]
    return [
        create_mesh(devices=groups[i % len(groups)] or [devs[i % len(devs)]])
        for i in range(n_replicas)
    ]


class ServingFleet:
    """N replicated scorers behind a deadline-aware router.

    Context-manager lifecycle; ``close()`` drains every replica's batcher
    and stamps the per-replica QPS gauges.  ``submit``/``score`` go
    through admission control (``deadline_s`` is a relative budget;
    sheds raise :class:`~photon_tpu.serving.router.RequestShedError`).
    """

    def __init__(
        self,
        model,
        replicas: int = 2,
        mesh=None,
        devices: str = "split",
        request_spec: Optional[Dict[str, ShardSpec]] = None,
        buckets=None,
        max_batch: int = DEFAULT_MAX_BATCH,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        max_delay_s: float = DEFAULT_MAX_DELAY_S,
        telemetry=None,
        admission: Optional[AdmissionPolicy] = None,
    ):
        from photon_tpu.telemetry import NULL_SESSION

        if replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        self.model = model
        self.telemetry = telemetry or NULL_SESSION
        meshes = _replica_meshes(int(replicas), mesh, devices)
        self.replicas: List[ScorerReplica] = []
        for i in range(int(replicas)):
            scorer = GameScorer(
                model,
                mesh=meshes[i],
                request_spec=request_spec,
                buckets=buckets,
                max_batch=max_batch,
                min_bucket=min_bucket,
                telemetry=self.telemetry,
            )
            self.replicas.append(
                ScorerReplica(
                    f"r{i}", scorer,
                    max_batch=max_batch, max_delay_s=max_delay_s,
                    telemetry=self.telemetry,
                )
            )
        self.router = FleetRouter(
            self.replicas, telemetry=self.telemetry, admission=admission
        )
        self._server = None
        self.telemetry.gauge("serving.replicas").set(int(replicas))

    @classmethod
    def from_model_dir(cls, model_dir: str, telemetry=None, logger=None,
                       **kwargs) -> "ServingFleet":
        """Shared model-artifact distribution: the artifact is read ONCE
        (retried like any guarded model load) and every replica builds its
        device tables from the same host object."""
        from photon_tpu.fault.retry import retry_call
        from photon_tpu.game.model_io import load_game_model

        model, _ = retry_call(
            lambda: load_game_model(model_dir),
            site="model:load", telemetry=telemetry, logger=logger,
        )
        return cls(model, telemetry=telemetry, **kwargs)

    # -- serving -------------------------------------------------------------
    def warmup(self) -> "ServingFleet":
        """AOT-compile every replica's bucket ladder; after this the fleet
        can never recompile on any arrival pattern."""
        for replica in self.replicas:
            replica.scorer.warmup()
        return self

    @property
    def compilations(self) -> int:
        return sum(r.scorer.compilations for r in self.replicas)

    def submit(self, request: ScoringRequest,
               deadline_s: Optional[float] = None):
        return self.router.submit(request, deadline_s=deadline_s)

    def score(self, request: ScoringRequest,
              deadline_s: Optional[float] = None):
        return self.submit(request, deadline_s=deadline_s).result()

    def rollout(self, model, **kwargs) -> None:
        """Staggered/canary ``swap_model`` across the fleet (see
        :meth:`photon_tpu.serving.router.FleetRouter.rollout`)."""
        self.router.rollout(model, **kwargs)
        self.model = model

    # -- transport -----------------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Attach the socket ingest; returns the
        :class:`~photon_tpu.serving.transport.ScoringServer` (its
        ``.address`` is the bound ``(host, port)``)."""
        from photon_tpu.serving.transport import ScoringServer

        if self._server is not None:
            raise RuntimeError("fleet already serving")
        self._server = ScoringServer(
            self.router, host=host, port=port, telemetry=self.telemetry
        )
        return self._server

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
        self.router.close()

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
