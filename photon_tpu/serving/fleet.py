"""Serving fleet: N scorer replicas + router + optional socket ingest.

The one assembly point for the fleet tier (ISSUE 12 tentpole): builds N
:class:`~photon_tpu.serving.router.ScorerReplica` instances from ONE model
artifact (shared model distribution — the host-side model object is loaded
once; each replica uploads its OWN device-resident tables from it), wires
them behind a :class:`~photon_tpu.serving.router.FleetRouter` with
deadline-aware admission control, and optionally attaches the
:class:`~photon_tpu.serving.transport.ScoringServer` socket ingest.

Per-replica device residency: with ``devices="split"`` (the default) the
addressable devices are dealt round-robin across replicas and each scorer
places its tables on its own sub-mesh (``reshard_to_mesh`` under each
scorer's mesh) — on a multi-device platform replicas genuinely own
disjoint device memory; on a single device they share it (thread-backed
replicas, the CPU fixture's shape).

Rollout and model lifecycle ride the router: :meth:`ServingFleet.rollout`
is the staggered/canary ``swap_model`` (one replica first, mirrored-
traffic parity probe, then the rest), and capacity-headroom serving
tables (amortized doubling + movable zero row) mean a GROWN vocabulary
publishes in place fleet-wide with zero recompiles.

Residency contract (``tools/check_host_sync.py`` guards this module): the
fleet layer moves requests and models between components — it never
fetches device data itself.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from photon_tpu.serving.router import (
    AdmissionPolicy,
    FleetRouter,
    ScorerReplica,
    host_score_request,
    parity_worst,
)
from photon_tpu.serving.scorer import (
    DEFAULT_MAX_BATCH,
    DEFAULT_MIN_BUCKET,
    GameScorer,
    ScoringRequest,
    ShardSpec,
    request_spec_for_model,
)
from photon_tpu.serving.batcher import DEFAULT_MAX_DELAY_S


class ReplicaRebuildError(RuntimeError):
    """A background-rebuild replacement failed its canary parity probe;
    the replacement was retired and the fleet is untouched."""


#: The capacity-plan refusal markers: a ``swap_model`` that cannot fit
#: the new model in the serving tables' headroom raises with ONE of
#: these texts (the scorer's plan comparison, or ``serving_table``'s
#: vocabulary-vs-capacity check underneath it) — and both survive the
#: subprocess boundary (the child's refusal travels back inside a typed
#: error frame's message).
CAPACITY_REFUSAL_MARKERS = (
    "requires a new GameScorer",
    "rebuild the scorer instead of hot-swapping",
)


def is_capacity_refusal(exc: BaseException) -> bool:
    """Does this exception chain carry the capacity-plan refusal?  Walks
    ``__cause__``/``__context__`` so a refusal wrapped by the transport
    (TransportError) or a retry layer still matches."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        text = str(exc)
        if any(marker in text for marker in CAPACITY_REFUSAL_MARKERS):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def _replica_meshes(n_replicas: int, mesh, devices) -> List[object]:
    """One mesh (or None) per replica.  An explicit ``mesh`` is shared by
    every replica; ``devices="split"`` deals the addressable devices
    round-robin so each replica's tables live on its own sub-mesh; any
    other value places every replica on the default device."""
    if mesh is not None or devices != "split":
        return [mesh] * n_replicas
    import jax

    devs = list(jax.devices())
    if len(devs) <= 1:
        return [None] * n_replicas
    from photon_tpu.parallel.mesh import create_mesh

    groups = [devs[i::n_replicas] for i in range(n_replicas)]
    return [
        create_mesh(devices=groups[i % len(groups)] or [devs[i % len(devs)]])
        for i in range(n_replicas)
    ]


class ServingFleet:
    """N replicated scorers behind a deadline-aware router.

    Context-manager lifecycle; ``close()`` drains every replica's batcher
    and stamps the per-replica QPS gauges.  ``submit``/``score`` go
    through admission control (``deadline_s`` is a relative budget;
    sheds raise :class:`~photon_tpu.serving.router.RequestShedError`).

    ``backend`` picks the replica runtime: ``"thread"`` (the PR 12 shape —
    scorers in this process, per-replica sub-meshes via ``devices``) or
    ``"subprocess"`` (ISSUE 13 — each replica is a CHILD PROCESS with its
    own Python/jax runtime speaking the frame protocol over loopback,
    devices dealt per child via ``JAX_PLATFORMS``/visible-device env; the
    shared model artifact lives under ``workdir``).  ``supervise()``
    attaches the self-healing supervisor — health probes, canary-gated
    resurrection, flap quarantine — over either backend.
    """

    def __init__(
        self,
        model,
        replicas: int = 2,
        mesh=None,
        devices: str = "split",
        backend: str = "thread",
        request_spec: Optional[Dict[str, ShardSpec]] = None,
        buckets=None,
        max_batch: int = DEFAULT_MAX_BATCH,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        max_delay_s: float = DEFAULT_MAX_DELAY_S,
        telemetry=None,
        admission: Optional[AdmissionPolicy] = None,
        workdir: Optional[str] = None,
        child_env: Optional[Dict[str, str]] = None,
        spawn_timeout_s: float = 120.0,
        table_capacity_factor: int = 1,
        table_dtype: str = "f32",
        models: Optional[Dict[str, object]] = None,
        reserve_rows: int = 0,
    ):
        from photon_tpu.game.lowp import check_dtype
        from photon_tpu.telemetry import NULL_SESSION

        if replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if backend not in ("thread", "subprocess"):
            raise ValueError(f"unknown replica backend {backend!r} "
                             "(thread | subprocess)")
        # Multi-model arena fleet (ISSUE 18): ``models`` maps tenant id ->
        # GameModel; every replica hosts ALL of them in one shared arena
        # behind one compiled bucket ladder, and requests route by their
        # ``model`` field.  ``model`` (positional) may be None then; the
        # first hosted model becomes the default tenant.
        self.models: Optional[Dict[str, object]] = (
            dict(models) if models else None
        )
        self._reserve_rows = int(reserve_rows)
        if self.models and model is None:
            model = next(iter(self.models.values()))
        if self.models is not None and not self.models:
            raise ValueError("models= needs at least one hosted model")
        self.model = model
        self.backend = backend
        # Rebuild inputs (ISSUE 19): a zero-downtime background rebuild
        # re-spawns replicas at a larger table_capacity_factor, so the
        # fleet remembers the construction shape it built them from.
        self._table_capacity_factor = int(table_capacity_factor)
        self._request_spec_cfg = request_spec
        self._buckets = buckets
        self._max_batch = int(max_batch)
        self._min_bucket = int(min_bucket)
        self._replica_mesh_list: List[object] = []
        # Fleet-wide gather-table storage tier (ISSUE 17): every replica
        # serves the same dtype, and the canary/probe parity gates default
        # to the tier's measured bound (lowp.parity_tol_for).
        self.table_dtype = check_dtype(table_dtype)
        self.telemetry = telemetry or NULL_SESSION
        self._model_lock = threading.Lock()
        # Serializes whole PUBLISH operations (rollout, fleet rollback):
        # two concurrent publishes interleaving their per-replica swaps
        # would leave the fleet split across models.
        self._publish_lock = threading.Lock()
        self._model_version = 0
        self._rolling = 0
        self._previous_model = None
        self._supervisor = None
        self._store = None
        self._workdir_owned = False
        self.replicas: List[ScorerReplica] = []
        if backend == "subprocess":
            import tempfile

            from photon_tpu.serving.replica_proc import (
                ModelStore,
                SubprocessReplica,
                child_device_env,
            )

            if workdir is None:
                workdir = tempfile.mkdtemp(prefix="photon-fleet-")
                self._workdir_owned = True
            self._store = ModelStore(workdir)
            if self.models:
                self._store.keep = max(self._store.keep,
                                       len(self.models) + 2)
                for m in self.models.values():
                    self._store.publish(m)
            else:
                self._store.publish(model)  # the v0 shared artifact
            spec = request_spec or request_spec_for_model(model)
            try:
                for i in range(int(replicas)):
                    env = dict(child_device_env(i, int(replicas)))
                    env.update(child_env or {})
                    self.replicas.append(
                        SubprocessReplica(
                            f"r{i}", model, self._store,
                            request_spec=spec, buckets=buckets,
                            max_batch=max_batch, min_bucket=min_bucket,
                            max_delay_s=max_delay_s,
                            telemetry=self.telemetry,
                            child_env=env, spawn_timeout_s=spawn_timeout_s,
                            table_capacity_factor=table_capacity_factor,
                            table_dtype=self.table_dtype,
                            models=self.models,
                            reserve_rows=self._reserve_rows,
                        )
                    )
            except BaseException:
                # Partial-spawn failure: a half-built fleet has no close()
                # caller — reap the children already spawned and the owned
                # workdir here, or they leak past the raised error.
                for replica in self.replicas:
                    try:
                        replica.close()
                    except Exception:  # noqa: BLE001 — best-effort reap
                        pass
                if self._workdir_owned:
                    import shutil

                    shutil.rmtree(workdir, ignore_errors=True)
                raise
        else:
            meshes = _replica_meshes(int(replicas), mesh, devices)
            self._replica_mesh_list = list(meshes)
            for i in range(int(replicas)):
                if self.models:
                    from photon_tpu.serving.arena import MultiModelScorer

                    scorer = MultiModelScorer(
                        self.models,
                        mesh=meshes[i],
                        request_spec=request_spec,
                        buckets=buckets,
                        max_batch=max_batch,
                        min_bucket=min_bucket,
                        telemetry=self.telemetry,
                        table_capacity_factor=table_capacity_factor,
                        table_dtype=self.table_dtype,
                        reserve_rows=self._reserve_rows,
                    )
                else:
                    scorer = GameScorer(
                        model,
                        mesh=meshes[i],
                        request_spec=request_spec,
                        buckets=buckets,
                        max_batch=max_batch,
                        min_bucket=min_bucket,
                        telemetry=self.telemetry,
                        table_capacity_factor=table_capacity_factor,
                        table_dtype=self.table_dtype,
                    )
                self.replicas.append(
                    ScorerReplica(
                        f"r{i}", scorer,
                        max_batch=max_batch, max_delay_s=max_delay_s,
                        telemetry=self.telemetry,
                    )
                )
        if backend == "thread":
            # Thread replicas have no child artifact version; the fleet
            # stamps its own monotonic version on them so response spans
            # carry the served model version on either backend.
            for replica in self.replicas:
                replica.served_version = 0
        self.router = FleetRouter(
            self.replicas, telemetry=self.telemetry, admission=admission
        )
        self._server = None
        self.observer = None
        self.telemetry.gauge("serving.replicas").set(int(replicas))

    @classmethod
    def from_model_dir(cls, model_dir: str, telemetry=None, logger=None,
                       **kwargs) -> "ServingFleet":
        """Shared model-artifact distribution: the artifact is read ONCE
        (retried like any guarded model load) and every replica builds its
        device tables from the same host object."""
        from photon_tpu.fault.retry import retry_call
        from photon_tpu.game.model_io import load_game_model

        model, _ = retry_call(
            lambda: load_game_model(model_dir),
            site="model:load", telemetry=telemetry, logger=logger,
        )
        return cls(model, telemetry=telemetry, **kwargs)

    # -- serving -------------------------------------------------------------
    def warmup(self) -> "ServingFleet":
        """AOT-compile every replica's bucket ladder; after this the fleet
        can never recompile on any arrival pattern."""
        for replica in self.replicas:
            replica.scorer.warmup()
        return self

    @property
    def compilations(self) -> int:
        return sum(r.scorer.compilations for r in self.replicas)

    def submit(self, request: ScoringRequest,
               deadline_s: Optional[float] = None,
               model: Optional[str] = None):
        """Admit one request.  ``model`` stamps a tenant id onto it (a
        convenience for callers that route per call instead of building
        requests with ``model=`` set); a multi-model fleet scores it
        against that tenant's arena slice."""
        if model is not None:
            request = dataclasses.replace(request, model=model)
        return self.router.submit(request, deadline_s=deadline_s)

    def score(self, request: ScoringRequest,
              deadline_s: Optional[float] = None,
              model: Optional[str] = None):
        return self.submit(request, deadline_s=deadline_s,
                           model=model).result()

    # -- multi-model lifecycle -----------------------------------------------
    def add_model(self, model_id: str, model) -> None:
        """Onboard a tenant fleet-wide under live traffic: each replica's
        arena takes the new model as a slice scatter (zero recompiles
        unless the arena grows); in-flight batches finish on the tables
        they captured — zero requests dropped."""
        if self.models is None:
            raise RuntimeError(
                "add_model needs a multi-model fleet (pass models= at "
                "construction)"
            )
        with self._publish_lock:
            for replica in self.replicas:
                if replica.alive:
                    replica.scorer.add_model(model_id, model)
            with self._model_lock:
                self.models[model_id] = model

    def retire_model(self, model_id: str) -> None:
        """Retire a tenant fleet-wide: its rows stay in place (unreachable
        via routing) until the free extents are reused; requests still
        naming it shed with a KeyError."""
        if self.models is None:
            raise RuntimeError("retire_model needs a multi-model fleet")
        with self._publish_lock:
            for replica in self.replicas:
                if replica.alive:
                    replica.scorer.retire_model(model_id)
            with self._model_lock:
                self.models.pop(model_id, None)

    def current_model(self) -> Tuple[object, int]:
        """The model the fleet serves NOW and its monotonic version — the
        supervisor's resurrection target (a replica resurrected
        mid-rollout re-syncs against this, never the model it died on)."""
        with self._model_lock:
            return self.model, self._model_version

    def rollout(self, model, **kwargs) -> None:
        """Staggered/canary ``swap_model`` across the fleet (see
        :meth:`photon_tpu.serving.router.FleetRouter.rollout`).

        The fleet's (model, version) is published BEFORE the router
        rollout runs and rolled back if it fails: a resurrection that
        completes while the rollout is in flight must target the model
        the fleet is converging TO — publishing only on return would let
        a replica rejoin on the old model mid-promotion and leave the
        fleet split until the next parity probe killed it again.  (If the
        rollout aborts, a replica resurrected against the new model fails
        its next known-answer probe and is re-resurrected on the restored
        one — the rare-path analog of the same self-healing loop.)

        Whole publishes serialize on ``_publish_lock``: a rollout and the
        supervisor's fleet rollback interleaving their per-replica swaps
        would split the fleet across models.

        The canary parity gate defaults to the fleet's TABLE-DTYPE bound
        (``lowp.parity_tol_for`` — f32 keeps the exact-path 1e-3; bf16/
        int8 gate at their measured codec bounds): a lossy fleet probed at
        the f32 tolerance would fail every healthy rollout.  An explicit
        ``parity_tol`` kwarg still wins."""
        if "parity_tol" not in kwargs:
            from photon_tpu.game.lowp import parity_tol_for

            kwargs["parity_tol"] = parity_tol_for(self.table_dtype)
        model_id = kwargs.get("model_id")
        with self._publish_lock:
            with self._model_lock:
                previous_model = self.model
                previous_slice = None
                if model_id is None:
                    self.model = model
                elif self.models is not None:
                    previous_slice = self.models.get(model_id)
                    self.models[model_id] = model
                self._model_version += 1
                self._rolling += 1
            try:
                self.router.rollout(model, **kwargs)
            except BaseException:
                with self._model_lock:
                    if model_id is None:
                        self.model = previous_model
                    elif (self.models is not None
                            and previous_slice is not None):
                        self.models[model_id] = previous_slice
                    # The version stays MONOTONIC: the rollback is itself
                    # a new published state.  Restoring the old number
                    # would let a later rollout reuse it and defeat the
                    # supervisor's stale-oracle version check.
                    self._model_version += 1
                raise
            finally:
                with self._model_lock:
                    self._rolling -= 1
            with self._model_lock:
                # Promoted fleet-wide: keep the PREDECESSOR artifact as
                # the supervisor's fleet-rollback target (a post-swap
                # fleet-wide known-answer parity regression rolls back to
                # it instead of quarantining every replica — ROADMAP
                # fleet edge (d)).  A per-tenant rollout leaves the
                # DEFAULT-model rollback target alone — the fleet-wide
                # known-answer probe runs against the default model, and
                # its rollback must not revert an unrelated slice.
                if model_id is None:
                    self._previous_model = previous_model
                self._stamp_served_version()

    def rollout_with_rebuild(self, model, **kwargs) -> bool:
        """Rollout that survives the capacity boundary (ISSUE 19): try
        the in-place staggered rollout first (zero recompiles when the
        grown model still fits the serving tables' headroom); when the
        canary swap REFUSES for capacity (the amortized-doubling plan is
        exhausted — ``is_capacity_refusal``), fall through to a
        zero-downtime background :meth:`rebuild` at doubled capacity.
        Returns True when a rebuild was needed, False when the plain
        rollout sufficed."""
        try:
            self.rollout(model, **kwargs)
            return False
        except BaseException as e:
            if not is_capacity_refusal(e):
                raise
        self.rebuild(
            model=model,
            probe_requests=kwargs.get("probe_requests"),
            parity_tol=kwargs.get("parity_tol"),
        )
        return True

    def rebuild(self, model=None, table_capacity_factor: Optional[int] = None,
                parity_tol: Optional[float] = None,
                probe_requests: Optional[List[ScoringRequest]] = None) -> None:
        """Zero-downtime background replica rebuild (ISSUE 19 tentpole).

        For each replica: build a REPLACEMENT backend at
        ``table_capacity_factor`` (default: double the current factor)
        while the old backend keeps serving, warm it, canary the FIRST
        replacement with mirrored traffic against the host oracle, then
        atomically cut the serving path over (new submissions to the
        replacement, the old batcher drains against the old backend —
        zero shed, zero lost) and bump the router generation so any
        answer the retired backend still produces is fenced.  Replicas
        after the canary cut over without re-probing (same artifact,
        same parity surface).

        A canary parity failure retires the replacement and raises
        :class:`ReplicaRebuildError` with the fleet untouched.  A
        NON-canary replacement that fails to spawn is declared unhealthy
        (the supervisor heals it — at the new factor) rather than
        aborting a half-cut-over fleet.

        ``model=None`` rebuilds on the currently served model (a pure
        capacity grow); passing a model publishes it with the same
        version discipline as :meth:`rollout`."""
        if self.models:
            raise RuntimeError(
                "rebuild currently supports single-model fleets (a "
                "multi-model arena grows per-slice via add_model)"
            )
        if parity_tol is None:
            from photon_tpu.game.lowp import parity_tol_for

            parity_tol = parity_tol_for(self.table_dtype)
        factor = (
            int(table_capacity_factor) if table_capacity_factor
            else max(1, self._table_capacity_factor) * 2
        )
        with self._publish_lock:
            with self._model_lock:
                previous = self.model
                published = model is not None and model is not self.model
                if published:
                    self.model = model
                    self._model_version += 1
                target = self.model
            try:
                self._rebuild_replicas(
                    target, factor, float(parity_tol), probe_requests
                )
            except BaseException:
                with self._model_lock:
                    if published:
                        self.model = previous
                        # Monotonic, like rollout's abort path: the
                        # restore is itself a new published state.
                        self._model_version += 1
                raise
            self._table_capacity_factor = factor
            with self._model_lock:
                if published:
                    self._previous_model = previous
                self._stamp_served_version()
        self.telemetry.counter("serving.fleet_rebuilds").inc()

    def _rebuild_replicas(self, model, factor: int, parity_tol: float,
                          probe_requests) -> None:
        live = [r for r in self.replicas if r.alive and not r.quarantined]
        if not live:
            raise RuntimeError("rebuild aborted: every replica is dead")
        probes = self._rebuild_probes(model, probe_requests)
        canary = True
        for replica in live:
            try:
                proc, scorer = self._build_replacement(replica, model, factor)
            except BaseException as e:
                if canary:
                    raise
                # Post-canary spawn failure: don't abort a half-cut-over
                # fleet — declare and let the supervisor heal at the new
                # factor (the replica's stored factor is updated first).
                if hasattr(replica, "_table_capacity_factor"):
                    replica._table_capacity_factor = factor
                self.router.mark_unhealthy(
                    replica, "rebuild", f"replacement spawn failed: {e}"
                )
                replica.abandon_pending(
                    RuntimeError(f"replica {replica.replica_id} rebuild "
                                 f"replacement failed: {e}")
                )
                continue
            if canary:
                # Mirrored-traffic canary BEFORE the replacement takes any
                # caller traffic: probe responses never reach callers.
                try:
                    for req in probes:
                        worst = parity_worst(
                            scorer.score_batch(req),
                            host_score_request(model, req),
                        )
                        if worst > parity_tol:
                            raise ReplicaRebuildError(
                                f"replacement for {replica.replica_id} "
                                f"failed its canary parity probe (max "
                                f"|delta| {worst:.2e} > {parity_tol:g})"
                            )
                except BaseException:
                    self._retire_replacement(proc, scorer)
                    raise
                canary = False
            self._mark_rebuild(replica.replica_id, "cutover")
            if proc is not None:
                replica.cutover_to(scorer, proc=proc,
                                   table_capacity_factor=factor)
            else:
                replica.cutover_to(scorer)
            self.router.cutover(replica)

    def _rebuild_probes(self, model,
                        probe_requests) -> List[ScoringRequest]:
        """The canary's traffic sample: explicit probes, else the
        router's mirror of recent requests, else one synthetic
        known-answer probe.  Per-row-routed mirrors (model id arrays) are
        dropped — they have no single host oracle."""
        probes = (
            list(probe_requests) if probe_requests
            else self.router.recent_requests()
        )
        probes = [
            p for p in probes
            if getattr(p, "model", None) is None
            or isinstance(p.model, str)
        ]
        if not probes:
            from photon_tpu.serving.supervisor import probe_request_for

            spec = None
            for replica in self.replicas:
                spec = getattr(replica.scorer, "request_spec", None)
                if spec:
                    break
            if not spec:
                spec = request_spec_for_model(model)
            probes = [probe_request_for(model, spec)]
        return probes

    def _build_replacement(self, replica, model, factor: int):
        """``(proc_or_None, warmed scorer)`` at the new capacity factor —
        the old backend serves untouched while this builds."""
        build = getattr(replica, "build_replacement", None)
        if build is not None:  # subprocess replica: a fresh child
            return build(model, factor)
        idx = self.replicas.index(replica)
        meshes = self._replica_mesh_list
        scorer = GameScorer(
            model,
            mesh=meshes[idx] if idx < len(meshes) else None,
            request_spec=self._request_spec_cfg,
            buckets=self._buckets,
            max_batch=self._max_batch,
            min_bucket=self._min_bucket,
            telemetry=self.telemetry,
            table_capacity_factor=factor,
            table_dtype=self.table_dtype,
        ).warmup()
        return None, scorer

    def _retire_replacement(self, proc, scorer) -> None:
        disconnect = getattr(scorer, "disconnect", None)
        if disconnect is not None:
            try:
                disconnect()
            except OSError:
                pass
        if proc is not None and proc.poll() is None:
            proc.kill()
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — reap is best-effort
                pass

    def _mark_rebuild(self, replica_id: str, phase: str) -> None:
        self.telemetry.counter(
            "serving.rebuild_phase", replica=replica_id, phase=phase
        ).inc()

    def _stamp_served_version(self) -> None:
        """Thread replicas: mirror the fleet's monotonic model version onto
        each live replica (subprocess replicas carry their child artifact
        version instead).  Caller holds ``_model_lock``."""
        for replica in self.replicas:
            if hasattr(replica, "served_version") and replica.alive:
                replica.served_version = self._model_version

    def rollback_to_previous(self, expected_version=None) -> bool:
        """Fleet-wide rollback to the predecessor artifact — the
        supervisor's answer to EVERY replica failing its known-answer
        probe right after a swap (a fleet-wide regression is a model/
        artifact fault, not N replica faults; N quarantines would scrap a
        healthy fleet).

        The predecessor is a model that already served and passed its own
        canary, so it republishes WITHOUT a canary stagger: the version
        bumps (monotonic — resurrected replicas re-sync against it), every
        live replica swaps back in place (zero recompiles: same capacity
        plan), and the predecessor slot clears so one regression cannot
        ping-pong.  Returns False when there is nothing to roll back to
        (no completed rollout yet, or a rollout is mid-flight) — the
        caller falls back to per-replica declarations.  Serialized with
        ``rollout`` on ``_publish_lock`` — the swaps of two publishes must
        never interleave — and version-guarded: ``expected_version`` is the
        model version the caller's probe evidence was collected against;
        if another publish landed while this call waited for the lock, the
        evidence is STALE (the probes never saw the new model) and the
        rollback refuses instead of reverting a fresh publish."""
        with self._publish_lock:
            return self._rollback_locked(expected_version)

    def _rollback_locked(self, expected_version) -> bool:
        with self._model_lock:
            if self._previous_model is None or self._rolling:
                return False
            if (expected_version is not None
                    and self._model_version != expected_version):
                return False
            target = self._previous_model
            self._previous_model = None
            self.model = target
            self._model_version += 1
            self._stamp_served_version()
        for replica in self.replicas:
            if not replica.alive:
                continue
            try:
                replica.scorer.swap_model(target)
            except Exception as e:  # noqa: BLE001 — a replica that cannot
                # take the restored model must not keep serving the bad one.
                self.router.mark_unhealthy(
                    replica, "swap", f"rollback swap failed: {e}"
                )
        self.telemetry.counter("serving.rollout_rollbacks").inc()
        return True

    def rollout_in_progress(self) -> bool:
        """True while a staggered rollout is mid-flight — the window in
        which different replicas legitimately serve different versions,
        so the supervisor must not read a known-answer parity mismatch
        as a replica fault."""
        with self._model_lock:
            return self._rolling > 0

    def supervise(self, policy=None, logger=None, start: bool = True):
        """Attach the self-healing supervisor (health probes, canary-gated
        resurrection, flap quarantine); returns the
        :class:`~photon_tpu.serving.supervisor.ReplicaSupervisor`.  With
        ``start=False`` the supervisor is built but not threaded — tests
        drive ``check_once()`` deterministically.

        Without an explicit policy, the known-answer/rejoin parity gates
        default to the fleet's table-dtype bound (a lossy fleet probed at
        the f32 tolerance would declare every healthy replica dead)."""
        from photon_tpu.serving.supervisor import (
            ReplicaSupervisor,
            SupervisorPolicy,
        )

        if self._supervisor is not None:
            raise RuntimeError("fleet already supervised")
        if policy is None and self.table_dtype != "f32":
            from photon_tpu.game.lowp import parity_tol_for

            policy = SupervisorPolicy(
                parity_tol=parity_tol_for(self.table_dtype)
            )
        self._supervisor = ReplicaSupervisor(
            self, policy=policy, telemetry=self.telemetry, logger=logger
        )
        if start:
            self._supervisor.start()
        return self._supervisor

    # -- observability -------------------------------------------------------
    def observe(self, policy=None, slos=None, flight_dir=None,
                start: bool = True):
        """Attach the fleet observability plane (cross-process tracing,
        live metrics, SLO burn rates, flight-recorder collection); returns
        the :class:`~photon_tpu.serving.observe.FleetObserver`.  Wires the
        router's request hook and each subprocess replica's span sink;
        the supervisor and online refresh pick the observer up via
        ``fleet.observer``.  ``flight_dir`` is where collected crash dumps
        persist (pass the run's output dir to land them next to the run
        report).  ``start=False`` builds it unthreaded — tests drive
        ``poll_once()`` deterministically."""
        from photon_tpu.serving.observe import FleetObserver

        if self.observer is not None:
            raise RuntimeError("fleet already observed")
        kwargs = {} if slos is None else {"slos": slos}
        observer = FleetObserver(
            fleet=self, telemetry=self.telemetry, policy=policy,
            flight_dir=flight_dir, **kwargs,
        )
        self.router.observer = observer
        for replica in self.replicas:
            if hasattr(replica, "span_sink"):
                replica.span_sink = observer.collector.merge_remote
        if getattr(observer.policy, "admission_guard", False):
            observer.attach_admission_guard(self.router)
        self.observer = observer
        if start:
            observer.start()
        return observer

    # -- transport -----------------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Attach the socket ingest; returns the
        :class:`~photon_tpu.serving.transport.ScoringServer` (its
        ``.address`` is the bound ``(host, port)``)."""
        from photon_tpu.serving.transport import ScoringServer

        if self._server is not None:
            raise RuntimeError("fleet already serving")
        self._server = ScoringServer(
            self.router, host=host, port=port, telemetry=self.telemetry
        )
        return self._server

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        # The supervisor stops FIRST: a teardown must not race a
        # resurrection re-spawning the replicas being closed.
        if self._supervisor is not None:
            self._supervisor.stop()
            self._supervisor = None
        # Observer closes while the children are still alive so its final
        # poll can drain pending span streams over the open control
        # connections; after router.close() those sockets are gone.
        if self.observer is not None:
            self.observer.close()
        if self._server is not None:
            self._server.close()
            self._server = None
        self.router.close()
        if self._workdir_owned and self._store is not None:
            import shutil

            shutil.rmtree(self._store.workdir, ignore_errors=True)
            self._store = None

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
