"""Async request batcher: coalesce concurrent scoring requests on a thread.

The serving analog of PR 5's depth-1 ``AsyncPublisher``: ONE dedicated
batcher thread sits between callers and the :class:`GameScorer`.  Callers
``submit()`` a request and get a ``concurrent.futures.Future``; the thread
coalesces whatever is queued under a max-delay/max-batch policy — the first
queued request opens a window of ``max_delay_s``, and the batch closes when
the window expires or ``max_batch`` rows have accumulated, whichever is
first — merges the requests into one micro-batch, scores it (one compiled
dispatch, one host sync), and resolves every future with its own row slice.

Coalescing is what buys the device's throughput back from small requests:
at 1-row requests and an 8-wide bucket the dispatch cost is amortized 8x
before padding even enters.  ``serving.requests`` / ``serving.batches``
count both sides of that ratio; ``serving.request_latency_s`` is the
submit→resolve distribution (the p50/p99 the bench reports), and
``serving.coalesced`` the requests-per-batch distribution.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import List, Optional

from photon_tpu.serving.scorer import (
    GameScorer,
    ScoringRequest,
    concat_requests,
    padded_cost,
)
from photon_tpu.telemetry.distributed import attach_trace, span_of

DEFAULT_MAX_DELAY_S = 0.002


def resolve_once(future: Future, value=None,
                 exc: Optional[BaseException] = None) -> None:
    """Resolve a pending future exactly once — the shared guard for every
    path where two resolvers can race the same future: a future abandoned
    by the supervisor (a hung replica torn down mid-batch) may already
    carry its ReplicaDeadError when the wedged batcher thread finally
    comes back, and an async transport future can be failed by the
    submit-side send error, the reader's decode, and the dead-connection
    sweep.  The loser's write must be a no-op, not an InvalidStateError
    that kills the resolving thread."""
    try:
        if future.cancelled():
            return
        if exc is not None:
            future.set_exception(exc)
        else:
            future.set_result(value)
    except InvalidStateError:
        pass


_resolve = resolve_once  # internal alias for the call sites below


class _Pending:
    __slots__ = ("request", "future", "enqueued", "rows", "padded")

    def __init__(self, request: ScoringRequest, padded: int):
        self.request = request
        self.future: Future = Future()
        self.enqueued = time.monotonic()
        self.rows = request.num_rows
        self.padded = padded


class RequestBatcher:
    """Depth-1 batcher thread over a :class:`GameScorer`.

    Context-manager lifecycle: ``with RequestBatcher(scorer) as b: ...``
    drains the queue and stops the thread on exit.  A scorer failure is
    delivered through the affected futures, never swallowed; submits after
    ``close()`` raise.
    """

    def __init__(
        self,
        scorer: GameScorer,
        max_batch: Optional[int] = None,
        max_delay_s: float = DEFAULT_MAX_DELAY_S,
        telemetry=None,
    ):
        from photon_tpu.telemetry import NULL_SESSION

        self.scorer = scorer
        self.max_batch = int(max_batch or scorer.max_bucket)
        self.max_delay_s = float(max_delay_s)
        self.telemetry = telemetry or scorer.telemetry or NULL_SESSION
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._stop = False
        # Rows accepted but not yet resolved (queued + in the batch being
        # scored): the queue-depth signal the fleet router dispatches and
        # sheds on.  Kept under the SAME lock as the queue so a router
        # reading depth mid-submit can never see a torn count.  The padded
        # twin charges each request at its bucket-ladder cost — the unit
        # the admission projection estimates wait in.
        self._inflight_rows = 0
        self._inflight_padded = 0
        self._current: List[_Pending] = []
        self._thread = threading.Thread(
            target=self._loop, name="serving-batcher", daemon=True
        )
        self._thread.start()

    def _padded_cost(self, n: int) -> int:
        try:
            return padded_cost(n, self.scorer.buckets)
        except Exception:  # a scorer stub without a ladder: raw rows
            return int(n)

    # -- caller side ---------------------------------------------------------
    def submit(self, request: ScoringRequest) -> Future:
        """Enqueue one request; the returned future resolves to its ``[n]``
        float32 scores (or raises the scorer's failure)."""
        pending = _Pending(request, self._padded_cost(request.num_rows))
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher is closed")
            self._queue.append(pending)
            self._inflight_rows += pending.rows
            self._inflight_padded += pending.padded
            self._cond.notify()
        self.telemetry.counter("serving.requests").inc()
        return pending.future

    def pending_rows(self) -> int:
        """Rows submitted but not yet resolved (queued + scoring) — the
        per-replica queue depth the fleet router's admission projection and
        least-loaded dispatch read."""
        with self._cond:
            return self._inflight_rows

    def pending_padded_rows(self) -> int:
        """Pending work at its PADDED bucket-ladder cost — the unit the
        admission projection multiplies by the per-row pace EWMA (padded
        rows cost compute too; raw rows under-project near saturation)."""
        with self._cond:
            return self._inflight_padded

    def close(self) -> None:
        """Drain queued requests (they still get scored) and stop.  The
        join is bounded: a batcher whose scorer is wedged (a hung replica
        being torn down) must not wedge close() too."""
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout=10.0)

    def abandon(self, exc: BaseException) -> None:
        """Fail every pending request — queued AND the batch being scored —
        with ``exc`` and stop accepting work: the dead/hung-replica
        teardown.  The router's done-callbacks then reroute each failed
        future exactly once.  Unlike :meth:`close`, abandon never joins the
        batcher thread (it may be wedged inside the hung scorer call); the
        thread is daemonic and its late resolutions are guarded no-ops."""
        with self._cond:
            self._stop = True
            victims = list(self._current) + list(self._queue)
            self._queue.clear()
            self._inflight_rows = 0
            self._inflight_padded = 0
            self._cond.notify()
        for p in victims:
            _resolve(p.future, exc=exc)

    def __enter__(self) -> "RequestBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- batcher thread ------------------------------------------------------
    def _take_batch(self) -> List[_Pending]:
        """Block for the first request, hold the window open until
        max-delay/max-batch closes it, then pop the batch."""
        with self._cond:
            while not self._queue and not self._stop:
                self._cond.wait()
            if not self._queue:
                return []
            deadline = self._queue[0].enqueued + self.max_delay_s
            while not self._stop:
                queued = sum(p.rows for p in self._queue)
                remaining = deadline - time.monotonic()
                if queued >= self.max_batch or remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch: List[_Pending] = []
            rows = 0
            # Whole requests only: a request larger than max_batch goes out
            # alone (the scorer chunks it); otherwise stop before the batch
            # would spill past max_batch.
            while self._queue:
                head = self._queue[0]
                if batch and rows + head.rows > self.max_batch:
                    break
                batch.append(self._queue.popleft())
                rows += head.rows
            self._current = batch
            return batch

    def _retire(self, batch: List[_Pending]) -> None:
        with self._cond:
            # max(0, …): an abandon() already zeroed the counts (and failed
            # these futures); the late retire must not drive them negative.
            self._inflight_rows = max(
                0, self._inflight_rows - sum(p.rows for p in batch)
            )
            self._inflight_padded = max(
                0, self._inflight_padded - sum(p.padded for p in batch)
            )
            self._current = []

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                return
            # Traced requests: stamp the coalesce window onto each root
            # span, and let the merged micro-batch carry the first traced
            # request's context so a subprocess scorer links its child hop
            # (the batch IS one device dispatch — one representative trace
            # is the honest granularity).
            spans = [sp for sp in (span_of(p.request) for p in batch) if sp]
            batch_rows = sum(p.rows for p in batch)
            for sp in spans:
                sp.event("batch_close", coalesced=len(batch),
                         batch_rows=batch_rows)
            try:
                merged = concat_requests([p.request for p in batch])
                if spans:
                    attach_trace(merged, spans[0].context())
                    for sp in spans:
                        sp.event("score_begin")
                scores = self.scorer.score_batch(merged)
                for sp in spans:
                    sp.event("score_end")
            except BaseException as e:  # surface through every waiter
                self._retire(batch)
                for p in batch:
                    _resolve(p.future, exc=e)
                continue
            self.telemetry.histogram("serving.coalesced").observe(len(batch))
            self._retire(batch)
            lo = 0
            now = time.monotonic()
            for p in batch:
                hi = lo + p.rows
                self.telemetry.histogram("serving.request_latency_s").observe(
                    now - p.enqueued
                )
                _resolve(p.future, scores[lo:hi])
                lo = hi


def run_closed_loop(
    batcher: RequestBatcher,
    requests: List[ScoringRequest],
    clients: int = 4,
):
    """Drive a request list through the batcher with ``clients`` closed-loop
    workers (each submits its next request only after its previous response
    lands — the concurrent-users arrival model the bench and the serve_game
    driver share).  Returns ``(scores, latencies_s, wall_s)`` with scores
    in request order."""
    results: List = [None] * len(requests)
    latencies: List = [None] * len(requests)
    errors: List[BaseException] = []

    def worker(tid: int) -> None:
        for i in range(tid, len(requests), clients):
            t0 = time.monotonic()
            try:
                results[i] = batcher.submit(requests[i]).result()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
                return
            latencies[i] = time.monotonic() - t0

    clients = max(1, min(int(clients), len(requests) or 1))
    threads = [
        threading.Thread(target=worker, args=(t,), daemon=True)
        for t in range(clients)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    if errors:
        raise errors[0]
    return results, latencies, wall
