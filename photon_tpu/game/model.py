"""GAME models: fixed-effect, random-effect, and the composite GameModel.

Rebuild of the reference's photon-api model layer (SURVEY.md §2.2 'GAME
models'): ``FixedEffectModel`` (broadcast coefficients + feature-shard id),
``RandomEffectModel`` (an ``RDD[(entityId, GeneralizedLinearModel)]``), and
``GameModel`` (ordered per-coordinate container), plus the scoring join
(``ModelDataScores`` accumulation — SURVEY.md §3.3).

TPU-native shape: a random-effect model is a dense coefficient **table**
``[num_entities, dim]`` resident in device memory — the per-entity model RDD
collapses into one array, and the scoring-time shuffle-join becomes a gather
by entity index.  Per-coordinate scores are raw margins (no offset, no link);
the dataset offset is added once when combining, exactly like the
reference's ``CoordinateDataScores -> ModelDataScores`` accumulation.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.core.losses import get_loss
from photon_tpu.data.batch import DenseBatch, SparseBatch
from photon_tpu.game.data import DenseShard, GameDataset, Shard, SparseShard
from photon_tpu.parallel.mesh import to_host
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel, model_for_task

Array = jax.Array


def shard_to_batch(
    shard: Shard,
    label: np.ndarray,
    offset: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
):
    """Device batch for one feature shard of a GameDataset."""
    n = len(label)
    label = jnp.asarray(label, jnp.float32)
    offset = (
        jnp.zeros(n, jnp.float32) if offset is None else jnp.asarray(offset, jnp.float32)
    )
    weight = (
        jnp.ones(n, jnp.float32) if weight is None else jnp.asarray(weight, jnp.float32)
    )
    if isinstance(shard, DenseShard):
        return DenseBatch(jnp.asarray(shard.x), label, offset, weight)
    return SparseBatch(
        jnp.asarray(shard.ids), jnp.asarray(shard.vals), label, offset, weight
    )


@partial(jax.jit, static_argnames=("dense",))
def _fixed_margins(w: Array, feats, dense: bool) -> Array:
    if dense:
        return feats @ w
    ids, vals = feats
    return jnp.sum(jnp.take(w, ids, axis=0) * vals, axis=-1)


@partial(jax.jit, static_argnames=("dense",))
def _random_margins(table: Array, entity_idx: Array, feats, dense: bool) -> Array:
    """Margins via gather of per-row entity coefficients; unseen entities -> 0."""
    safe = jnp.maximum(entity_idx, 0)
    if dense:
        m = jnp.einsum("nd,nd->n", feats, table[safe])
    else:
        ids, vals = feats
        # table[entity, feature] gathered per nonzero: [n, k].
        m = jnp.sum(table[safe[:, None], ids] * vals, axis=-1)
    return jnp.where(entity_idx >= 0, m, 0.0)


def _shard_feats(shard: Shard):
    if isinstance(shard, DenseShard):
        return jnp.asarray(shard.x), True
    return (jnp.asarray(shard.ids), jnp.asarray(shard.vals)), False


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global GLM on one feature shard (reference: FixedEffectModel)."""

    model: GeneralizedLinearModel
    shard_name: str

    @property
    def coefficients(self) -> Coefficients:
        return self.model.coefficients

    def score(self, data: GameDataset) -> np.ndarray:
        """Raw margins ``w . x_i`` over the dataset's shard (no offset)."""
        feats, dense = _shard_feats(data.shard(self.shard_name))
        return to_host(_fixed_margins(self.coefficients.means, feats, dense))

    def margins_device(self, feats, dense: bool) -> Array:
        """Device-resident margins against pre-uploaded shard features —
        the residual engine's scoring path (no host round-trip)."""
        return _fixed_margins(jnp.asarray(self.coefficients.means), feats, dense)


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity coefficient table for one random-effect coordinate.

    ``table[i]`` is entity ``keys[i]``'s coefficient vector; entities never
    seen in training keep (implicit) zero coefficients and contribute zero
    score — matching the reference's left-outer scoring join.
    """

    table: Array  # [num_entities, dim]
    keys: np.ndarray  # sorted entity vocabulary
    entity_column: str
    shard_name: str
    task_type: str
    variances: Optional[Array] = None  # [num_entities, dim]

    @property
    def num_entities(self) -> int:
        return len(self.keys)

    @property
    def dim(self) -> int:
        return self.table.shape[1]

    def model_for_entity(self, key) -> Optional[GeneralizedLinearModel]:
        """Single-entity view (the reference's per-entity GLM objects)."""
        idx = np.searchsorted(self.keys, key)
        if idx >= len(self.keys) or self.keys[idx] != key:
            return None
        variances = None if self.variances is None else self.variances[idx]
        return model_for_task(self.task_type, Coefficients(self.table[idx], variances))

    def score(self, data: GameDataset) -> np.ndarray:
        from photon_tpu.game.data import entity_index_for

        entity_idx = entity_index_for(data.id_columns[self.entity_column], self.keys)
        feats, dense = _shard_feats(data.shard(self.shard_name))
        return to_host(
            _random_margins(self.table, jnp.asarray(entity_idx), feats, dense)
        )

    def margins_device(self, entity_idx: Array, feats, dense: bool) -> Array:
        """Device-resident margins against pre-uploaded shard features and a
        pre-computed per-row entity index — the residual engine's scoring
        path (the gather-join with no host round-trip)."""
        return _random_margins(jnp.asarray(self.table), entity_idx, feats, dense)


CoordinateModel = "FixedEffectModel | RandomEffectModel"


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Ordered per-coordinate model container (reference: GameModel).

    ``task_type`` fixes the link for prediction; coordinate order is the
    score-accumulation order (it does not affect the sum).
    """

    coordinates: Dict[str, object]  # name -> FixedEffectModel | RandomEffectModel
    task_type: str

    def coordinate(self, name: str):
        return self.coordinates[name]

    def score(self, data: GameDataset) -> np.ndarray:
        """Total raw score: dataset offset + sum of coordinate margins
        (reference: ModelDataScores accumulation, SURVEY.md §3.3)."""
        total = data.offset.astype(np.float64).copy()
        for model in self.coordinates.values():
            total += np.asarray(model.score(data), np.float64)
        return total.astype(np.float32)

    def predict(self, data: GameDataset) -> np.ndarray:
        """Apply the task's mean/inverse-link to the total score (e.g.
        sigmoid for logistic — SURVEY.md §3.3 'sigmoid for logistic')."""
        # get_loss resolves task-type names directly (core/losses.TASK_TO_LOSS).
        loss = get_loss(self.task_type)
        return np.asarray(loss.mean(jnp.asarray(self.score(data))))
