"""GAME models: fixed-effect, random-effect, and the composite GameModel.

Rebuild of the reference's photon-api model layer (SURVEY.md §2.2 'GAME
models'): ``FixedEffectModel`` (broadcast coefficients + feature-shard id),
``RandomEffectModel`` (an ``RDD[(entityId, GeneralizedLinearModel)]``), and
``GameModel`` (ordered per-coordinate container), plus the scoring join
(``ModelDataScores`` accumulation — SURVEY.md §3.3).

TPU-native shape: a random-effect model is a dense coefficient **table**
``[num_entities, dim]`` resident in device memory — the per-entity model RDD
collapses into one array, and the scoring-time shuffle-join becomes a gather
by entity index.  Per-coordinate scores are raw margins (no offset, no link);
the dataset offset is added once when combining, exactly like the
reference's ``CoordinateDataScores -> ModelDataScores`` accumulation.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.core.losses import get_loss
from photon_tpu.data.batch import DenseBatch, SparseBatch
from photon_tpu.game.data import (
    DenseShard,
    GameDataset,
    Shard,
    SparseShard,
    keys_match,
)
from photon_tpu.parallel.mesh import to_host
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel, model_for_task
from photon_tpu.utils import pow2_at_least

Array = jax.Array


def shard_to_batch(
    shard: Shard,
    label: np.ndarray,
    offset: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
):
    """Device batch for one feature shard of a GameDataset."""
    n = len(label)
    label = jnp.asarray(label, jnp.float32)
    offset = (
        jnp.zeros(n, jnp.float32) if offset is None else jnp.asarray(offset, jnp.float32)
    )
    weight = (
        jnp.ones(n, jnp.float32) if weight is None else jnp.asarray(weight, jnp.float32)
    )
    if isinstance(shard, DenseShard):
        return DenseBatch(jnp.asarray(shard.x), label, offset, weight)
    return SparseBatch(
        jnp.asarray(shard.ids), jnp.asarray(shard.vals), label, offset, weight
    )


@partial(jax.jit, static_argnames=("dense",))
def _fixed_margins(w: Array, feats, dense: bool) -> Array:
    if dense:
        return feats @ w
    ids, vals = feats
    return jnp.sum(jnp.take(w, ids, axis=0) * vals, axis=-1)


@partial(jax.jit, static_argnames=("dense",))
def serving_gather_margins(table, safe_idx: Array, feats, dense: bool) -> Array:
    """Margins via the serving gather convention: ``safe_idx`` is already
    in-table (unknown entities pre-mapped to the trailing all-zero row by the
    caller — :meth:`RandomEffectModel.serving_table`), so the gather itself
    produces the fixed-effect-only fallback with no output mask.  The online
    scoring hot path (photon_tpu.serving) runs this inside its per-bucket
    compiled programs; it is defined HERE so the serving path and the batch
    ``margins_device`` path share one model layer.

    ``table`` is the serving STORAGE form (ISSUE 17 precision tiers): an
    f32 or bf16 ``[capacity, dim]`` array, or an int8 ``(q, scale)`` tuple
    (per-row absmax scale).  The gather moves the narrow stored bytes —
    that IS the bandwidth win — and the decode runs on the gathered
    ``[n, d]`` block; every multiply-accumulate stays f32.  The storage
    form is part of the traced pytree structure, so each dtype compiles
    its own bucket program at warmup and never again."""
    if isinstance(table, tuple):
        q, scale = table
        row_scale = scale[safe_idx].astype(jnp.float32)
        if dense:
            rows = q[safe_idx].astype(jnp.float32) * row_scale[:, None]
            return jnp.einsum("nd,nd->n", feats, rows)
        ids, vals = feats
        gathered = q[safe_idx[:, None], ids].astype(jnp.float32)
        return jnp.sum(gathered * row_scale[:, None] * vals, axis=-1)
    if dense:
        return jnp.einsum(
            "nd,nd->n", feats, table[safe_idx].astype(jnp.float32)
        )
    ids, vals = feats
    return jnp.sum(
        table[safe_idx[:, None], ids].astype(jnp.float32) * vals, axis=-1
    )


@partial(jax.jit, static_argnames=("dense",))
def _random_margins(table: Array, entity_idx: Array, feats, dense: bool) -> Array:
    """Margins via gather of per-row entity coefficients; unseen entities -> 0."""
    safe = jnp.maximum(entity_idx, 0)
    if dense:
        m = jnp.einsum("nd,nd->n", feats, table[safe])
    else:
        ids, vals = feats
        # table[entity, feature] gathered per nonzero: [n, k].
        m = jnp.sum(table[safe[:, None], ids] * vals, axis=-1)
    return jnp.where(entity_idx >= 0, m, 0.0)


def _shard_feats(shard: Shard):
    if isinstance(shard, DenseShard):
        return jnp.asarray(shard.x), True
    return (jnp.asarray(shard.ids), jnp.asarray(shard.vals)), False


def _shard_feats_padded(shard: Shard, n_pad: int):
    """Host-side feature leaves padded to ``n_pad`` rows (zero rows on the
    padding — they produce zero margins and carry weight 0 everywhere), in
    upload-ready numpy form: ``(leaves, dense)`` like :func:`_shard_feats`.
    """
    if isinstance(shard, DenseShard):
        x = shard.x
        if n_pad != x.shape[0]:
            x = np.pad(x, [(0, n_pad - x.shape[0]), (0, 0)])
        return x, True
    ids, vals = shard.ids, shard.vals
    if n_pad != ids.shape[0]:
        widths = [(0, n_pad - ids.shape[0]), (0, 0)]
        ids, vals = np.pad(ids, widths), np.pad(vals, widths)
    return (ids, vals), False


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global GLM on one feature shard (reference: FixedEffectModel)."""

    model: GeneralizedLinearModel
    shard_name: str

    @property
    def coefficients(self) -> Coefficients:
        return self.model.coefficients

    def score(self, data: GameDataset) -> np.ndarray:
        """Raw margins ``w . x_i`` over the dataset's shard (no offset)."""
        feats, dense = _shard_feats(data.shard(self.shard_name))
        return to_host(_fixed_margins(self.coefficients.means, feats, dense))

    def margins_device(self, feats, dense: bool) -> Array:
        """Device-resident margins against pre-uploaded shard features —
        the residual engine's scoring path (no host round-trip)."""
        return _fixed_margins(jnp.asarray(self.coefficients.means), feats, dense)

    def serving_weights(self, mesh=None) -> Array:
        """Device-resident coefficient vector for the online scoring
        service: placed once (replicated — every shard reads the whole
        vector) and then closed over by every pre-compiled bucket program,
        so serving requests never re-upload model state."""
        from photon_tpu.parallel.mesh import put_replicated

        return put_replicated(
            jnp.asarray(self.coefficients.means, jnp.float32), mesh
        )


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity coefficient table for one random-effect coordinate.

    ``table[i]`` is entity ``keys[i]``'s coefficient vector; entities never
    seen in training keep (implicit) zero coefficients and contribute zero
    score — matching the reference's left-outer scoring join.
    """

    table: Array  # [num_entities, dim]
    keys: np.ndarray  # sorted entity vocabulary
    entity_column: str
    shard_name: str
    task_type: str
    variances: Optional[Array] = None  # [num_entities, dim]

    @property
    def num_entities(self) -> int:
        return len(self.keys)

    @property
    def dim(self) -> int:
        return self.table.shape[1]

    def model_for_entity(self, key) -> Optional[GeneralizedLinearModel]:
        """Single-entity view (the reference's per-entity GLM objects)."""
        idx = np.searchsorted(self.keys, key)
        if idx >= len(self.keys) or self.keys[idx] != key:
            return None
        variances = None if self.variances is None else self.variances[idx]
        return model_for_task(self.task_type, Coefficients(self.table[idx], variances))

    def with_entities(self, keys: np.ndarray) -> "RandomEffectModel":
        """Grow this model to a larger entity vocabulary ON DEVICE — the
        incremental-onboarding warm start.

        ``keys`` is the merged (superset) vocabulary — typically the
        onboarded device data's ``dataset.keys``.  Existing entities keep
        their rows, scattered to their new sorted positions with one device
        scatter (no host table rebuild); new entities start at zero
        coefficients (they have never been fit), exactly what a cold start
        would give them.  Variances scatter alongside when present."""
        merged = np.asarray(keys)
        from photon_tpu.game.data import entity_index_for

        idx = entity_index_for(self.keys, merged)
        if (idx < 0).any():
            raise ValueError(
                "with_entities() grows the vocabulary: every existing "
                "entity key must appear in the merged keys"
            )
        idx_dev = jnp.asarray(idx)

        def scatter(table):
            grown = jnp.zeros((len(merged), self.dim), jnp.float32)
            return grown.at[idx_dev].set(jnp.asarray(table, jnp.float32))

        return dataclasses.replace(
            self,
            table=scatter(self.table),
            keys=merged,
            variances=(
                None if self.variances is None else scatter(self.variances)
            ),
        )

    def score(self, data: GameDataset) -> np.ndarray:
        from photon_tpu.game.data import entity_index_for

        entity_idx = entity_index_for(data.id_columns[self.entity_column], self.keys)
        feats, dense = _shard_feats(data.shard(self.shard_name))
        return to_host(
            _random_margins(self.table, jnp.asarray(entity_idx), feats, dense)
        )

    def margins_device(self, entity_idx: Array, feats, dense: bool) -> Array:
        """Device-resident margins against pre-uploaded shard features and a
        pre-computed per-row entity index — the residual engine's scoring
        path (the gather-join with no host round-trip)."""
        return _random_margins(jnp.asarray(self.table), entity_idx, feats, dense)

    @property
    def serving_capacity(self) -> int:
        """Default row capacity of this coordinate's serving gather table:
        the next power of two past ``num_entities + 1`` (entities + the
        zero row).  Amortized doubling — the headroom is what lets a GROWN
        vocabulary hot-swap into a live scorer in place: as long as the new
        ``num_entities + 1`` still fits the capacity, the table SHAPE (and
        with it every compiled bucket program) is unchanged and only the
        movable zero-row index advances."""
        return pow2_at_least(self.num_entities + 1)

    def serving_table(self, mesh=None, capacity: Optional[int] = None,
                      dtype: Optional[str] = None):
        """Flatten this coordinate's per-entity rows into ONE device-resident
        gather table for the online scoring service: ``[capacity, dim]``
        (default :attr:`serving_capacity` — amortized-doubling headroom),
        rows ``num_entities`` … ``capacity - 1`` all-zero, sharded over the
        mesh rows.

        Unknown entities (entity index -1) are pre-mapped by the scorer to
        the movable zero row at index ``num_entities``, so the serving
        gather yields exactly zero margin — the fixed-effect-only fallback
        — without a per-row output mask (photon_tpu.serving counts them as
        ``serving.cold_entities``).  Rows past ``num_entities`` — the
        capacity headroom AND whatever reshard_to_mesh's padding adds — are
        zero by construction, so any index into the tail stays harmless.

        ``capacity`` pins the table shape explicitly: a live scorer
        hot-swapping a grown model passes its SERVED capacity so the new
        table keeps the compiled programs' shape.  A vocabulary that no
        longer fits is a layout-shape change and is refused loudly — that
        rebuild boundary is the amortized-doubling contract.

        ``dtype`` picks the STORAGE precision tier (ISSUE 17):

        - ``"f32"`` (default) — today's exact table;
        - ``"bf16"`` — the same shape at half the bytes;
        - ``"int8"`` — an ``(q int8 [capacity, dim], scale f32 [capacity])``
          tuple: symmetric per-row absmax quantization, ~4x fewer gather
          bytes.  Headroom/zero rows have absmax 0, so their stored scale
          is 0 and the decoded margin is EXACTLY zero — the cold-entity
          fallback survives quantization bit-for-bit.

        All three forms feed :func:`serving_gather_margins`, which decodes
        on device after the gather and accumulates in f32."""
        from photon_tpu.game.lowp import check_dtype
        from photon_tpu.parallel.mesh import reshard_to_mesh

        dtype = check_dtype(dtype)
        rows = self.num_entities + 1
        capacity = self.serving_capacity if capacity is None else int(capacity)
        if rows > capacity:
            raise ValueError(
                f"serving_table: vocabulary ({self.num_entities} entities "
                f"+ zero row) exceeds the table capacity {capacity}; "
                "capacity growth is a layout-shape change — rebuild the "
                "scorer instead of hot-swapping"
            )
        table = jnp.concatenate(
            [
                jnp.asarray(self.table, jnp.float32),
                jnp.zeros((capacity - self.num_entities, self.dim),
                          jnp.float32),
            ]
        )
        if dtype == "bf16":
            return reshard_to_mesh(table.astype(jnp.bfloat16), mesh)
        if dtype == "int8":
            absmax = jnp.max(jnp.abs(table), axis=-1)
            scale = (absmax / 127.0).astype(jnp.float32)
            divisor = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
            q = jnp.clip(
                jnp.round(table / divisor[:, None]), -127.0, 127.0
            ).astype(jnp.int8)
            return (reshard_to_mesh(q, mesh), reshard_to_mesh(scale, mesh))
        return reshard_to_mesh(table, mesh)


CoordinateModel = "FixedEffectModel | RandomEffectModel"


class DeviceScoringCache:
    """Device-resident scoring-side data for one (validation) GameDataset.

    Holds everything the on-device validation pipeline needs to re-score a
    coordinate and evaluate metrics without touching host memory: per-shard
    feature blocks, labels and weights, per-id-column integer entity codes
    (for the segment-reduce sharded evaluators), and per-(column,
    vocabulary) row→entity indices.  Rows are padded to a multiple of the
    mesh size (padded rows carry weight 0 and entity index -1 — invisible
    to margins and metrics) and every per-row array is SHARDED over the
    data axis: one copy of the validation data across the mesh.

    Built once per estimator and shared across sweep configurations and
    descent runs — feature uploads happen once per shard, not once per
    (configuration × iteration) as the host path's ``GameModel.score`` did.
    """

    def __init__(self, data: GameDataset, mesh=None, telemetry=None):
        from photon_tpu.parallel.mesh import mesh_shards, pad_to_multiple
        from photon_tpu.telemetry import NULL_SESSION

        self.data = data
        self.mesh = mesh
        self.telemetry = telemetry or NULL_SESSION
        self.n = data.num_examples
        self.n_pad = pad_to_multiple(self.n, mesh_shards(mesh))
        self.device_bytes = 0
        self._feats: Dict[str, tuple] = {}
        self._entity_codes: Dict[str, Array] = {}
        self._entity_idx: Dict[str, tuple] = {}
        self.label = self._put(np.asarray(data.label, np.float32))
        self.weight = self._put(np.asarray(data.weight, np.float32))

    def _put(self, host: np.ndarray, pad_value=0) -> Array:
        """Upload one per-row host array padded + sharded, with transfer and
        residency accounting.  Logical rows in, mesh-padded sharded buffer
        out (reshard_to_mesh) — the cache is rebuilt per run against the
        CURRENT mesh, which is what keeps it out of the checkpoint: a
        resumed fit on a different device count pays one fresh upload here
        instead of carrying mesh-shaped state in the snapshot."""
        from photon_tpu.parallel.mesh import reshard_to_mesh

        dev = reshard_to_mesh(host, self.mesh, pad_value=pad_value)
        self.telemetry.counter(
            "descent.host_transfer_bytes", direction="h2d", path="validation"
        ).inc(dev.nbytes)
        self.device_bytes += dev.nbytes
        return dev

    def feats(self, shard_name: str) -> tuple:
        """Shard ``shard_name``'s features as padded, sharded device leaves
        (uploaded on first use): ``(leaves, dense)``."""
        if shard_name not in self._feats:
            leaves, dense = _shard_feats_padded(
                self.data.shard(shard_name), self.n_pad
            )
            if dense:
                dev = self._put(leaves)
            else:
                dev = (self._put(leaves[0]), self._put(leaves[1]))
            self._feats[shard_name] = (dev, dense)
        return self._feats[shard_name]

    def entity_index(self, column: str, keys: np.ndarray) -> Array:
        """Per-row entity index of ``column`` against ``keys`` (``[n_pad]``
        int32, -1 = unseen/padding), cached per column for the latest
        vocabulary — identity-checked first, so the common case (a model
        trained on this run's own vocabulary, every iteration) never pays
        the O(n) host key lookup again."""
        cached = self._entity_idx.get(column)
        if cached is not None:
            ref, arr, dev = cached
            # host-sync: key compare runs only for FOREIGN vocabularies
            # (warm starts loaded from disk); same-run models hit the
            # identity check.
            if keys_match(keys, ref, arr):
                return dev
        from photon_tpu.game.data import entity_index_for

        arr = np.asarray(keys)
        idx = entity_index_for(self.data.id_columns[column], arr)
        dev = self._put(idx.astype(np.int32), pad_value=-1)
        if cached is not None:
            # The replaced index buffer is dropped: keep the residency
            # gauge honest (device_bytes tracks LIVE bytes, not uploads).
            self.device_bytes -= cached[2].nbytes
        self._entity_idx[column] = (keys, arr, dev)
        return dev

    def entity_codes(self, column: str) -> tuple:
        """``(codes, num_segments)``: dense integer codes of ``column``'s
        raw entity keys (``[n_pad]`` int32; padding rows get a fresh code so
        they form their own — all weight-0, hence skipped — segment) plus
        the static segment count, for the segment-reduce sharded
        evaluators (``evaluation.metrics.sharded_metric_device``)."""
        if column not in self._entity_codes:
            uniq, codes = np.unique(self.data.id_columns[column],
                                    return_inverse=True)
            self._entity_codes[column] = (
                self._put(codes.astype(np.int32), pad_value=len(uniq)),
                len(uniq) + 1,
            )
        return self._entity_codes[column]

    def score(self, model) -> Array:
        """Device-resident margins of one coordinate model over the cached
        (validation) rows — ``[n_pad]``, sharded, no host round-trip."""
        if isinstance(model, FixedEffectModel):
            feats, dense = self.feats(model.shard_name)
            return model.margins_device(feats, dense)
        if isinstance(model, RandomEffectModel):
            entity_idx = self.entity_index(model.entity_column, model.keys)
            feats, dense = self.feats(model.shard_name)
            return model.margins_device(entity_idx, feats, dense)
        raise TypeError(
            f"cannot device-score a {type(model).__name__}; expected "
            "FixedEffectModel or RandomEffectModel"
        )


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Ordered per-coordinate model container (reference: GameModel).

    ``task_type`` fixes the link for prediction; coordinate order is the
    score-accumulation order (it does not affect the sum).
    """

    coordinates: Dict[str, object]  # name -> FixedEffectModel | RandomEffectModel
    task_type: str

    def coordinate(self, name: str):
        return self.coordinates[name]

    def score(self, data: GameDataset) -> np.ndarray:
        """Total raw score: dataset offset + sum of coordinate margins
        (reference: ModelDataScores accumulation, SURVEY.md §3.3)."""
        total = data.offset.astype(np.float64).copy()
        for model in self.coordinates.values():
            total += np.asarray(model.score(data), np.float64)
        return total.astype(np.float32)

    def predict(self, data: GameDataset) -> np.ndarray:
        """Apply the task's mean/inverse-link to the total score (e.g.
        sigmoid for logistic — SURVEY.md §3.3 'sigmoid for logistic')."""
        # get_loss resolves task-type names directly (core/losses.TASK_TO_LOSS).
        loss = get_loss(self.task_type)
        return np.asarray(loss.mean(jnp.asarray(self.score(data))))
